#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line.

Metric (BASELINE.json): GFLOPs/chip + step-time on the matmul benchmark that
the reference intended but never ran (tf_distributed_1000Matrix.py:42-48
defines C = A@B for N=1000 but the driver loop crashes, SURVEY.md §2.9).

Reported metric: best sustained matmul TFLOP/s per chip over an N-sweep
(marginal timing, fixed overhead cancelled).  ``vs_baseline`` is the fraction
of the >=90%-of-roofline north-star target achieved, i.e.
``roofline_fraction / 0.90`` (>=1.0 means the target is met).  On hardware
with no known roofline (CPU), falls back to the N=1000 reference shape's
absolute GFLOP/s with vs_baseline = 1.0.

Outage handling: a dead TPU relay presents as either a raised
``Unavailable: backend init`` error or an indefinite hang inside
``jax.devices()`` (both observed live, round 3).  Either way this entry
still prints exactly ONE JSON line — ``{"error": "tpu_unavailable", ...}``
with a nonzero exit code — so ``BENCH_r*.json`` distinguishes "the relay is
down" from "the harness is broken" without reading tracebacks.  Backend
init runs under a watchdog (``DTF_BENCH_INIT_TIMEOUT_S``, default 600s —
first compile on the relay can legitimately take tens of seconds).  Before
any of that, a ~60s KILLABLE subprocess probe (``preflight_probe``,
``DTF_BENCH_PREFLIGHT_TIMEOUT_S``; 0 disables) catches the hang mode fast:
the watchdog thread can only flag a hang, not reclaim it, so without the
preflight a dead relay still burned the full outer timeout.
"""

import json
import os
import sys
import threading
import time

_METRIC = "matmul_tflops_per_chip"


def _failure_line(error: str, stage: str, reason: str) -> dict:
    """The one failure shape: same metric/unit keys as success, null values."""
    return {
        "error": error,
        "metric": _METRIC,
        "value": None,
        "unit": "TFLOP/s",
        "vs_baseline": None,
        "detail": {"stage": stage, "reason": reason},
    }


_emit_lock = threading.Lock()


def _emit_once(line: dict, state: dict) -> bool:
    """Print ``line`` iff nothing has been emitted yet for this run.

    The exactly-one-JSON-line contract has a genuine race: the deadline
    Timer can start firing in the same instant the sweep finishes (Timer
    .cancel() cannot stop a callback already running).  All emission —
    success, classified failure, deadline abort — goes through this latch.
    """
    with _emit_lock:
        if state.get("emitted"):
            return False
        state["emitted"] = True
        print(json.dumps(line), flush=True)
        return True


# Test seam: init_backend's probe thread class (patching the stdlib
# threading.Thread would hijack unrelated threads).
_Thread = threading.Thread

# Preflight probe body: the minimal backend init, run in a KILLABLE
# subprocess.  The daemon-thread watchdog below can only FLAG a hang (the
# thread is stuck in C++ and unreclaimable), so a dead relay still burns
# the full DTF_BENCH_INIT_TIMEOUT_S/deadline budget; a subprocess probe is
# killed after ~60s and the run fails fast instead (BENCH_r05.json: "dead
# relay hangs rather than raising").
_PREFLIGHT_SRC = """\
import os
import jax
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.devices()
"""


def _want_preflight() -> bool:
    """Probe only when the run may touch the TPU relay: JAX_PLATFORMS
    unset (this image's plugin auto-selects the TPU) or explicitly
    requesting tpu.  A cpu-only run cannot hit the relay's hang mode and
    must not pay a ~2s interpreter+jax-import tax for it."""
    req = [p.strip().lower() for p in
           os.environ.get("JAX_PLATFORMS", "").split(",") if p.strip()]
    return not req or "tpu" in req


def preflight_probe(timeout_s: float) -> "tuple[bool, str]":
    """Run backend init in a subprocess; returns ``(hung, reason)``.

    Only the HANG mode is terminal here: a probe that *raises* exits
    quickly, and the real ``init_backend`` will re-raise the same error
    under main()'s existing outage/config classifiers — the preflight
    must not duplicate that logic.  ``subprocess.run`` kills the child on
    timeout, so a wedged probe cannot outlive the verdict."""
    import subprocess
    try:
        subprocess.run([sys.executable, "-c", _PREFLIGHT_SRC],
                       timeout=timeout_s, capture_output=True)
        return False, ""
    except subprocess.TimeoutExpired:
        return True, (f"backend init probe subprocess hung past "
                      f"DTF_BENCH_PREFLIGHT_TIMEOUT_S={timeout_s:.0f}s "
                      f"(dead relay hang mode)")
    except Exception as exc:       # no interpreter/fork: not outage evidence
        return False, f"probe unavailable ({exc})"


def init_backend(timeout_s: float):
    """Initialise the jax backend under a watchdog.

    A dead relay makes ``jax.devices()`` hang forever rather than raise, so
    the probe runs in a daemon thread: on timeout we raise TimeoutError and
    the main thread can still exit cleanly.  Backend init errors (e.g.
    ``Unavailable``) propagate as-is.
    """
    result: dict = {}

    def probe() -> None:
        try:
            import jax

            # This image's sitecustomize imports the axon TPU plugin before
            # user code, so the JAX_PLATFORMS env var alone can silently
            # lose; jax.config.update after import is the reliable form.
            if os.environ.get("JAX_PLATFORMS"):
                jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
            result["devices"] = [str(d) for d in jax.devices()]
        except BaseException as exc:
            # Normalised below: anything non-Exception except operator abort
            # (e.g. a plugin calling sys.exit) must not escape main()'s
            # Exception classifiers, or no JSON line gets printed.
            result["exc"] = exc

    t = _Thread(target=probe, daemon=True, name="bench-backend-probe")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise TimeoutError(
            f"jax backend init did not complete within {timeout_s:.0f}s "
            "(dead relay hangs rather than raising)")
    if "exc" in result:
        exc = result["exc"]
        if isinstance(exc, (Exception, KeyboardInterrupt)):
            raise exc  # Exceptions are classified by main; Ctrl+C aborts
        raise RuntimeError(f"backend init raised {type(exc).__name__}: {exc}")
    return result["devices"]


def main(_init=init_backend, _preflight=preflight_probe) -> int:
    emit_state: dict = {}

    def fail(error: str, stage: str, reason: str) -> int:
        _emit_once(_failure_line(error, stage, reason), emit_state)
        return 1

    # All env knobs parse before any backend work so a typo in any of them
    # gets its own config_error line instead of a traceback or a misleading
    # stage.  DTF_BENCH_NS: comma-separated N override for smoke runs (the
    # full sweep is TPU-sized; N=8192 bf16 alone is minutes/matmul on CPU).
    # DTF_BENCH_DEADLINE_S: whole-run deadline — the relay's hang mode can
    # strike mid-sweep too, after init succeeded.
    try:
        timeout_s = float(os.environ.get("DTF_BENCH_INIT_TIMEOUT_S", "600"))
        deadline_s = float(os.environ.get("DTF_BENCH_DEADLINE_S", "1800"))
        preflight_s = float(
            os.environ.get("DTF_BENCH_PREFLIGHT_TIMEOUT_S", "60"))
        # Retry-next-window: the r03-r05 relay hangs were TRANSIENT (the
        # relay cycles), so one probe at one instant under-samples the
        # window.  On a hung probe, wait and re-probe up to RETRIES more
        # times with doubling waits starting at RETRY_WAIT_S — bounded,
        # so a genuinely dead relay still fails this run in minutes, but
        # a relay that comes back mid-window gets the round recorded
        # instead of another stalled BENCH_r*.json.
        preflight_retries = int(
            os.environ.get("DTF_BENCH_PREFLIGHT_RETRIES", "2"))
        preflight_wait_s = float(
            os.environ.get("DTF_BENCH_PREFLIGHT_RETRY_WAIT_S", "30"))
        ns = tuple(int(n) for n in
                   os.environ.get("DTF_BENCH_NS", "1000,1024,2048,4096,8192")
                   .split(","))
    except ValueError as exc:
        return fail("config_error", "config",
                    f"bad DTF_BENCH_* env var: {exc}")

    # `0 < x <= TIMEOUT_MAX` also rejects NaN and inf (Thread.join/Timer
    # raise OverflowError past TIMEOUT_MAX, which would misclassify as a
    # tpu_unavailable or kill the deadline thread).
    if not (0 < timeout_s <= threading.TIMEOUT_MAX
            and 0 < deadline_s <= threading.TIMEOUT_MAX):
        return fail("config_error", "config",
                    "DTF_BENCH_INIT_TIMEOUT_S and DTF_BENCH_DEADLINE_S must "
                    f"be in (0, {threading.TIMEOUT_MAX:.0f}], "
                    f"got {timeout_s} / {deadline_s}")
    # 0 disables the preflight (operators who know the relay is up and
    # want the 2s back); NaN/inf rejected like the other knobs.
    if not (0 <= preflight_s <= threading.TIMEOUT_MAX):
        return fail("config_error", "config",
                    "DTF_BENCH_PREFLIGHT_TIMEOUT_S must be in "
                    f"[0, {threading.TIMEOUT_MAX:.0f}], got {preflight_s}")
    if not 0 <= preflight_retries <= 100:
        return fail("config_error", "config",
                    "DTF_BENCH_PREFLIGHT_RETRIES must be in [0, 100], "
                    f"got {preflight_retries}")
    if not (0 <= preflight_wait_s <= threading.TIMEOUT_MAX):
        return fail("config_error", "config",
                    "DTF_BENCH_PREFLIGHT_RETRY_WAIT_S must be in "
                    f"[0, {threading.TIMEOUT_MAX:.0f}], "
                    f"got {preflight_wait_s}")
    if not ns or not all(n > 0 for n in ns):
        return fail("config_error", "config",
                    f"DTF_BENCH_NS values must be positive, got {ns}")

    # Fail-fast preflight: a dead relay's hang mode is caught by a
    # killable ~60s subprocess probe instead of burning the full
    # DTF_BENCH_INIT_TIMEOUT_S (600s) inside an unreclaimable daemon
    # thread.  Raise-mode failures fall through to the real init, which
    # classifies them (outage vs config vs harness) exactly as before.
    run_deadline = time.monotonic() + deadline_s
    if preflight_s > 0 and _preflight is not None and _want_preflight():
        # The whole-run deadline bounds the retry windows too: the
        # doubling waits could otherwise dwarf DTF_BENCH_DEADLINE_S
        # (retries=12 at the 30s base is a ~17h final window) with no
        # JSON line and no watchdog armed yet.  ONE budget for the whole
        # run: the watchdog below is armed with whatever the retries
        # left, so preflight + init + run never exceed deadline_s total.
        retry_deadline = run_deadline
        hung, why = _preflight(preflight_s)
        probes, waited = 1, 0.0
        while hung and probes <= preflight_retries:
            # Doubling window between probes (bounded by the retry
            # budget): a relay mid-cycle gets time to come back without
            # this run waiting forever on one that is down for the day.
            wait = preflight_wait_s * (2 ** (probes - 1))
            # Never sleep past the deadline, and stop probing once it
            # has no room left for another probe window.
            room = retry_deadline - time.monotonic() - preflight_s
            if room <= 0:
                break
            time.sleep(min(wait, room))
            waited += min(wait, room)
            hung, why = _preflight(preflight_s)
            probes += 1
        if hung:
            return fail(
                "tpu_unavailable", "preflight",
                f"{why} ({probes} probe(s) over ~{waited:.0f}s of "
                f"retry windows; DTF_BENCH_PREFLIGHT_RETRIES="
                f"{preflight_retries})")

    # Classify a deadline hit by where it struck: before backend init
    # succeeded it is the relay's hang mode; after, the backend provably
    # came up, so it is a run that died/stalled — not an outage.
    init_ok = threading.Event()

    def deadline_abort() -> None:
        if init_ok.is_set():
            err, where = "benchmark_error", "hang after successful backend init"
        else:
            err, where = "tpu_unavailable", "relay hang during backend init"
        line = _failure_line(
            err, "deadline",
            f"no result within DTF_BENCH_DEADLINE_S={deadline_s:.0f}s ({where})")
        if _emit_once(line, emit_state):  # a finished run wins the race
            os._exit(1)

    # Armed with what the preflight retries left of the budget (>= 1s so
    # a last-instant recovery still gets a beat to emit its JSON line),
    # so a run that burned most of deadline_s waiting on the relay can't
    # hold the slot for another full deadline_s.
    deadline = threading.Timer(
        max(1.0, run_deadline - time.monotonic()), deadline_abort)
    deadline.daemon = True
    deadline.start()
    try:
        try:
            devices = _init(timeout_s)
        except ImportError as exc:
            # A venv where jax itself fails to import is a harness bug, not
            # an outage; keep the two distinguishable as the docstring
            # promises.
            return fail("harness_error", "backend_init",
                        f"{type(exc).__name__}: {exc}")
        except Exception as exc:
            msg = str(exc).lower()
            # A JAX_PLATFORMS typo surfaces here as jax's "unknown
            # backend/platform" error.  Platform names are an open PJRT
            # registry (no allowlist possible), but the CORE names are
            # fixed: if the operator asked only for core platforms and one
            # is missing, that is a plugin/relay failure (outage), not a
            # typo — only an unrecognized name classifies as config_error.
            core = {"cpu", "tpu", "gpu", "cuda", "rocm"}
            req = [p.strip().lower() for p in
                   os.environ.get("JAX_PLATFORMS", "").split(",")
                   if p.strip()]
            if (req and not all(p in core for p in req)
                    and "unknown" in msg
                    and ("backend" in msg or "platform" in msg)):
                return fail("config_error", "backend_init",
                            f"bad JAX_PLATFORMS="
                            f"{os.environ['JAX_PLATFORMS']!r}? "
                            f"{type(exc).__name__}: {exc}")
            return fail("tpu_unavailable", "backend_init",
                        f"{type(exc).__name__}: {exc}")
        init_ok.set()

        try:
            # ANY import-time failure (ImportError or module-level code
            # dying) is a broken package, i.e. a harness bug; once sweep
            # is RUNNING, any error (even a lazy ImportError inside it)
            # means the backend came up and the run died ->
            # benchmark_error.
            from dtf_tpu.bench.matmul import sweep
        except KeyboardInterrupt:
            raise
        except BaseException as exc:
            return fail("harness_error", "sweep",
                        f"{type(exc).__name__}: {exc}")
        try:
            results = sweep(ns=ns, dtype="bfloat16")
            best = max(results, key=lambda r: r["tflops_per_chip"])
        except KeyboardInterrupt:
            raise
        except BaseException as exc:
            # BaseException: an observed plugin failure mode is calling
            # sys.exit() mid-run, which must still produce the JSON line.
            return fail("benchmark_error", "sweep",
                        f"{type(exc).__name__}: {exc}")
    finally:
        # Disarm the process-killer on EVERY exit path — main() is embedded
        # by tests; a live Timer would os._exit a pytest session 30 min in.
        deadline.cancel()

    if best["roofline_fraction"] is not None:
        detail = {
            "best_n": best["n"],
            "device": best["device_kind"],
            "n_chips": best["n_chips"],
            "roofline_fraction": round(best["roofline_fraction"], 4),
            "sweep_tflops": {str(r["n"]): round(r["tflops_per_chip"], 2)
                             for r in results},
        }
        # The reference-shape timing key is only honest when N=1000 ran
        # (a DTF_BENCH_NS smoke run may not include it).
        for r in results:
            if r["n"] == 1000:
                detail["n1000_matmul_time_us"] = round(r["matmul_time_us"], 3)
        line = {
            "metric": _METRIC,
            "value": round(best["tflops_per_chip"], 2),
            "unit": "TFLOP/s",
            "vs_baseline": round(best["roofline_fraction"] / 0.90, 4),
            "detail": detail,
        }
    else:
        line = {
            "metric": "matmul_gflops_per_chip",
            "value": round(best["tflops_per_chip"] * 1000, 2),
            "unit": "GFLOP/s",
            "vs_baseline": 1.0,
            "detail": {"best_n": best["n"], "device": best["device_kind"],
                       "n_devices": len(devices)},
        }
    # If the deadline callback won the emission race, the failure line is
    # already out; the exit code must match it.
    return 0 if _emit_once(line, emit_state) else 1


def main_check_ledger(argv) -> int:
    """``python bench.py --check-ledger [--ledger PATH] [--tol PCT]``:
    the perf-regression gate over LEDGER.jsonl (scripts/bench_ledger.py
    writes it from the BENCH_r*/MULTICHIP_r* round files).  The newest
    green run per rig must hold >= (1 - tol) x the best prior green run
    on that rig; a trailing error streak (the stalled r03-r05
    ``tpu_unavailable`` trajectory) prints loud.  No benchmark runs —
    this judges the committed history, so CI can arm it without a TPU."""
    import argparse
    p = argparse.ArgumentParser(prog="python bench.py --check-ledger")
    p.add_argument("--check-ledger", action="store_true", required=True)
    p.add_argument("--ledger", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "LEDGER.jsonl"))
    p.add_argument("--tol", type=float, default=float(
        os.environ.get("DTF_LEDGER_TOL_PCT", "10")))
    ns = p.parse_args(argv)
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from bench_ledger import check_ledger, read_ledger
    try:
        rows = read_ledger(ns.ledger)
    except (OSError, ValueError) as exc:
        print(f"ledger check: FAIL — cannot read {ns.ledger}: {exc}")
        return 1
    ok, lines = check_ledger(rows, tol_pct=ns.tol)
    for line in lines:
        print(line)
    print(f"ledger check: {'OK' if ok else 'FAIL'} "
          f"({len(rows)} row(s), tol {ns.tol:g}%)")
    return 0 if ok else 1


if __name__ == "__main__":
    if "--check-ledger" in sys.argv[1:]:
        sys.exit(main_check_ledger(sys.argv[1:]))
    sys.exit(main())
