#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line.

Metric (BASELINE.json): GFLOPs/chip + step-time on the matmul benchmark that
the reference intended but never ran (tf_distributed_1000Matrix.py:42-48
defines C = A@B for N=1000 but the driver loop crashes, SURVEY.md §2.9).

Reported metric: best sustained matmul TFLOP/s per chip over an N-sweep
(marginal timing, fixed overhead cancelled).  ``vs_baseline`` is the fraction
of the >=90%-of-roofline north-star target achieved, i.e.
``roofline_fraction / 0.90`` (>=1.0 means the target is met).  On hardware
with no known roofline (CPU), falls back to the N=1000 reference shape's
absolute GFLOP/s with vs_baseline = 1.0.
"""

import json
import sys


def main() -> None:
    from dtf_tpu.bench.matmul import sweep

    results = sweep(ns=(1000, 1024, 2048, 4096, 8192), dtype="bfloat16")
    best = max(results, key=lambda r: r["tflops_per_chip"])
    if best["roofline_fraction"] is not None:
        line = {
            "metric": "matmul_tflops_per_chip",
            "value": round(best["tflops_per_chip"], 2),
            "unit": "TFLOP/s",
            "vs_baseline": round(best["roofline_fraction"] / 0.90, 4),
            "detail": {
                "best_n": best["n"],
                "device": best["device_kind"],
                "n_chips": best["n_chips"],
                "roofline_fraction": round(best["roofline_fraction"], 4),
                "n1000_matmul_time_us": round(results[0]["matmul_time_us"], 3),
                "sweep_tflops": {str(r["n"]): round(r["tflops_per_chip"], 2)
                                 for r in results},
            },
        }
    else:
        line = {
            "metric": "matmul_gflops_per_chip",
            "value": round(best["tflops_per_chip"] * 1000, 2),
            "unit": "GFLOP/s",
            "vs_baseline": 1.0,
            "detail": {"best_n": best["n"], "device": best["device_kind"]},
        }
    print(json.dumps(line))


if __name__ == "__main__":
    sys.exit(main())
