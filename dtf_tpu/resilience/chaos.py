"""Deterministic fault injection: a seeded :class:`FaultPlan` parsed from a
compact spec string.

Chaos testing only earns its keep if a failing run is *replayable*: every
fault fires at an exact global step, exactly once (or on an exact period),
and byte-level corruption draws from a seeded rng — so ``--chaos
"nan_grad@17,sigterm@40"`` produces the same failure sequence on every
run.  Spec grammar (comma-separated)::

    nan_grad@S           poison the step-S batch's float leaves with NaN
                         (drives the train step's non-finite guard)
    loader_error@S       raise a transient ChaosLoaderError from the step-S
                         batch fetch (drives the data-path retry)
    stall@S:DURs         sleep DUR seconds before step S (drives the hang
                         watchdog; '3s' or bare '3' both parse)
    sigterm@S            deliver SIGTERM to this process before step S
                         (drives the preemption save/exit path)
    preempt@S            alias of sigterm that is ALSO valid with @every —
                         the spot-reclamation schedule: each firing is a
                         clean checkpoint + exit, and the supervisor's
                         restart resumes past it, so the semantics survive
                         refiring (e.g. 'preempt@every:12')
    ckpt_stall@S:DURms   the step-S checkpoint save stalls DUR extra
                         (slow/contended shared filesystem; '200ms' or
                         bare ms, @every:N:DUR for a persistent slow
                         store) — books as checkpoint time, so the
                         goodput gate sees it
    corrupt_ckpt@S       after the step-S checkpoint save lands, scribble
                         over its files (drives restore_robust fallback)
    corrupt_ckpt@latest  corrupt the newest checkpoint right before the
                         next restore (the restart-after-crash window)
    host_down@S:P        process P dies ABRUPTLY (SIGKILL) before step S —
                         the lost-host case (drives heartbeat detection +
                         coordinated abort, resilience/health.py)
    slow_host@S:P:DURms  from step S on, process P sleeps DUR per step —
                         a persistent straggler (drives slower-than-
                         median*factor flagging at logging sync points)
    partition@S[:P]      before step S, process P (default: every process)
                         enters a simulated network partition: beats stop,
                         observations stop; the minority side self-
                         isolates (exit 72), the majority plants the pill
                         (exit 71)
    slow_decode@S:DURms[:N]  serving: from engine ITERATION S every decode
                         iteration pays DUR extra — a decode-rate brownout
                         (contended HBM, a slow replica).  Optional :N
                         bounds the spike to N iterations; without it the
                         slowness is persistent.  '@every:K:DUR' instead
                         hits every Kth iteration once.
    client_drop@S        serving: at engine iteration S the oldest active
                         request's client "disconnects" — the engine must
                         cancel it and free its KV blocks immediately
    kv_poison@S          serving: at engine iteration S the oldest active
                         request's KV blocks are NaN-scribbled (HBM
                         corruption); the engine must detect the
                         non-finite logits, evict ONLY the victim, and
                         keep serving the rest
    replica_down@S[:P]   serving fleet: at acceptor dispatch sequence S
                         replica P (default 0) dies ABRUPTLY — SIGKILL
                         semantics: open connections sever, beats stop,
                         no drain, no goodbye.  The acceptor must detach
                         it and replay its accepted-but-unfinished
                         requests token-identically on a survivor.
                         One-shot only: a dead replica cannot die twice.
    replica_wedge@S:DURms[:P]  serving fleet: replica P (default 0)
                         stops draining its frontend mailbox for DUR —
                         the process is alive (its sockets still accept)
                         but the engine never steps and beats go stale;
                         detection must come from missed beats or the
                         response-stream timeout, not a clean conn
                         error.  '@every:K:DUR[:P]' = recurring GC-pause
                         flavor.
    conn_flake@S:P       serving fleet: at dispatch sequence S the
                         acceptor<->replica-P sockets are severed
                         mid-flight (transient network flake); in-flight
                         legs must retry/fail over and the replica stays
                         in rotation.  '@every:K:P' = flaky link.
    KIND@every:N[...]    repeating variant: fire at steps N, 2N, 3N, ...
                         instead of once (nan_grad/loader_error/stall
                         only), e.g. 'stall@every:50:1s'
    seed=N               seed for corruption bytes (default 0)

Serving kinds (``slow_decode``/``client_drop``/``kv_poison``) are keyed
on the ENGINE ITERATION, not the optimizer step — the serving engine
calls their ``maybe_*`` hooks from its iteration loop.  Fleet kinds
(``replica_down``/``replica_wedge``/``conn_flake``) are keyed on the
ACCEPTOR'S DISPATCH SEQUENCE (accepted-request count) and their ``:P``
names the TARGET REPLICA, not a host to fire on — the acceptor process
owns the plan and performs the side effect on replica P, so the
host-match filter does not apply to them.

One-shot faults fire once; ``@every`` faults fire on every multiple of
their period.  A plan is shared state: an in-process supervisor must pass
ONE plan through all restart attempts (``Trainer(..., chaos=plan)``),
otherwise step-keyed faults re-fire when the resumed run replays their
step.  Host-targeted faults (``host_down``/``slow_host``/``partition``
with P) parse identically on every process and fire only where
``process_index`` matches — ONE spec string describes the whole cluster's
failure schedule.  The trainer owns the injection points; this module only
decides *when* and performs the host-side side effects.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import signal
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

log = logging.getLogger("dtf_tpu")

_KINDS = ("nan_grad", "loader_error", "stall", "sigterm", "preempt",
          "ckpt_stall", "corrupt_ckpt", "host_down", "slow_host",
          "partition", "slow_decode", "client_drop", "kv_poison",
          "replica_down", "replica_wedge", "conn_flake")
# Fleet kinds: ``process`` is the TARGET replica index (the acceptor
# fires the side effect FOR it), not a host filter — _take must not
# compare it against this process's own index.
_FLEET_KINDS = ("replica_down", "replica_wedge", "conn_flake")
# Kinds whose semantics survive refiring (a host_down process is gone;
# corruption of the same step proves nothing twice).  preempt refires
# safely BECAUSE each firing ends in a clean checkpoint + supervisor
# restart that resumes past it; plain sigterm stays one-shot as the
# single-preemption scenario's spelling.  Serving: a periodic
# slow_decode is a recurring latency hiccup, a periodic client_drop is
# flappy clients — both meaningful on every firing; kv_poison stays
# one-shot (corrupting the same pool twice proves nothing twice).
# Fleet: a periodic replica_wedge is a recurring GC pause and a periodic
# conn_flake is a flaky link — both survive refiring; replica_down is
# one-shot for the same reason host_down is (a dead replica is gone, and
# refiring would silently no-op against an already-detached target).
_PERIODIC_OK = ("nan_grad", "loader_error", "stall", "preempt",
                "ckpt_stall", "slow_decode", "client_drop",
                "replica_wedge", "conn_flake")

_DUR_RE = re.compile(r"^([0-9]+(?:\.[0-9]+)?)(ms|s)?$")


def _parse_duration(text: str, default_unit: str, what: str) -> float:
    """'3s' / '250ms' / bare number (default_unit) -> seconds."""
    m = _DUR_RE.match(text)
    if not m:
        raise ValueError(f"bad duration {text!r} in {what!r} "
                         f"(expected e.g. '3s' or '250ms')")
    scale = {"s": 1.0, "ms": 1e-3}[m.group(2) or default_unit]
    return float(m.group(1)) * scale


class ChaosLoaderError(OSError):
    """Injected transient data-loader failure (an OSError so the data
    path's normal ``retry_on=(OSError,)`` policy handles it — the test
    exercises the real retry code, not a chaos-only branch)."""


@dataclasses.dataclass
class Fault:
    kind: str
    step: Optional[int]          # None for corrupt_ckpt@latest / periodic
    duration_s: float = 0.0      # stall / slow_host / slow_decode
    process: Optional[int] = None  # host-targeted kinds; None = every host
    period: Optional[int] = None   # @every:N repeating faults
    count: Optional[int] = None    # slow_decode spike width (iterations)
    fired: bool = False
    # Periodic latch: a repeating fault fires ONCE per matching step —
    # without it, loader_error@every:N would re-raise on every attempt of
    # the data path's retry loop at step N and turn a transient-error
    # simulation into a guaranteed crash.
    last_fired_step: Optional[int] = None

    def __str__(self) -> str:
        if self.period is not None:
            at = f"every:{self.period}"
        else:
            at = "latest" if self.step is None else str(self.step)
        extra = ""
        if self.kind == "stall":
            extra = f":{self.duration_s:g}s"
        elif self.kind == "ckpt_stall":
            extra = f":{self.duration_s * 1e3:g}ms"
        elif self.kind == "host_down":
            extra = f":{self.process}"
        elif self.kind == "slow_host":
            extra = f":{self.process}:{self.duration_s * 1e3:g}ms"
        elif self.kind == "partition" and self.process is not None:
            extra = f":{self.process}"
        elif self.kind == "slow_decode":
            extra = f":{self.duration_s * 1e3:g}ms"
            if self.count is not None:
                extra += f":{self.count}"
        elif self.kind == "replica_down" and self.process is not None:
            extra = f":{self.process}"
        elif self.kind == "replica_wedge":
            extra = f":{self.duration_s * 1e3:g}ms"
            if self.process is not None:
                extra += f":{self.process}"
        elif self.kind == "conn_flake":
            extra = f":{self.process}"
        return f"{self.kind}@{at}{extra}"


class FaultPlan:
    """The parsed spec; trainers call the ``maybe_*`` hooks at their
    injection points.  One-shot faults fire exactly once; periodic faults
    fire at every multiple of their period."""

    def __init__(self, faults: List[Fault], seed: int = 0,
                 sleep=time.sleep, kill=os.kill,
                 process_index: Optional[int] = None):
        self.faults = faults
        self.seed = seed
        self._sleep = sleep
        self._kill = kill
        self._process_index = process_index
        self._slow_delay_s = 0.0
        # serving: persistent/windowed decode slowdown state
        self._slow_decode_s = 0.0
        self._slow_decode_until: Optional[int] = None
        self._on_partition: Optional[Callable[[], None]] = None
        # Fault selection is shared mutable state (fired/last_fired_step
        # latches) and is now hit from TWO threads: the trainer's loop
        # (step faults) and the device-prefetcher's producer
        # (loader_error/nan_grad, step-keyed however far ahead it runs).
        # One lock keeps a latch from double-firing across them.
        self._take_lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, **kwargs) -> "FaultPlan":
        faults, seed = [], 0
        for raw in spec.split(","):
            entry = raw.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                seed = int(entry[len("seed="):])
                continue
            kind, at_sep, rest = entry.partition("@")
            if not at_sep or kind not in _KINDS:
                raise ValueError(
                    f"bad chaos entry {entry!r}; expected kind@step with "
                    f"kind in {_KINDS} (e.g. 'nan_grad@17,sigterm@40,"
                    f"stall@25:3s,host_down@30:1,slow_host@10:1:250ms,"
                    f"stall@every:50:1s,preempt@every:12,"
                    f"ckpt_stall@10:200ms,corrupt_ckpt@latest,seed=7')")
            args = rest.split(":") if rest else [""]
            step: Optional[int] = None
            period: Optional[int] = None
            if args[0] == "every":
                if kind not in _PERIODIC_OK:
                    hint = (" (for recurring preemption use "
                            "'preempt@every:N' — each firing checkpoints "
                            "cleanly, so it refires safely)"
                            if kind == "sigterm" else "")
                    raise ValueError(
                        f"@every is only valid for {_PERIODIC_OK}, got "
                        f"{entry!r}{hint}")
                if len(args) < 2 or not args[1].isdigit() or int(args[1]) < 1:
                    raise ValueError(f"@every needs a positive period, "
                                     f"e.g. '{kind}@every:50'; got {entry!r}")
                period = int(args[1])
                args = args[2:]
            elif args[0] == "latest":
                if kind != "corrupt_ckpt":
                    raise ValueError(f"@latest is only valid for "
                                     f"corrupt_ckpt, got {entry!r}")
                args = args[1:]
            else:
                if not re.fullmatch(r"[0-9]+", args[0] or ""):
                    raise ValueError(f"bad step in chaos entry {entry!r}")
                step = int(args[0])
                args = args[1:]
            duration_s, process, count = 0.0, None, None
            if kind == "slow_decode":
                if not args or not args[0]:
                    raise ValueError(
                        f"slow_decode needs a per-iteration delay, e.g. "
                        f"'slow_decode@40:80ms' or "
                        f"'slow_decode@40:80ms:60' (60-iteration spike); "
                        f"got {entry!r}")
                duration_s = _parse_duration(args[0], "ms", entry)
                if len(args) == 2:
                    if not args[1].isdigit() or int(args[1]) < 1:
                        raise ValueError(
                            f"slow_decode spike width must be a positive "
                            f"iteration count; got {entry!r}")
                    if period is not None:
                        raise ValueError(
                            f"slow_decode@every takes only a delay (each "
                            f"firing is one hit); got {entry!r}")
                    count = int(args[1])
                elif len(args) > 2:
                    raise ValueError(f"slow_decode takes delay[:count]; "
                                     f"got {entry!r}")
            elif kind == "stall":
                if len(args) != 1 or not args[0]:
                    raise ValueError(f"stall needs a duration, e.g. "
                                     f"'stall@{rest.split(':')[0]}:3s'; "
                                     f"got {entry!r}")
                duration_s = _parse_duration(args[0], "s", entry)
            elif kind == "ckpt_stall":
                if len(args) != 1 or not args[0]:
                    raise ValueError(
                        f"ckpt_stall needs a duration, e.g. "
                        f"'ckpt_stall@10:200ms' or "
                        f"'ckpt_stall@every:5:150ms'; got {entry!r}")
                duration_s = _parse_duration(args[0], "ms", entry)
            elif kind == "host_down":
                if len(args) != 1 or not args[0].isdigit():
                    raise ValueError(f"host_down needs a process, e.g. "
                                     f"'host_down@30:1'; got {entry!r}")
                process = int(args[0])
            elif kind == "slow_host":
                if len(args) != 2 or not args[0].isdigit():
                    raise ValueError(
                        f"slow_host needs process and per-step delay, e.g. "
                        f"'slow_host@10:1:250ms'; got {entry!r}")
                process = int(args[0])
                duration_s = _parse_duration(args[1], "ms", entry)
            elif kind == "partition":
                if len(args) > 1 or (args and args[0]
                                     and not args[0].isdigit()):
                    raise ValueError(f"partition takes an optional process, "
                                     f"e.g. 'partition@30:1'; got {entry!r}")
                process = int(args[0]) if args and args[0] else None
            elif kind == "replica_down":
                if len(args) > 1 or (args and args[0]
                                     and not args[0].isdigit()):
                    raise ValueError(
                        f"replica_down takes an optional target replica, "
                        f"e.g. 'replica_down@12:1'; got {entry!r}")
                process = int(args[0]) if args and args[0] else None
            elif kind == "replica_wedge":
                if not args or not args[0]:
                    raise ValueError(
                        f"replica_wedge needs a wedge duration, e.g. "
                        f"'replica_wedge@12:800ms' or "
                        f"'replica_wedge@12:800ms:1' (target replica 1); "
                        f"got {entry!r}")
                duration_s = _parse_duration(args[0], "ms", entry)
                if len(args) == 2:
                    if not args[1].isdigit():
                        raise ValueError(
                            f"replica_wedge target must be a replica "
                            f"index; got {entry!r}")
                    process = int(args[1])
                elif len(args) > 2:
                    raise ValueError(f"replica_wedge takes "
                                     f"duration[:replica]; got {entry!r}")
            elif kind == "conn_flake":
                if len(args) != 1 or not args[0].isdigit():
                    raise ValueError(
                        f"conn_flake needs the target replica, e.g. "
                        f"'conn_flake@8:1'; got {entry!r}")
                process = int(args[0])
            elif args and args[0]:
                raise ValueError(f"{kind} takes no extra arguments; "
                                 f"got {entry!r}")
            faults.append(Fault(kind, step, duration_s=duration_s,
                                process=process, period=period,
                                count=count))
        return cls(faults, seed=seed, **kwargs)

    def __str__(self) -> str:
        return ",".join(str(f) for f in self.faults)

    def pending(self) -> List[Fault]:
        """One-shot faults that never fired (periodic faults are excluded:
        they are standing schedules, not obligations)."""
        return [f for f in self.faults
                if not f.fired and f.period is None]

    def bind_partition(self, callback: Callable[[], None]) -> None:
        """Wire ``partition@S`` to the health monitor's simulated-partition
        entry point (resilience/health.py)."""
        self._on_partition = callback

    def _pid(self) -> int:
        if self._process_index is not None:
            return self._process_index
        import jax
        return jax.process_index()

    def _take(self, kind: str, step: Optional[int]) -> Optional[Fault]:
        with self._take_lock:
            return self._take_locked(kind, step)

    def _take_locked(self, kind: str, step: Optional[int]) -> Optional[Fault]:
        for f in self.faults:
            if f.kind != kind:
                continue
            if (f.process is not None and f.kind not in _FLEET_KINDS
                    and self._pid() != f.process):
                continue
            if f.period is not None:
                if (step is not None and step > 0 and step % f.period == 0
                        and step != f.last_fired_step):
                    f.last_fired_step = step
                    log.warning("[chaos] firing %s (step %d)", f, step)
                    self._mark_fired(f, step)
                    return f
                continue
            if not f.fired and f.step == step:
                f.fired = True
                log.warning("[chaos] firing %s", f)
                self._mark_fired(f, step)
                return f
        return None

    @staticmethod
    def _mark_fired(fault: Fault, step: Optional[int]) -> None:
        """Telemetry: an eagerly-flushed timeline instant + a fired
        counter — written BEFORE the fault's side effect runs, because for
        host_down/sigterm there is no after."""
        from dtf_tpu import telemetry as tel
        tel.counter("chaos/faults_fired_total").inc()
        tel.instant(f"chaos/{fault.kind}",
                    **({"step": step} if step is not None else {}),
                    spec=str(fault))

    # -- injection hooks (trainer calls these) ------------------------------

    def maybe_step_faults(self, step: int) -> None:
        """Stall, slow-host delay, partition, SIGTERM and host-down, fired
        at the top of the step loop."""
        f = self._take("stall", step)
        if f is not None:
            self._sleep(f.duration_s)
        f = self._take("slow_host", step)
        if f is not None:
            # Persistent straggler: every step from here on pays the delay
            # (the fault "fires" once; the slowness stays).
            self._slow_delay_s = f.duration_s
        if self._slow_delay_s > 0:
            self._sleep(self._slow_delay_s)
        if self._take("partition", step) is not None:
            if self._on_partition is not None:
                self._on_partition()
            else:
                log.warning("[chaos] partition@%d fired but no health "
                            "monitor is bound (enable --hb_interval_s); "
                            "no-op", step)
        if self._take("sigterm", step) is not None:
            self._kill(os.getpid(), signal.SIGTERM)
        if self._take("preempt", step) is not None:
            # Same delivery as sigterm; a separate kind because it is
            # periodic-capable — each firing drains through the clean
            # preemption save, and the supervisor's restart resumes past
            # it, so the schedule keeps firing across attempts.
            self._kill(os.getpid(), signal.SIGTERM)
        if self._take("host_down", step) is not None:
            # SIGKILL, not SIGTERM or sys.exit: a lost host gets no
            # goodbye — no preemption save, no clean shutdown, no flushed
            # buffers.  Peers must notice via missed heartbeats alone.
            log.warning("[chaos] host_down: killing process %d (SIGKILL)",
                        self._pid())
            self._kill(os.getpid(), signal.SIGKILL)

    def maybe_loader_error(self, step: int) -> None:
        """Raises inside the batch fetch so the REAL retry path recovers."""
        if self._take("loader_error", step) is not None:
            raise ChaosLoaderError(
                f"injected loader failure at step {step} (chaos)")

    def maybe_poison_batch(self, step: int, batch: Any) -> Any:
        """NaN-fill the float leaves of the host batch — the loss and every
        gradient go non-finite, driving the guard end-to-end through the
        real compiled step.  (Integer-only batches, e.g. pure token LM
        data, have no float leaf to poison — fail loudly rather than
        silently not injecting.)"""
        if self._take("nan_grad", step) is None:
            return batch
        import jax

        poisoned = [False]

        def nanify(x):
            x = np.asarray(x)
            if np.issubdtype(x.dtype, np.floating):
                poisoned[0] = True
                return np.full_like(x, np.nan)
            return x

        batch = jax.tree_util.tree_map(nanify, batch)
        if not poisoned[0]:
            raise ValueError(
                "chaos nan_grad: batch has no float leaf to poison (token-"
                "only data); inject at a float-input workload instead")
        return batch

    def maybe_ckpt_stall(self, step: int) -> None:
        """ckpt_stall@S / @every:N: the step-S checkpoint write stalls an
        extra duration — a slow or contended shared filesystem.  The
        trainer calls this inside its checkpoint-measured (and watchdog-
        suspended) window, so the injected latency books as checkpoint
        time and degrades the goodput fraction the scenario gate reads —
        never trips the hang watchdog."""
        f = self._take("ckpt_stall", step)
        if f is not None:
            self._sleep(f.duration_s)

    # -- serving hooks (the engine calls these per ITERATION) ---------------

    def maybe_slow_decode(self, iteration: int) -> float:
        """Extra seconds this decode iteration must pay (0.0 = none).
        One-shot ``slow_decode@S:DUR`` arms a persistent slowdown from
        iteration S (``:N`` bounds it to N iterations); periodic
        ``@every:K:DUR`` is a single hit per firing."""
        delay = 0.0
        f = self._take("slow_decode", iteration)
        if f is not None:
            if f.period is not None:
                delay = f.duration_s
            else:
                self._slow_decode_s = f.duration_s
                self._slow_decode_until = (
                    None if f.count is None else iteration + f.count)
        if self._slow_decode_s > 0:
            if (self._slow_decode_until is not None
                    and iteration >= self._slow_decode_until):
                self._slow_decode_s = 0.0       # spike over
            else:
                delay = max(delay, self._slow_decode_s)
        return delay

    def maybe_client_drop(self, iteration: int) -> bool:
        """True when iteration S's injected client disconnect fires —
        the engine cancels its oldest active request and must free the
        request's KV blocks immediately."""
        return self._take("client_drop", iteration) is not None

    def maybe_kv_poison(self, iteration: int) -> bool:
        """True when the iteration-S KV-corruption fires — the engine
        NaN-scribbles its oldest active request's pool blocks and must
        then detect + evict exactly that victim."""
        return self._take("kv_poison", iteration) is not None

    # -- fleet hooks (the ACCEPTOR calls these per accepted request) --------

    def maybe_replica_down(self, seq: int) -> Optional[int]:
        """``replica_down@S[:P]``: at dispatch sequence S, returns the
        replica index to kill ABRUPTLY (SIGKILL semantics: sever its
        sockets, stop its stepping, no drain).  None = no fire."""
        f = self._take("replica_down", seq)
        return None if f is None else (f.process or 0)

    def maybe_replica_wedge(self, seq: int) -> Optional[Tuple[int, float]]:
        """``replica_wedge@S:DURms[:P]``: returns ``(replica, seconds)``
        — the target stops draining its mailbox (and stepping, so beats
        go stale) for that long.  None = no fire."""
        f = self._take("replica_wedge", seq)
        return None if f is None else ((f.process or 0), f.duration_s)

    def maybe_conn_flake(self, seq: int) -> Optional[int]:
        """``conn_flake@S:P``: returns the replica whose acceptor-side
        sockets must be severed mid-flight (the replica itself stays
        healthy).  None = no fire."""
        f = self._take("conn_flake", seq)
        return None if f is None else (f.process or 0)

    def maybe_corrupt_after_save(self, step: int, ckpt) -> None:
        """corrupt_ckpt@S: wait for the step-S save to land, then scribble
        on it (the manifest was computed from the clean bytes, so the
        corruption is detectable)."""
        if self._take("corrupt_ckpt", step) is None:
            return
        ckpt.wait()              # async save must land before we can maul it
        self._corrupt(ckpt, step)

    def maybe_corrupt_latest(self, ckpt) -> None:
        """corrupt_ckpt@latest: corrupt the newest step right before a
        restore — the crash-mid-save / bit-rot-at-rest window a restart
        walks into."""
        if self._take("corrupt_ckpt", None) is None:
            return
        ckpt.wait()
        step = ckpt.latest_step()
        if step is None:
            log.warning("[chaos] corrupt_ckpt@latest: no checkpoint exists")
            return
        self._corrupt(ckpt, step)

    def _corrupt(self, ckpt, step: int) -> None:
        step_dir = ckpt.step_dir(step)
        if step_dir is None:
            log.warning("[chaos] corrupt_ckpt: no directory for step %d",
                        step)
            return
        corrupt_tree(step_dir, seed=self.seed)
        log.warning("[chaos] corrupted checkpoint step %d (%s)", step,
                    step_dir)


def corrupt_tree(root: str, seed: int = 0, max_bytes: int = 1024) -> int:
    """Overwrite the head of every regular file under ``root`` with seeded
    random bytes (and truncate one file to simulate a partial write).
    Returns the number of files corrupted."""
    rng = np.random.default_rng(seed)
    count = 0
    for dirpath, _, files in sorted(os.walk(root)):
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            size = os.path.getsize(path)
            if size == 0:
                continue
            with open(path, "r+b") as f:
                f.write(rng.bytes(min(size, max_bytes)))
                if count == 0:       # one partial-write casualty
                    f.truncate(max(size // 2, 1))
            count += 1
    return count
