"""Deterministic fault injection: a seeded :class:`FaultPlan` parsed from a
compact spec string.

Chaos testing only earns its keep if a failing run is *replayable*: every
fault fires at an exact global step, exactly once, and byte-level corruption
draws from a seeded rng — so ``--chaos "nan_grad@17,sigterm@40"`` produces
the same failure sequence on every run.  Spec grammar (comma-separated)::

    nan_grad@S           poison the step-S batch's float leaves with NaN
                         (drives the train step's non-finite guard)
    loader_error@S       raise a transient ChaosLoaderError from the step-S
                         batch fetch (drives the data-path retry)
    stall@S:DURs         sleep DUR seconds before step S (drives the hang
                         watchdog; '3s' or bare '3' both parse)
    sigterm@S            deliver SIGTERM to this process before step S
                         (drives the preemption save/exit path)
    corrupt_ckpt@S       after the step-S checkpoint save lands, scribble
                         over its files (drives restore_robust fallback)
    corrupt_ckpt@latest  corrupt the newest checkpoint right before the
                         next restore (the restart-after-crash window)
    seed=N               seed for corruption bytes (default 0)

Every fault fires once.  A plan is shared state: an in-process supervisor
must pass ONE plan through all restart attempts (``Trainer(...,
chaos=plan)``), otherwise step-keyed faults re-fire when the resumed run
replays their step.  The trainer owns the injection points; this module
only decides *when* and performs the host-side side effects.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import signal
import time
from typing import Any, List, Optional

import numpy as np

log = logging.getLogger("dtf_tpu")

_KINDS = ("nan_grad", "loader_error", "stall", "sigterm", "corrupt_ckpt")


class ChaosLoaderError(OSError):
    """Injected transient data-loader failure (an OSError so the data
    path's normal ``retry_on=(OSError,)`` policy handles it — the test
    exercises the real retry code, not a chaos-only branch)."""


@dataclasses.dataclass
class Fault:
    kind: str
    step: Optional[int]          # None for corrupt_ckpt@latest
    duration_s: float = 0.0      # stall only
    fired: bool = False

    def __str__(self) -> str:
        at = "latest" if self.step is None else str(self.step)
        extra = f":{self.duration_s:g}s" if self.kind == "stall" else ""
        return f"{self.kind}@{at}{extra}"


class FaultPlan:
    """The parsed spec; trainers call the ``maybe_*`` hooks at their
    injection points and each matching fault fires exactly once."""

    def __init__(self, faults: List[Fault], seed: int = 0,
                 sleep=time.sleep, kill=os.kill):
        self.faults = faults
        self.seed = seed
        self._sleep = sleep
        self._kill = kill

    @classmethod
    def parse(cls, spec: str, **kwargs) -> "FaultPlan":
        faults, seed = [], 0
        for raw in spec.split(","):
            entry = raw.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                seed = int(entry[len("seed="):])
                continue
            m = re.fullmatch(r"([a-z_]+)@([a-z0-9]+)(?::([0-9.]+)s?)?", entry)
            if not m or m.group(1) not in _KINDS:
                raise ValueError(
                    f"bad chaos entry {entry!r}; expected kind@step with "
                    f"kind in {_KINDS} (e.g. 'nan_grad@17,sigterm@40,"
                    f"stall@25:3s,corrupt_ckpt@latest,seed=7')")
            kind, at, dur = m.group(1), m.group(2), m.group(3)
            if at == "latest":
                if kind != "corrupt_ckpt":
                    raise ValueError(f"@latest is only valid for "
                                     f"corrupt_ckpt, got {entry!r}")
                step = None
            else:
                step = int(at)
            if kind == "stall" and not dur:
                raise ValueError(f"stall needs a duration, e.g. "
                                 f"'stall@{at}:3s'; got {entry!r}")
            faults.append(Fault(kind, step,
                                duration_s=float(dur) if dur else 0.0))
        return cls(faults, seed=seed, **kwargs)

    def __str__(self) -> str:
        return ",".join(str(f) for f in self.faults)

    def pending(self) -> List[Fault]:
        return [f for f in self.faults if not f.fired]

    def _take(self, kind: str, step: Optional[int]) -> Optional[Fault]:
        for f in self.faults:
            if not f.fired and f.kind == kind and f.step == step:
                f.fired = True
                log.warning("[chaos] firing %s", f)
                return f
        return None

    # -- injection hooks (trainer calls these) ------------------------------

    def maybe_step_faults(self, step: int) -> None:
        """Stall and SIGTERM, fired at the top of the step loop."""
        f = self._take("stall", step)
        if f is not None:
            self._sleep(f.duration_s)
        if self._take("sigterm", step) is not None:
            self._kill(os.getpid(), signal.SIGTERM)

    def maybe_loader_error(self, step: int) -> None:
        """Raises inside the batch fetch so the REAL retry path recovers."""
        if self._take("loader_error", step) is not None:
            raise ChaosLoaderError(
                f"injected loader failure at step {step} (chaos)")

    def maybe_poison_batch(self, step: int, batch: Any) -> Any:
        """NaN-fill the float leaves of the host batch — the loss and every
        gradient go non-finite, driving the guard end-to-end through the
        real compiled step.  (Integer-only batches, e.g. pure token LM
        data, have no float leaf to poison — fail loudly rather than
        silently not injecting.)"""
        if self._take("nan_grad", step) is None:
            return batch
        import jax

        poisoned = [False]

        def nanify(x):
            x = np.asarray(x)
            if np.issubdtype(x.dtype, np.floating):
                poisoned[0] = True
                return np.full_like(x, np.nan)
            return x

        batch = jax.tree_util.tree_map(nanify, batch)
        if not poisoned[0]:
            raise ValueError(
                "chaos nan_grad: batch has no float leaf to poison (token-"
                "only data); inject at a float-input workload instead")
        return batch

    def maybe_corrupt_after_save(self, step: int, ckpt) -> None:
        """corrupt_ckpt@S: wait for the step-S save to land, then scribble
        on it (the manifest was computed from the clean bytes, so the
        corruption is detectable)."""
        if self._take("corrupt_ckpt", step) is None:
            return
        ckpt.wait()              # async save must land before we can maul it
        self._corrupt(ckpt, step)

    def maybe_corrupt_latest(self, ckpt) -> None:
        """corrupt_ckpt@latest: corrupt the newest step right before a
        restore — the crash-mid-save / bit-rot-at-rest window a restart
        walks into."""
        if self._take("corrupt_ckpt", None) is None:
            return
        ckpt.wait()
        step = ckpt.latest_step()
        if step is None:
            log.warning("[chaos] corrupt_ckpt@latest: no checkpoint exists")
            return
        self._corrupt(ckpt, step)

    def _corrupt(self, ckpt, step: int) -> None:
        step_dir = ckpt.step_dir(step)
        if step_dir is None:
            log.warning("[chaos] corrupt_ckpt: no directory for step %d",
                        step)
            return
        corrupt_tree(step_dir, seed=self.seed)
        log.warning("[chaos] corrupted checkpoint step %d (%s)", step,
                    step_dir)


def corrupt_tree(root: str, seed: int = 0, max_bytes: int = 1024) -> int:
    """Overwrite the head of every regular file under ``root`` with seeded
    random bytes (and truncate one file to simulate a partial write).
    Returns the number of files corrupted."""
    rng = np.random.default_rng(seed)
    count = 0
    for dirpath, _, files in sorted(os.walk(root)):
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            size = os.path.getsize(path)
            if size == 0:
                continue
            with open(path, "r+b") as f:
                f.write(rng.bytes(min(size, max_bytes)))
                if count == 0:       # one partial-write casualty
                    f.truncate(max(size // 2, 1))
            count += 1
    return count
