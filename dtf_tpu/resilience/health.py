"""Multi-host failure domain: heartbeats, liveness aggregation, straggler
flagging, and coordinated abort.

The watchdog (utils/watchdog.py) catches a hang in THIS process; the
coordination service eventually propagates a dead peer — but "eventually"
is minutes of every healthy host wedged inside a collective.  This module
closes that gap the way the TPU-pod training systems do (MLPerf-0.6 pods,
pjit-scaling): every process heartbeats out-of-band, liveness is
aggregated, and when a peer goes quiet past its budget the healthy hosts
ABORT COHERENTLY — distinct exit code, stack dump, poison-pill handshake —
instead of blocking forever in the next psum.  An external supervisor
(resilience.supervisor.run_elastic_hosts, or the job scheduler) then
relaunches on the hardware that remains; checkpoint restore reshards onto
the shrunken mesh (parallel/mesh.shrink_to_devices + the state template).

Pieces, all transport-agnostic and jax-free so they unit-test in-process:

* :class:`FileHeartbeatTransport` — beats as atomically-replaced files in a
  shared rendezvous dir (GCS/NFS in production, tmpfs in tests);
* :class:`TcpHeartbeatTransport` — no shared FS: non-coordinators push
  beats to a tiny coordinator-hosted TCP service and learn of poison from
  the beat response (``health_dir="tcp://host:port"`` selects it);
* :class:`HealthMonitor` — the per-process daemon thread: beats every
  ``interval_s``, observes peers (every process in file mode, coordinator
  in TCP mode), publishes the cluster-health snapshot (coordinator), and
  runs the abort protocol;
* :func:`flag_stragglers` — the pure slower-than-``median * factor``
  policy the trainer applies to allgathered per-host step times at its
  logging sync points.

Liveness is judged by OBSERVED CHANGE, not by timestamps in the beat
payload: the observer records (its own monotonic clock) when each peer's
beat counter last advanced, so cross-host clock skew cannot fake a death
or hide one.

Abort protocol (exit codes are the supervisor's survivor signal):

* a peer (not all) went quiet => plant the poison pill, dump all-thread
  stacks, ``os._exit(EXIT_PEER_LOST)`` — "I am healthy; the job is not";
* ALL peers went quiet => ``os._exit(EXIT_SELF_ISOLATED)`` — "I am the
  one partitioned/abandoned" (a network partition's minority side exits
  with this, so the supervisor never mistakes it for a survivor);
* the poison pill is observed => same EXIT_PEER_LOST path (someone else
  made the call; exit before the next collective wedges us).

A clean shutdown writes a DEPARTED beat so hosts finishing at slightly
different times never read each other's completion as death.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

log = logging.getLogger("dtf_tpu")

# Exit codes (watchdog owns 70 = wedged-in-place hang):
EXIT_PEER_LOST = 71      # healthy host: a peer missed its heartbeat budget
EXIT_SELF_ISOLATED = 72  # this host lost contact with EVERY peer

DEPARTED = -1            # beat value meaning "exited cleanly, not dead"

_POISON_FILE = "poison.json"
_SNAPSHOT_FILE = "health.json"


def atomic_write(path: str, data: str) -> None:
    """Write-then-rename so no reader ever observes a torn document —
    the invariant every mesh-published snapshot (heartbeats, health.json,
    the fleet plane's per-host docs) leans on."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


_atomic_write = atomic_write          # internal spelling, kept for callers


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class FileHeartbeatTransport:
    """Beats in a shared rendezvous directory: ``hb_<process>`` holds the
    beat counter (atomically replaced), ``poison.json`` is the pill,
    ``health.json`` the coordinator's published snapshot.  Every process
    can observe every other — symmetric detection."""

    observes_peers = True

    def __init__(self, directory: str, process_index: int):
        self.directory = directory
        self.process_index = process_index
        os.makedirs(directory, exist_ok=True)

    def _beat_path(self, process: int) -> str:
        return os.path.join(self.directory, f"hb_{process}")

    def beat(self, count: int) -> Optional[dict]:
        """Record this process's beat; returns the poison (if planted) so
        the send path doubles as the fastest poison check."""
        _atomic_write(self._beat_path(self.process_index), str(count))
        return self.read_poison()

    def read_beats(self) -> Dict[int, int]:
        beats: Dict[int, int] = {}
        for name in os.listdir(self.directory):
            if not name.startswith("hb_"):
                continue
            try:
                beats[int(name[3:])] = int(
                    open(os.path.join(self.directory, name)).read())
            except (OSError, ValueError):
                continue          # mid-replace or foreign file: skip
        return beats

    def plant_poison(self, reason: str, source: int) -> None:
        """Atomic replace — overwriting matters: a pill left by a PREVIOUS
        elastic round (which relaunched monitors deliberately ignore) must
        not block this round's verdict.  Concurrent planters racing is
        harmless: every current-round pill names a real failure."""
        _atomic_write(os.path.join(self.directory, _POISON_FILE),
                      json.dumps({"reason": reason, "source": source,
                                  "time": time.time()}))

    def read_poison(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.directory, _POISON_FILE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def publish_snapshot(self, snapshot: dict) -> None:
        try:
            _atomic_write(os.path.join(self.directory, _SNAPSHOT_FILE),
                          json.dumps(snapshot))
        except OSError as exc:      # observability must never kill the job
            log.warning("health snapshot write failed: %s", exc)

    def close(self) -> None:
        pass


class TcpHeartbeatServer:
    """Coordinator-side beat sink for meshes with no shared filesystem:
    line protocol, one request per connection.

        beat <process> <count>   ->  "ok" | "poison <json>"
        poison <json>            ->  "ok"       (a client made the call)
        snapshot                 ->  one JSON line (ops/debug endpoint)
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._sock.settimeout(0.25)
        self.address = self._sock.getsockname()
        self._lock = threading.Lock()
        self._beats: Dict[int, int] = {}
        self._poison: Optional[dict] = None
        self._snapshot: dict = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dtf_tpu-hb-server")
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                with conn:
                    conn.settimeout(2.0)
                    line = conn.makefile("r").readline().strip()
                    try:
                        reply = self._handle(line)
                    except Exception as exc:
                        # A malformed request (port scanner, HTTP probe,
                        # buggy client) must never kill the serve thread —
                        # a dead beat sink reads as a dead COORDINATOR and
                        # would self-isolate every healthy client.
                        reply = f"err {type(exc).__name__}"
                    conn.sendall((reply + "\n").encode())
            except OSError:
                continue

    def _handle(self, line: str) -> str:
        parts = line.split(" ", 2)
        with self._lock:
            if parts[0] == "beat" and len(parts) == 3:
                self._beats[int(parts[1])] = int(parts[2])
                return ("poison " + json.dumps(self._poison)
                        if self._poison else "ok")
            if parts[0] == "poison" and len(parts) >= 2:
                if self._poison is None:
                    self._poison = json.loads(line.split(" ", 1)[1])
                return "ok"
            if parts[0] == "snapshot":
                return json.dumps(self._snapshot)
            return "err unknown command"

    # -- coordinator-local accessors (no socket round trip) -----------------

    def read_beats(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._beats)

    def plant_poison(self, reason: str, source: int) -> None:
        with self._lock:
            if self._poison is None:
                self._poison = {"reason": reason, "source": source,
                                "time": time.time()}

    def read_poison(self) -> Optional[dict]:
        with self._lock:
            return self._poison

    def publish_snapshot(self, snapshot: dict) -> None:
        with self._lock:
            self._snapshot = snapshot

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2)


class TcpHeartbeatTransport:
    """Client/coordinator facade over :class:`TcpHeartbeatServer`.

    The coordinator hosts the server in-process (full observer); other
    processes push beats over TCP and learn of poison from the response.
    ``consecutive_failures`` counts unreachable-coordinator sends — the
    monitor treats budget-many of those as losing every peer."""

    def __init__(self, address: str, process_index: int,
                 is_coordinator: bool):
        host, _, port = address.partition(":")
        self.process_index = process_index
        self.consecutive_failures = 0
        self._server: Optional[TcpHeartbeatServer] = None
        self._poison: Optional[dict] = None
        if is_coordinator:
            self._server = TcpHeartbeatServer(host or "127.0.0.1", int(port))
        self._addr = (host or "127.0.0.1", int(port))
        self.observes_peers = is_coordinator

    def _request(self, line: str) -> Optional[str]:
        try:
            with socket.create_connection(self._addr, timeout=2.0) as conn:
                conn.sendall((line + "\n").encode())
                reply = conn.makefile("r").readline().strip()
            self.consecutive_failures = 0
            return reply
        except OSError:
            self.consecutive_failures += 1
            return None

    def beat(self, count: int) -> Optional[dict]:
        if self._server is not None:
            self._server._beats[self.process_index] = count
            return self._server.read_poison()
        reply = self._request(f"beat {self.process_index} {count}")
        if reply and reply.startswith("poison "):
            self._poison = json.loads(reply[len("poison "):])
        return self._poison

    def read_beats(self) -> Dict[int, int]:
        return self._server.read_beats() if self._server else {}

    def plant_poison(self, reason: str, source: int) -> None:
        if self._server is not None:
            self._server.plant_poison(reason, source)
        else:
            self._request("poison " + json.dumps(
                {"reason": reason, "source": source, "time": time.time()}))

    def read_poison(self) -> Optional[dict]:
        if self._server is not None:
            return self._server.read_poison()
        return self._poison

    def publish_snapshot(self, snapshot: dict) -> None:
        if self._server is not None:
            self._server.publish_snapshot(snapshot)

    def close(self) -> None:
        if self._server is not None:
            self._server.close()


def make_transport(health_dir: str, process_index: int,
                   is_coordinator: bool):
    """``tcp://host:port`` selects the socket transport (no shared FS);
    anything else is a shared rendezvous directory."""
    if health_dir.startswith("tcp://"):
        return TcpHeartbeatTransport(health_dir[len("tcp://"):],
                                     process_index, is_coordinator)
    return FileHeartbeatTransport(health_dir, process_index)


# ---------------------------------------------------------------------------
# Straggler policy
# ---------------------------------------------------------------------------


def finite_median(values: Sequence[float]) -> float:
    """THE straggler baseline: median over the finite entries (NaN from a
    broken host is flagged, never averaged in).  Shared by the flagging
    decision and every display of it, so the printed 'cluster median'
    can't drift from the threshold that produced the flags."""
    arr = np.asarray(values, np.float64)
    finite = arr[np.isfinite(arr)]
    return float(np.median(finite)) if finite.size else float("nan")


def flag_stragglers(step_ms: Sequence[float], factor: float) -> List[int]:
    """Process indices slower than ``finite_median * factor``.

    Median, not mean: one dying host must not drag the baseline up and
    mask itself.  ``factor <= 1`` disables (everything exceeds nothing);
    non-finite entries are flagged unconditionally (a host reporting NaN
    timing is broken by definition) and excluded from the median."""
    if factor <= 1.0 or len(step_ms) < 2:
        return []
    arr = np.asarray(step_ms, np.float64)
    med = finite_median(arr)
    return [i for i, t in enumerate(arr)
            if not np.isfinite(t) or (med > 0 and t > med * factor)]


# ---------------------------------------------------------------------------
# Monitor
# ---------------------------------------------------------------------------


def _default_abort(code: int, reason: str) -> None:
    print(f"[dtf_tpu] HEALTH: {reason} — coordinated abort (exit {code}). "
          f"All-thread stacks follow:", flush=True)
    from dtf_tpu.utils.watchdog import dump_all_stacks
    dump_all_stacks()
    # os._exit from the monitor thread: the main thread is (or is about to
    # be) wedged inside a collective whose peer is gone — only a hard exit
    # gets the process out (same rationale as the hang watchdog).
    os._exit(code)


@dataclasses.dataclass
class PeerState:
    last_count: int = 0
    last_change: Optional[float] = None   # observer monotonic clock
    departed: bool = False


class HealthMonitor:
    """Per-process heartbeat + liveness daemon (see module docstring).

    ``interval_s`` is the beat period; a peer whose counter hasn't
    advanced in ``miss_budget * interval_s`` (after ``boot_grace_s`` for a
    peer never seen at all) is declared lost.  The thread is independent
    of training progress by design: beats keep flowing through compiles
    and long collectives, so a quiet peer means death/partition, never
    mere slowness.
    """

    def __init__(self, transport, process_index: int, num_processes: int, *,
                 interval_s: float, miss_budget: int = 3,
                 boot_grace_s: float = 30.0,
                 is_coordinator: Optional[bool] = None,
                 on_abort: Callable[[int, str], None] = _default_abort,
                 print_fn: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if miss_budget < 1:
            raise ValueError(f"miss_budget must be >= 1, got {miss_budget}")
        self.transport = transport
        self.process_index = process_index
        self.num_processes = num_processes
        self.interval_s = interval_s
        self.miss_budget = miss_budget
        self.boot_grace_s = boot_grace_s
        self.is_coordinator = (process_index == 0 if is_coordinator is None
                               else is_coordinator)
        self._on_abort = on_abort
        self._print = print_fn or (lambda msg: print(msg, flush=True))
        self._clock = clock
        self._peers: Dict[int, PeerState] = {
            p: PeerState() for p in range(num_processes)
            if p != process_index}
        self._count = 0
        self._start: Optional[float] = None
        self._stale_poison: Optional[dict] = None
        self._partitioned = False
        self._partition_at: Optional[float] = None
        self._last_stragglers: List[int] = []
        self._stop = threading.Event()
        self._aborted: Optional[str] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtf_tpu-health")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "HealthMonitor":
        self._start = self._clock()
        # An elastic relaunch reuses the rendezvous (same --health_dir):
        # a pill already present at start is the PREVIOUS round's verdict,
        # not ours — remember its identity and ignore it, else every
        # multi-host relaunch would abort on arrival.  (Stale hb_* files
        # are harmless: counters are judged by observed change, and a
        # reused slot's fresh beats un-latch DEPARTED below.)
        try:
            self._stale_poison = self.transport.read_poison()
        except Exception:
            self._stale_poison = None
        self._thread.start()
        return self

    def wait_for_peers(self, timeout_s: float = 120.0) -> bool:
        """Startup rendezvous over the beat channel: block until every
        peer has beaten at least once (True) or ``timeout_s`` elapses
        (False — the caller decides whether to proceed degraded).  Puts
        hosts into the step loop in lockstep without a collective — the
        same reason the abort path avoids collectives: at the edges of a
        job's life you cannot rely on them.  A TCP *client* cannot
        observe peers; it waits one miss budget instead (the coordinator
        holds the real barrier)."""
        deadline = time.monotonic() + timeout_s
        if not self.transport.observes_peers:
            time.sleep(min(self.miss_budget * self.interval_s,
                           max(deadline - time.monotonic(), 0)))
            return True
        while time.monotonic() < deadline:
            if self._aborted is not None:
                return False
            try:
                beats = self.transport.read_beats()
            except Exception:
                beats = {}
            if all(p in beats for p in self._peers):
                return True
            time.sleep(self.interval_s / 2)
        return False

    def close(self, mark_departed: bool = True) -> None:
        """Stop the monitor.  ``mark_departed=True`` (a COMPLETED fit)
        writes the DEPARTED beat so peers finishing later don't read our
        exit as a death; a crash path must pass False — its beats simply
        stop, and the peers' abort protocol (correctly) fires, because a
        host going down mid-job is a job failure however Python-level the
        exit was."""
        self._stop.set()
        self._thread.join(timeout=max(2.0, 4 * self.interval_s))
        if mark_departed and not self._partitioned:
            try:
                self.transport.beat(DEPARTED)
            except Exception:
                pass
        self.transport.close()

    # -- chaos hook ---------------------------------------------------------

    def partition(self) -> None:
        """Simulate a network partition of THIS host: stop sending beats
        and stop believing anything we read (we can't see the far side).
        Our own all-peers-stale rule then self-isolates us with
        EXIT_SELF_ISOLATED, while the majority side plants the pill and
        exits EXIT_PEER_LOST."""
        self._print(f"[dtf_tpu] HEALTH: process {self.process_index} "
                    f"entering simulated network partition")
        # From the partition instant NO information flows either way —
        # the monitor stops beating AND stops believing the transport
        # (whose reads would otherwise still work in this simulation,
        # including the TCP coordinator's embedded beat sink).  Staleness
        # is measured from now, unconditionally.
        self._partition_at = self._clock()
        self._partitioned = True

    # -- trainer feed -------------------------------------------------------

    def note_stragglers(self, step: int, per_host_ms: Sequence[float],
                        flagged: Sequence[int]) -> None:
        """Latest straggler verdict (trainer sync points) for the
        published snapshot."""
        self._last_stragglers = [int(i) for i in flagged]

    # -- internals ----------------------------------------------------------

    @property
    def aborted(self) -> Optional[str]:
        """The abort reason when a non-exiting ``on_abort`` was injected
        (tests); None while healthy."""
        return self._aborted

    def _abort(self, code: int, reason: str) -> None:
        # Timeline instant + counter, BEFORE the abort callback: the
        # default callback is os._exit, so there is no after.  The
        # eagerly-flushed span file is how the post-mortem learns which
        # host pulled the pill and why.  Best-effort ONLY — a full disk /
        # unwritable logdir (plausible in exactly the degraded scenarios
        # that trigger aborts) must not skip the abort and convert
        # fail-fast into a distributed hang.
        try:
            from dtf_tpu import telemetry as tel
            tel.counter(f"event/health_abort_{code}").inc()
            tel.instant("health/abort", code=code, reason=reason)
        except Exception:
            pass
        self._aborted = reason
        self._stop.set()
        self._on_abort(code, reason)

    def _observe(self, now: float) -> List[int]:
        """Update per-peer freshness from the transport; return the list
        of peers past their budget."""
        beats = self.transport.read_beats()
        stale: List[int] = []
        budget = self.miss_budget * self.interval_s
        for p, st in self._peers.items():
            count = beats.get(p)
            if count == DEPARTED:
                st.departed = True
                continue
            if count is not None and (st.last_change is None
                                      or count != st.last_count):
                # A fresh counter un-latches DEPARTED too: after an
                # elastic relaunch this slot may be a NEW host reusing a
                # beat file whose previous owner departed.
                st.departed = False
                st.last_count, st.last_change = count, now
                continue
            if st.departed:
                continue
            if st.last_change is None:      # never seen: boot grace applies
                if now - self._start > max(self.boot_grace_s, budget):
                    stale.append(p)
            elif now - st.last_change > budget:
                stale.append(p)
        return stale

    def _snapshot(self, now: float, stale: List[int]) -> dict:
        procs = {}
        for p, st in sorted(self._peers.items()):
            procs[p] = {
                "beats": st.last_count,
                "age_s": (round(now - st.last_change, 3)
                          if st.last_change is not None else None),
                "departed": st.departed,
                "alive": st.departed or p not in stale,
            }
        procs[self.process_index] = {"beats": self._count, "age_s": 0.0,
                                     "departed": False, "alive": True}
        return {"coordinator": self.process_index,
                "interval_s": self.interval_s,
                "miss_budget": self.miss_budget,
                "stragglers": self._last_stragglers,
                "processes": procs}

    def _run(self) -> None:
        while not self._stop.is_set():
            now = self._clock()
            poison = None
            if not self._partitioned:
                self._count += 1
                try:
                    poison = self.transport.beat(self._count)
                except Exception as exc:
                    log.warning("heartbeat send failed: %s", exc)
                if poison is None:
                    try:
                        poison = self.transport.read_poison()
                    except Exception:
                        poison = None
            if (poison is not None and poison != self._stale_poison
                    and poison.get("source") != self.process_index):
                self._abort(
                    EXIT_PEER_LOST,
                    f"poison pill from process {poison.get('source')}: "
                    f"{poison.get('reason')}")
                return
            live_peers = [p for p, st in self._peers.items()
                          if not st.departed]
            if self.transport.observes_peers and not self._partitioned:
                stale = self._observe(now)
                if (len(live_peers) >= 2
                        and set(stale) >= set(live_peers)):
                    # EVERYONE going quiet at once means *we* are the cut-
                    # off side of a partition — with >= 2 independent
                    # peers, simultaneous death of all of them is the far
                    # less likely read.  (With a single peer the evidence
                    # is symmetric, so the peer-lost branch below wins and
                    # the supervisor counts us a survivor.)
                    self._abort(
                        EXIT_SELF_ISOLATED,
                        f"lost contact with ALL peers {sorted(stale)} "
                        f"(am I partitioned?)")
                    return
                if stale:
                    reason = (f"process(es) {sorted(stale)} missed "
                              f"{self.miss_budget} heartbeats "
                              f"({self.miss_budget * self.interval_s:g}s)")
                    try:        # best-effort: never block the poison plant
                        from dtf_tpu import telemetry as tel
                        tel.instant("health/peer_stale",
                                    peers=sorted(stale), reason=reason)
                    except Exception:
                        pass
                    try:
                        self.transport.plant_poison(reason,
                                                    self.process_index)
                    except Exception as exc:
                        log.warning("poison plant failed: %s", exc)
                    if self.is_coordinator:
                        self.transport.publish_snapshot(
                            self._snapshot(now, stale))
                    self._abort(EXIT_PEER_LOST, reason)
                    return
                if self.is_coordinator:
                    self.transport.publish_snapshot(self._snapshot(now, []))
            elif self._partitioned:
                # Simulated partition: nothing flows either way, so once
                # a miss budget elapses with (by definition) no peer
                # heard, this side has lost everyone — self-isolate.
                if live_peers and (now - self._partition_at
                                   > self.miss_budget * self.interval_s):
                    self._abort(
                        EXIT_SELF_ISOLATED,
                        "lost contact with ALL peers (partitioned side "
                        "self-isolating)")
                    return
            elif (not self.transport.observes_peers
                  and getattr(self.transport, "consecutive_failures", 0)
                  >= self.miss_budget):
                # TCP client that cannot reach the coordinator for a full
                # budget: the far side is unreachable, we are the isolated
                # one.
                self._abort(
                    EXIT_SELF_ISOLATED,
                    "lost contact with the coordinator (self-isolating)")
                return
            self._stop.wait(self.interval_s)
