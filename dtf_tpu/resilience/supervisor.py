"""Restart supervisor: run a fit under bounded retries, resuming from the
last checkpoint between attempts.

This is the outermost layer of the failure model (DESIGN.md §5) and the
piece that proves the others compose: the watchdog, the health monitor
and the coordination service turn hangs/dead peers into process exits,
the preemption handler turns SIGTERM into a clean checkpoint, the
non-finite guard turns bad math into skipped steps — and the supervisor
turns the RETRYABLE ones into "restore the last good checkpoint and go
again", with :class:`~dtf_tpu.utils.retry.Backoff` between attempts and a
bounded restart budget so a permanently-broken job still terminates
loudly.  Exit causes are CLASSIFIED first (:func:`classify_exit`):
deterministic failures — :class:`~dtf_tpu.train.trainer.TrainingDiverged`
after the in-fit rollback budget, checkpoint template mismatches, a
refused resume — replay identically on every attempt, so they re-raise
immediately instead of consuming restarts in an unwinnable loop.

In production the supervisor is the job scheduler (k8s restartPolicy, GKE
node auto-repair re-admitting the pod): each attempt is a fresh process
whose ``--resume`` picks up the trajectory.  ``run_supervised`` is the
in-process equivalent for single-host jobs, integration tests, and the
chaos suite; ``fit_once`` must build a FRESH trainer + data stream per
attempt (resume fast-forwards the cursor from the restored step — a reused
mid-stream dataset cannot rewind).
"""

from __future__ import annotations

import logging
import subprocess
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from dtf_tpu import telemetry as tel
from dtf_tpu.utils.retry import Backoff

log = logging.getLogger("dtf_tpu")


def classify_exit(exc: BaseException) -> str:
    """``'terminal'`` or ``'retryable'`` — THE restart-budget gate.

    Terminal failures replay identically on every attempt: a checkpoint
    template/schema mismatch (:class:`CheckpointMismatchError`), a
    refused-resume, or :class:`~dtf_tpu.train.trainer.TrainingDiverged`
    (the rollback budget already restored the last good checkpoint and
    the instability returned — the trajectory is deterministic, so an
    outer restart re-runs the exact same divergence).  Burning the
    restart budget on those buries the loud signal under an unwinnable
    retry loop; the supervisor re-raises them immediately instead.
    Classification is by the ``no_restart`` attribute the deterministic
    error types carry."""
    return "terminal" if getattr(exc, "no_restart", False) else "retryable"


class SupervisorGaveUp(RuntimeError):
    """Restart budget exhausted.  ``history`` holds (attempt, outcome)
    strings; ``__cause__`` chains the last crash (None if the budget went
    to preemptions)."""

    def __init__(self, restarts: int, history: List[Tuple[int, str]]):
        hist = "; ".join(f"#{a}: {o}" for a, o in history)
        super().__init__(
            f"supervisor gave up after {restarts} restart(s): {hist}")
        self.history = history


def _default_needs_restart(result: Any) -> bool:
    """Trainer.fit reports SIGTERM preemption as a clean result with
    ``preempted=True`` — finished-by-interruption, so restart."""
    return isinstance(result, dict) and bool(result.get("preempted"))


def run_supervised(fit_once: Callable[[int], Any], *,
                   max_restarts: int = 3,
                   backoff: Optional[Backoff] = None,
                   retry_on: Sequence[type] = (Exception,),
                   needs_restart: Callable[[Any], bool] = _default_needs_restart,
                   on_restart: Optional[Callable[[int, str], None]] = None,
                   sleep: Callable[[float], None] = time.sleep) -> Any:
    """Run ``fit_once(attempt)`` until it completes, restarting on crash or
    preemption up to ``max_restarts`` times; returns the completed result.

    A restart is consumed when ``fit_once`` raises an exception matching
    ``retry_on`` or returns a result for which ``needs_restart`` is true
    (default: a preempted fit).  ``KeyboardInterrupt``/``SystemExit`` are
    never swallowed.  ``on_restart(attempt, why)`` observes each restart
    before the backoff sleep.  Exhaustion raises :class:`SupervisorGaveUp`
    chained to the last crash.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    backoff = backoff or Backoff(base_s=1.0, max_s=60.0)
    retry_on = tuple(retry_on)
    history: List[Tuple[int, str]] = []
    last_exc: Optional[BaseException] = None
    for attempt in range(max_restarts + 1):
        try:
            # (No span around the attempt itself: the trainer binds the
            # tracer INSIDE fit_once, so a span entered here would capture
            # the previous attempt's closed tracer and silently vanish.
            # The restart instant + backoff span below land on the still-
            # open tracer of the attempt that just failed.)
            result = fit_once(attempt)
        except retry_on as exc:
            if classify_exit(exc) == "terminal":
                # Deterministic failures (TrainingDiverged, checkpoint
                # template/schema mismatch) replay identically on every
                # attempt — restarting only delays and buries the loud
                # signal (see classify_exit).
                raise
            last_exc = exc
            why = f"crashed ({type(exc).__name__}: {exc})"
        else:
            if not needs_restart(result):
                if attempt:
                    log.info("supervisor: completed on attempt %d after "
                             "%d restart(s)", attempt + 1, attempt)
                return result
            why = "preempted"
        # Goodput: downtime starts HERE (the failure point) and runs
        # until the next attempt's trainer starts building — the trainer
        # closes the window (goodput.mark_up) into the restart bucket.
        tel.get_tracker().mark_down()
        history.append((attempt, why))
        if attempt < max_restarts:
            d = backoff.delay_s(attempt)
            log.warning("supervisor: attempt %d %s; restarting from last "
                        "checkpoint in %.2fs (%d/%d restarts used)",
                        attempt + 1, why, d, attempt + 1, max_restarts)
            tel.counter("supervisor/restarts_total").inc()
            tel.instant("event/supervisor_restart", attempt=attempt,
                        why=why)
            if on_restart is not None:
                on_restart(attempt, why)
            with tel.span("supervisor/backoff", delay_s=round(d, 3)):
                sleep(d)
    raise SupervisorGaveUp(max_restarts, history) from last_exc


def run_supervised_fit(trainer_factory: Callable, splits_factory: Callable,
                       base_cfg, *, max_restarts: int,
                       chaos: Any = None,
                       initial_splits: Any = None,
                       backoff: Optional[Backoff] = None,
                       fit_kwargs: Optional[dict] = None,
                       sleep: Callable[[float], None] = time.sleep) -> Any:
    """The supervised-workload pattern, shared by the Trainer-style CLIs
    (mnist, cifar) and tests:

    * ONE chaos plan across all attempts (step-keyed faults fire exactly
      once per supervised run, not once per restart);
    * a FRESH trainer + data stream per attempt, with ``resume=True`` from
      the second attempt on (resume fast-forwards the cursor from the
      restored step — a reused mid-stream dataset cannot rewind);
    * the attempt's checkpoint manager closed win or lose.

    ``trainer_factory(cfg, plan) -> Trainer``; ``splits_factory() ->
    DataSplits`` (or anything ``Trainer.fit`` accepts).  A caller that
    already loaded the data (e.g. to size its lr schedule) passes it as
    ``initial_splits`` — attempt 0 trains on it instead of loading twice;
    only restarts need a fresh, rewound stream.  ``fit_kwargs`` forwards
    extra ``Trainer.fit`` arguments (``max_steps``/``epochs`` — the
    scenario cells' fixed-step budgets) to EVERY attempt; resume
    fast-forwards to the restored step, so a capped budget completes
    across attempts exactly like an uninterrupted run.  Returns the
    completed fit result."""
    import dataclasses

    plan = chaos
    if isinstance(plan, str):
        from dtf_tpu.resilience.chaos import FaultPlan
        plan = FaultPlan.parse(plan)

    def fit_once(attempt: int):
        # No explicit attempt tag: resumed attempts auto-continue past the
        # metrics.csv file's last recorded attempt (MetricLogger), which
        # stays monotonic even when the file already holds attempts from a
        # PREVIOUS supervised run of the same logdir — an absolute
        # attempt=1 here could sort below them and corrupt the report's
        # latest-attempt de-duplication.
        cfg = dataclasses.replace(base_cfg,
                                  resume=base_cfg.resume or attempt > 0)
        trainer = trainer_factory(cfg, plan)
        splits = (initial_splits if attempt == 0
                  and initial_splits is not None else splits_factory())
        try:
            return trainer.fit(splits, **(fit_kwargs or {}))
        finally:
            if trainer.ckpt is not None:
                trainer.ckpt.close()

    return run_supervised(fit_once, max_restarts=max_restarts,
                          backoff=backoff, sleep=sleep)


# ---------------------------------------------------------------------------
# Elastic host-level supervision
# ---------------------------------------------------------------------------


def run_elastic_hosts(build_cmd: Callable[[int, int, int], List[str]],
                      num_hosts: int, *,
                      max_rounds: int = 2,
                      min_hosts: int = 1,
                      env: Optional[dict] = None,
                      cwd: Optional[str] = None,
                      timeout_s: float = 600.0,
                      on_round: Optional[Callable[[int, int], None]] = None,
                      popen=subprocess.Popen) -> Tuple[List[str], int, int]:
    """Run a multi-host job elastically: when a host dies, relaunch on the
    SURVIVING host set (shrunken mesh) instead of giving up.

    The health subsystem (resilience/health.py) makes the survivor set
    legible from exit codes alone: a host that loses a peer exits
    ``EXIT_PEER_LOST`` (71) after the coordinated abort, the dead/
    partitioned host exits some other way (SIGKILL, ``EXIT_SELF_ISOLATED``,
    a crash).  Each round spawns ``build_cmd(slot, n_hosts, round) ->
    argv`` for every surviving slot with CONTIGUOUS re-assigned indices —
    slot k of round r+1 is the k-th survivor of round r — so the relaunch
    is a normal smaller job: ``--num_processes`` drops, ``data=-1`` (or an
    ``--elastic`` fixed mesh via :func:`~dtf_tpu.parallel.mesh.
    shrink_to_devices`) re-resolves over the remaining devices, and
    ``--resume`` reshards the last intact checkpoint onto the shrunken
    mesh through the restore template.

    In production this loop IS the job scheduler (GKE/k8s recreating the
    job with the live node set); this function is the same decision
    procedure in-process for single-machine rigs, integration tests, and
    the chaos suite.

    Returns ``(outputs, final_num_hosts, rounds_used)`` of the completing
    round.  Raises :class:`SupervisorGaveUp` when the round budget is
    spent or fewer than ``min_hosts`` survivors remain.  A host that
    neither completes nor aborts within ``timeout_s`` is killed and
    counted dead (its coordinated abort failed — don't trust it)."""
    from dtf_tpu.resilience.health import EXIT_PEER_LOST

    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    history: List[Tuple[int, str]] = []
    n = num_hosts
    for round_idx in range(max_rounds + 1):
        if on_round is not None:
            on_round(round_idx, n)
        # Outputs go to spooled temp files, not PIPEs: the hosts of one
        # round are interdependent (collectives), so blocking on host k's
        # pipe while host k+1 fills its 64KB buffer could wedge a healthy
        # round into the timeout.  Launching inside the try keeps a
        # mid-fan-out popen failure from leaking the already-started
        # workers.
        import tempfile

        procs, files, outs, codes = [], [], [], []
        deadline = time.monotonic() + timeout_s
        try:
            for slot in range(n):
                f = tempfile.TemporaryFile(mode="w+")
                files.append(f)
                procs.append(popen(build_cmd(slot, n, round_idx), env=env,
                                   cwd=cwd, stdout=f,
                                   stderr=subprocess.STDOUT, text=True))
            for p, f in zip(procs, files):
                killed = False
                try:
                    p.wait(timeout=max(deadline - time.monotonic(), 1.0))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                    killed = True
                f.seek(0)
                out = f.read()
                if killed:
                    out += ("\n[elastic] killed: neither completed nor "
                            "aborted within the round timeout")
                outs.append(out)
                codes.append(p.returncode)
        finally:
            for p in procs:        # never leak workers from a failed round
                if p.poll() is None:
                    p.kill()
            for f in files:
                f.close()
        if all(rc == 0 for rc in codes):
            if round_idx:
                log.info("elastic: completed on round %d with %d/%d hosts",
                         round_idx + 1, n, num_hosts)
            return outs, n, round_idx
        # Survivors: clean completions (finished before the abort fanned
        # out) and coordinated aborts.  Everything else — SIGKILL,
        # self-isolated, crashes, timeouts — is dead hardware.
        survivors = [slot for slot, rc in enumerate(codes)
                     if rc in (0, EXIT_PEER_LOST)]
        why = "; ".join(f"slot {s} rc={rc}" for s, rc in enumerate(codes)
                        if rc not in (0, EXIT_PEER_LOST))
        history.append((round_idx,
                        f"{n} host(s) -> {len(survivors)} survivor(s) "
                        f"({why or 'no host failed?'})"))
        log.warning("elastic: round %d lost %d host(s) (%s); survivors %s",
                    round_idx + 1, n - len(survivors), why, survivors)
        if len(survivors) < min_hosts or not survivors:
            break
        n = len(survivors)
    raise SupervisorGaveUp(max_rounds, history)
