"""Restart supervisor: run a fit under bounded retries, resuming from the
last checkpoint between attempts.

This is the outermost layer of the failure model (DESIGN.md §5) and the
piece that proves the others compose: the watchdog and the coordination
service turn hangs/dead peers into process exits, the preemption handler
turns SIGTERM into a clean checkpoint, the non-finite guard turns bad math
into skipped steps (or a :class:`~dtf_tpu.train.trainer.TrainingDiverged`
raise when it persists) — and the supervisor turns ALL of those into
"restore the last good checkpoint and go again", with
:class:`~dtf_tpu.utils.retry.Backoff` between attempts and a bounded
restart budget so a permanently-broken job still terminates loudly.

In production the supervisor is the job scheduler (k8s restartPolicy, GKE
node auto-repair re-admitting the pod): each attempt is a fresh process
whose ``--resume`` picks up the trajectory.  ``run_supervised`` is the
in-process equivalent for single-host jobs, integration tests, and the
chaos suite; ``fit_once`` must build a FRESH trainer + data stream per
attempt (resume fast-forwards the cursor from the restored step — a reused
mid-stream dataset cannot rewind).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from dtf_tpu.utils.retry import Backoff

log = logging.getLogger("dtf_tpu")


class SupervisorGaveUp(RuntimeError):
    """Restart budget exhausted.  ``history`` holds (attempt, outcome)
    strings; ``__cause__`` chains the last crash (None if the budget went
    to preemptions)."""

    def __init__(self, restarts: int, history: List[Tuple[int, str]]):
        hist = "; ".join(f"#{a}: {o}" for a, o in history)
        super().__init__(
            f"supervisor gave up after {restarts} restart(s): {hist}")
        self.history = history


def _default_needs_restart(result: Any) -> bool:
    """Trainer.fit reports SIGTERM preemption as a clean result with
    ``preempted=True`` — finished-by-interruption, so restart."""
    return isinstance(result, dict) and bool(result.get("preempted"))


def run_supervised(fit_once: Callable[[int], Any], *,
                   max_restarts: int = 3,
                   backoff: Optional[Backoff] = None,
                   retry_on: Sequence[type] = (Exception,),
                   needs_restart: Callable[[Any], bool] = _default_needs_restart,
                   on_restart: Optional[Callable[[int, str], None]] = None,
                   sleep: Callable[[float], None] = time.sleep) -> Any:
    """Run ``fit_once(attempt)`` until it completes, restarting on crash or
    preemption up to ``max_restarts`` times; returns the completed result.

    A restart is consumed when ``fit_once`` raises an exception matching
    ``retry_on`` or returns a result for which ``needs_restart`` is true
    (default: a preempted fit).  ``KeyboardInterrupt``/``SystemExit`` are
    never swallowed.  ``on_restart(attempt, why)`` observes each restart
    before the backoff sleep.  Exhaustion raises :class:`SupervisorGaveUp`
    chained to the last crash.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    backoff = backoff or Backoff(base_s=1.0, max_s=60.0)
    retry_on = tuple(retry_on)
    history: List[Tuple[int, str]] = []
    last_exc: Optional[BaseException] = None
    for attempt in range(max_restarts + 1):
        try:
            result = fit_once(attempt)
        except retry_on as exc:
            if getattr(exc, "no_restart", False):
                # Deterministic failures (e.g. checkpoint template/schema
                # mismatch, CheckpointMismatchError) replay identically on
                # every attempt — restarting only delays and buries the
                # loud signal.
                raise
            last_exc = exc
            why = f"crashed ({type(exc).__name__}: {exc})"
        else:
            if not needs_restart(result):
                if attempt:
                    log.info("supervisor: completed on attempt %d after "
                             "%d restart(s)", attempt + 1, attempt)
                return result
            why = "preempted"
        history.append((attempt, why))
        if attempt < max_restarts:
            d = backoff.delay_s(attempt)
            log.warning("supervisor: attempt %d %s; restarting from last "
                        "checkpoint in %.2fs (%d/%d restarts used)",
                        attempt + 1, why, d, attempt + 1, max_restarts)
            if on_restart is not None:
                on_restart(attempt, why)
            sleep(d)
    raise SupervisorGaveUp(max_restarts, history) from last_exc


def run_supervised_fit(trainer_factory: Callable, splits_factory: Callable,
                       base_cfg, *, max_restarts: int,
                       chaos: Any = None,
                       initial_splits: Any = None,
                       backoff: Optional[Backoff] = None,
                       sleep: Callable[[float], None] = time.sleep) -> Any:
    """The supervised-workload pattern, shared by the Trainer-style CLIs
    (mnist, cifar) and tests:

    * ONE chaos plan across all attempts (step-keyed faults fire exactly
      once per supervised run, not once per restart);
    * a FRESH trainer + data stream per attempt, with ``resume=True`` from
      the second attempt on (resume fast-forwards the cursor from the
      restored step — a reused mid-stream dataset cannot rewind);
    * the attempt's checkpoint manager closed win or lose.

    ``trainer_factory(cfg, plan) -> Trainer``; ``splits_factory() ->
    DataSplits`` (or anything ``Trainer.fit`` accepts).  A caller that
    already loaded the data (e.g. to size its lr schedule) passes it as
    ``initial_splits`` — attempt 0 trains on it instead of loading twice;
    only restarts need a fresh, rewound stream.  Returns the completed
    fit result."""
    import dataclasses

    plan = chaos
    if isinstance(plan, str):
        from dtf_tpu.resilience.chaos import FaultPlan
        plan = FaultPlan.parse(plan)

    def fit_once(attempt: int):
        cfg = dataclasses.replace(base_cfg,
                                  resume=base_cfg.resume or attempt > 0)
        trainer = trainer_factory(cfg, plan)
        splits = (initial_splits if attempt == 0
                  and initial_splits is not None else splits_factory())
        try:
            return trainer.fit(splits)
        finally:
            if trainer.ckpt is not None:
                trainer.ckpt.close()

    return run_supervised(fit_once, max_restarts=max_restarts,
                          backoff=backoff, sleep=sleep)
