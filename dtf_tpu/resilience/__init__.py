"""Self-healing subsystem: deterministic fault injection + restart supervision.

The reference had *no* failure handling — a dead PS hung every worker
forever and any crash lost all state (SURVEY.md §5.3-§5.4).  The seed
framework answered with fail-fast primitives (hang watchdog, SIGTERM →
checkpoint, orbax resume); this package closes the loop with the failures
that *don't* kill the process and the machinery that proves recovery
end-to-end:

* :mod:`dtf_tpu.resilience.chaos` — a seeded, spec-driven fault plan
  (non-finite gradients, loader errors, stalls, checkpoint corruption,
  simulated preemption, plus host-level faults: abrupt host death,
  persistent stragglers, network partitions, repeating ``@every`` faults)
  injected at exact steps, from tests or the CLI;
* :mod:`dtf_tpu.resilience.health` — the multi-host failure domain:
  per-process heartbeats (shared-dir or coordinator-TCP transport),
  coordinator-published cluster-health snapshots, straggler flagging, and
  the poison-pill coordinated abort (exit 71/72) that frees healthy hosts
  from a dead peer's collective instead of hanging in it;
* :mod:`dtf_tpu.resilience.supervisor` — bounded-restart supervision of a
  whole fit with exit-cause classification (deterministic failures fail
  fast instead of burning restarts), plus ``run_elastic_hosts``: relaunch
  a multi-host job on the surviving host set with a shrunken mesh.

The in-step non-finite guard and rollback policy live in the trainer
(``train/trainer.py``); checkpoint checksums and the corruption-tolerant
restore live in ``train/checkpoint.py``.  DESIGN.md §5 has the full
failure-model walkthrough.
"""

from dtf_tpu.resilience.chaos import ChaosLoaderError, FaultPlan  # noqa: F401
from dtf_tpu.resilience.health import (  # noqa: F401
    EXIT_PEER_LOST, EXIT_SELF_ISOLATED, HealthMonitor, flag_stragglers,
    make_transport,
)
from dtf_tpu.resilience.supervisor import (  # noqa: F401
    SupervisorGaveUp, classify_exit, run_elastic_hosts, run_supervised,
    run_supervised_fit,
)
