"""Self-healing subsystem: deterministic fault injection + restart supervision.

The reference had *no* failure handling — a dead PS hung every worker
forever and any crash lost all state (SURVEY.md §5.3-§5.4).  The seed
framework answered with fail-fast primitives (hang watchdog, SIGTERM →
checkpoint, orbax resume); this package closes the loop with the failures
that *don't* kill the process and the machinery that proves recovery
end-to-end:

* :mod:`dtf_tpu.resilience.chaos` — a seeded, spec-driven fault plan
  (non-finite gradients, loader errors, stalls, checkpoint corruption,
  simulated preemption) injected at exact steps, from tests or the CLI;
* :mod:`dtf_tpu.resilience.supervisor` — bounded-restart supervision of a
  whole fit, resuming from the last good checkpoint between attempts.

The in-step non-finite guard and rollback policy live in the trainer
(``train/trainer.py``); checkpoint checksums and the corruption-tolerant
restore live in ``train/checkpoint.py``.  DESIGN.md §5 has the full
failure-model walkthrough.
"""

from dtf_tpu.resilience.chaos import ChaosLoaderError, FaultPlan  # noqa: F401
from dtf_tpu.resilience.supervisor import (  # noqa: F401
    SupervisorGaveUp, run_supervised, run_supervised_fit,
)
