// Native data loader: IDX (MNIST-format) parser + threaded prefetch ring.
//
// The reference delegated all native work to the TensorFlow C++ runtime
// (SURVEY.md §2.13); this framework's compute path is XLA/Pallas, and the
// host-side runtime around it is native where it matters.  Input pipelines
// are host-bound work that competes with dispatch on the Python thread, so
// batch assembly (shuffle, normalize, one-hot) runs here on a background
// thread with a bounded ring buffer; Python only memcpy's finished batches.
//
// Contract mirrors dtf_tpu.data.Dataset.next_batch (shuffled epochs,
// sequential batches, reshuffle at epoch end) with its own xorshift RNG.
//
// Build: g++ -O3 -shared -fPIC dataloader.cpp -o _libdtfdata.so  (see
// dtf_tpu/native/__init__.py, which builds lazily and caches by mtime).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Idx {
  std::vector<uint8_t> data;
  std::vector<int> shape;
};

bool read_idx(const char* path, Idx* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  uint8_t magic[4];
  if (fread(magic, 1, 4, f) != 4) { fclose(f); return false; }
  // IDX magic: 0x00 0x00 <dtype> <ndim>; only uint8 (0x08) is supported
  int ndim = magic[3];
  if (magic[0] != 0 || magic[1] != 0 || magic[2] != 0x08 ||
      ndim < 1 || ndim > 4) {
    fclose(f);
    return false;
  }
  out->shape.assign(ndim, 0);
  size_t total = 1;
  constexpr size_t kMaxBytes = size_t{1} << 33;  // 8 GiB sanity cap
  for (int i = 0; i < ndim; i++) {
    uint8_t b[4];
    if (fread(b, 1, 4, f) != 4) { fclose(f); return false; }
    int dim = (b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3];
    if (dim <= 0) { fclose(f); return false; }
    out->shape[i] = dim;
    total *= static_cast<size_t>(dim);
    if (total > kMaxBytes) { fclose(f); return false; }
  }
  out->data.resize(total);
  size_t got = fread(out->data.data(), 1, total, f);
  fclose(f);
  return got == total;
}

uint64_t xorshift64(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return *s = x;
}

struct Loader {
  Idx images, labels;
  int n = 0, feat = 0, classes = 0, batch = 0, depth = 0;
  uint64_t rng = 0;
  std::vector<uint32_t> order;
  size_t pos = 0;

  // ring buffer of finished batches
  std::vector<std::vector<float>> img_q, lab_q;
  size_t head = 0, tail = 0, count = 0;
  std::mutex mu;
  std::condition_variable cv_can_produce, cv_can_consume;
  std::atomic<bool> stop{false};
  std::thread worker;

  void reshuffle() {  // Fisher-Yates over the index order
    for (size_t i = order.size() - 1; i > 0; i--) {
      size_t j = xorshift64(&rng) % (i + 1);
      std::swap(order[i], order[j]);
    }
  }

  void fill_batch(float* img_out, float* lab_out) {
    // mirror dtf_tpu.data.Dataset.next_batch: reshuffle at batch start
    // when the whole batch no longer fits in the epoch
    if (pos + static_cast<size_t>(batch) > static_cast<size_t>(n)) {
      reshuffle();
      pos = 0;
    }
    for (int b = 0; b < batch; b++) {
      uint32_t idx = order[pos++];
      const uint8_t* src = images.data.data() +
                           static_cast<size_t>(idx) * feat;
      float* dst = img_out + static_cast<size_t>(b) * feat;
      for (int k = 0; k < feat; k++) dst[k] = src[k] * (1.0f / 255.0f);
      float* lab = lab_out + static_cast<size_t>(b) * classes;
      memset(lab, 0, sizeof(float) * classes);
      int y = labels.data[idx];
      if (y >= 0 && y < classes) lab[y] = 1.0f;
    }
  }

  void run() {
    while (!stop.load()) {
      std::unique_lock<std::mutex> lk(mu);
      cv_can_produce.wait(lk, [&] {
        return stop.load() || count < static_cast<size_t>(depth);
      });
      if (stop.load()) return;
      size_t slot = head;
      lk.unlock();
      fill_batch(img_q[slot].data(), lab_q[slot].data());
      lk.lock();
      head = (head + 1) % depth;
      count++;
      cv_can_consume.notify_one();
    }
  }
};

}  // namespace

extern "C" {

Loader* dtf_loader_open(const char* images_path, const char* labels_path,
                        int classes, int batch, uint64_t seed,
                        int depth) try {
  Loader* ld = new Loader();
  if (!read_idx(images_path, &ld->images) ||
      !read_idx(labels_path, &ld->labels) ||
      ld->images.shape.empty() || ld->labels.shape.empty() ||
      ld->images.shape[0] != ld->labels.shape[0] ||
      batch < 1 || batch > ld->images.shape[0]) {
    delete ld;
    return nullptr;
  }
  ld->n = ld->images.shape[0];
  ld->feat = static_cast<int>(ld->images.data.size()) / ld->n;
  ld->classes = classes;
  ld->batch = batch;
  ld->depth = depth < 1 ? 1 : depth;
  ld->rng = seed ? seed : 0x9E3779B97F4A7C15ull;
  ld->order.resize(ld->n);
  for (int i = 0; i < ld->n; i++) ld->order[i] = i;
  ld->reshuffle();
  ld->img_q.assign(ld->depth, std::vector<float>(
      static_cast<size_t>(batch) * ld->feat));
  ld->lab_q.assign(ld->depth, std::vector<float>(
      static_cast<size_t>(batch) * classes));
  ld->worker = std::thread([ld] { ld->run(); });
  return ld;
} catch (...) {
  return nullptr;   // never let C++ exceptions cross the C boundary
}

int dtf_loader_num_examples(Loader* ld) { return ld ? ld->n : -1; }
int dtf_loader_feat(Loader* ld) { return ld ? ld->feat : -1; }

// Blocking: copies the next prefetched batch into caller buffers.
int dtf_loader_next(Loader* ld, float* images_out, float* labels_out) {
  if (!ld) return -1;
  std::unique_lock<std::mutex> lk(ld->mu);
  ld->cv_can_consume.wait(lk, [&] { return ld->count > 0; });
  size_t slot = ld->tail;
  memcpy(images_out, ld->img_q[slot].data(),
         ld->img_q[slot].size() * sizeof(float));
  memcpy(labels_out, ld->lab_q[slot].data(),
         ld->lab_q[slot].size() * sizeof(float));
  ld->tail = (ld->tail + 1) % ld->depth;
  ld->count--;
  ld->cv_can_produce.notify_one();
  return 0;
}

void dtf_loader_close(Loader* ld) {
  if (!ld) return;
  ld->stop.store(true);
  ld->cv_can_produce.notify_all();
  if (ld->worker.joinable()) ld->worker.join();
  delete ld;
}

}  // extern "C"
