"""Native (C++) runtime components, built lazily with the system toolchain.

The compute path of this framework is XLA/Pallas on TPU; the host-side
runtime around it is C++ where the reference delegated to TF's C++ runtime
(SURVEY.md §2.13).  Components here build on demand with ``g++`` into a
shared library next to the source, cached by source mtime, and every
consumer has a pure-Python fallback so the framework works without a
toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger("dtf_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "dataloader.cpp")
_LIB = os.path.join(_DIR, "_libdtfdata.so")
_lock = threading.Lock()
_lib: "Optional[ctypes.CDLL] | bool" = None   # None=untried, False=failed


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        log.warning("native dataloader build failed (%s); using the Python "
                    "loader. %s", e, detail.decode(errors="replace")[:500])
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """The native dataloader library, building it on first use.  Returns
    None (and logs once) when no toolchain is available."""
    global _lib
    with _lock:
        if _lib is False:
            return None
        if _lib is not None:
            return _lib
        stale = (not os.path.exists(_LIB)
                 or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        if stale and not _build():
            _lib = False
            return None
        lib = ctypes.CDLL(_LIB)
        lib.dtf_loader_open.restype = ctypes.c_void_p
        lib.dtf_loader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int]
        lib.dtf_loader_next.restype = ctypes.c_int
        lib.dtf_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float)]
        for name in ("dtf_loader_num_examples", "dtf_loader_feat"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p]
        lib.dtf_loader_close.restype = None
        lib.dtf_loader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib
