"""Python binding for the native prefetching data loader.

``NativeDataset`` exposes the same ``next_batch``/``num_examples`` contract
as :class:`dtf_tpu.data.Dataset`, but batch assembly (shuffle, /255
normalize, one-hot) happens on a C++ background thread with a bounded ring
buffer — the Python thread only memcpy's finished batches, so input work
overlaps jit dispatch instead of serializing with it.  Fixed batch size
(set at construction; the prefetcher owns the shapes).

Falls back cleanly: ``from_idx`` returns None when the native library can't
build or the files aren't raw IDX (e.g. gzipped) — callers keep the pure
Python loader.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from dtf_tpu.native import load_library
from dtf_tpu.utils.retry import Backoff, retry_call


class NativeDataset:
    """Prefetched IDX dataset with the Dataset batch contract."""

    def __init__(self, lib, handle: int, batch_size: int, num_classes: int):
        self._lib = lib
        self._handle = handle
        self.batch_size = batch_size
        self.num_classes = num_classes
        self._n = lib.dtf_loader_num_examples(handle)
        self._feat = lib.dtf_loader_feat(handle)
        self.batches_consumed = 0
        # One schedule for the loader's lifetime — next_batch is the hot
        # data path and must not re-seed an rng per fetch.
        self._retry_backoff = Backoff(base_s=0.05, max_s=0.5)

    @classmethod
    def from_idx(cls, images_path: str, labels_path: str, *,
                 batch_size: int, num_classes: int = 10,
                 seed: int = 1, queue_depth: int = 4
                 ) -> "Optional[NativeDataset]":
        lib = load_library()
        if lib is None:
            return None
        handle = lib.dtf_loader_open(
            images_path.encode(), labels_path.encode(), num_classes,
            batch_size, seed, queue_depth)
        if not handle:
            return None
        return cls(lib, handle, batch_size, num_classes)

    @property
    def num_examples(self) -> int:
        return self._n

    @property
    def feature_dim(self) -> int:
        return self._feat

    def _check_batch_size(self, batch_size: int) -> None:
        if batch_size != self.batch_size:
            raise ValueError(
                f"NativeDataset prefetches fixed batches of "
                f"{self.batch_size}, got request for {batch_size}")

    def _pull_into(self, imgs: np.ndarray, labs: np.ndarray) -> None:
        """Fill caller-owned buffers with the next prefetched batch.

        A nonzero rc today means a closed/invalid handle (deterministic),
        so the bounded retry exists for the error CONTRACT — any future
        transient rc codes get a brief retry, and every failure ends in
        a loud terminal RetryExhausted, never an unbounded loop.  A dead
        producer thread is a different failure class: it blocks inside
        the C++ wait, which the trainer's hang watchdog (not this retry)
        converts into a fail-fast exit."""
        def pull():
            rc = self._lib.dtf_loader_next(
                self._handle,
                imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                labs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            if rc != 0:
                raise OSError(f"native loader dtf_loader_next rc={rc}")

        from dtf_tpu import telemetry as tel
        retry_call(pull, attempts=3, backoff=self._retry_backoff,
                   retry_on=(OSError,), what="native loader next_batch",
                   on_retry=lambda a, e: tel.counter(
                       "data/fetch_retries_total").inc())

    def next_batch(self, batch_size: int) -> tuple:
        self._check_batch_size(batch_size)
        imgs = np.empty((self.batch_size, self._feat), np.float32)
        labs = np.empty((self.batch_size, self.num_classes), np.float32)
        from dtf_tpu import telemetry as tel
        with tel.span("data/next_batch", n=batch_size, native=1):
            self._pull_into(imgs, labs)
        self.batches_consumed += 1
        return imgs, labs

    def fast_forward(self, n_batches: int, batch_size: int) -> None:
        """Resume support: drain n batches (the prefetcher computes them
        anyway; draining keeps the shuffle stream aligned).  ONE scratch
        buffer pair is reused for the whole drain — a multi-epoch resume
        drains O(steps) batches and must not allocate O(steps) arrays the
        way looping next_batch would."""
        if n_batches <= 0:
            return
        self._check_batch_size(batch_size)
        imgs = np.empty((self.batch_size, self._feat), np.float32)
        labs = np.empty((self.batch_size, self.num_classes), np.float32)
        from dtf_tpu import telemetry as tel
        with tel.span("data/fast_forward", n=n_batches, native=1):
            for _ in range(n_batches):
                self._pull_into(imgs, labs)
        self.batches_consumed += n_batches

    def close(self) -> None:
        if self._handle:
            self._lib.dtf_loader_close(self._handle)
            self._handle = 0

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
