"""Data pipeline.

The reference loaded MNIST via ``input_data.read_data_sets('MNIST_data',
one_hot=True)`` and batched with ``mnist.train.next_batch(batch_size)``
through feed_dict (tf_distributed.py:27-28,108) — on *every* process
including the PS and even in the matmul benchmark that never used it
(SURVEY.md §2.5).

This module preserves the ``next_batch`` API shape, with fixes:

* loads lazily (only the processes/workloads that need data);
* reads the standard IDX files from ``MNIST_data/`` if present; in a
  zero-egress environment it falls back to a deterministic synthetic dataset
  with the same shapes/dtypes (class-prototype + noise, linearly separable
  enough to test convergence);
* deterministic shuffling from a seed, so runs are bitwise reproducible
  (the reference's async updates were nondeterministic by design,
  SURVEY.md §7 "determinism").

Sharding note: batches are produced as host numpy arrays for the *global*
batch; the trainer device_puts them with the batch sharded over the data
axes.  Under multi-process SPMD each process produces the same global batch
from the same seed and jax.make_array_from_process_local_data carves out its
addressable shards.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Iterator, Optional

import numpy as np


class _ShuffledSplit:
    """Shared shuffle-cursor machinery behind the ``next_batch`` contract.

    Subclasses store the payload and implement ``take(idx)`` (gather rows)
    and ``examples(lo, hi)`` (sequential rows for eval — the generic
    accessor the trainer's eval loop uses so it never touches
    ``.images``/``.labels`` directly)."""

    def _init_cursor(self):
        self._rng = np.random.default_rng(self.seed)
        self._order = np.arange(self.num_examples)
        self._rng.shuffle(self._order)
        self._pos = 0
        self.batches_consumed = 0

    def _advance(self, batch_size: int) -> np.ndarray:
        """Shuffled row indices for the next batch; reshuffles at epoch end
        (mnist.train.next_batch semantics, tf_distributed.py:108)."""
        if batch_size > self.num_examples:
            raise ValueError(
                f"batch_size {batch_size} exceeds the split's "
                f"{self.num_examples} examples; shrink the (global) batch "
                f"or provide more data")
        if self._pos + batch_size > self.num_examples:
            self._rng.shuffle(self._order)
            self._pos = 0
        idx = self._order[self._pos:self._pos + batch_size]
        self._pos += batch_size
        return idx

    def next_batch(self, batch_size: int):
        from dtf_tpu import telemetry as tel
        with tel.span("data/next_batch", n=batch_size):
            idx = self._advance(batch_size)
            self.batches_consumed += 1
            return self.take(idx)

    def fast_forward(self, n_batches: int, batch_size: int) -> None:
        """Advance the shuffle cursor as if ``next_batch`` had been called
        ``n_batches`` times, without materializing any batch (checkpoint
        resume: replays only the per-epoch reshuffles + position)."""
        if n_batches and batch_size > self.num_examples:
            raise ValueError(
                f"batch_size {batch_size} exceeds the split's "
                f"{self.num_examples} examples; shrink the (global) batch "
                f"or provide more data")
        for _ in range(n_batches):
            if self._pos + batch_size > self.num_examples:
                self._rng.shuffle(self._order)
                self._pos = 0
            self._pos += batch_size
        self.batches_consumed += n_batches

    def process_shard(self, process_index: int,
                      process_count: int) -> "ProcessShard":
        """Per-host view for true multi-host loading: serves this process's
        contiguous rows of each *global* batch (pair with
        ``put_process_batch``)."""
        return ProcessShard(self, process_index, process_count)


@dataclasses.dataclass
class Dataset(_ShuffledSplit):
    """In-memory split with the reference's ``next_batch`` contract."""

    images: np.ndarray          # (N, ...) float32
    labels: np.ndarray          # (N, num_classes) one-hot float32
    seed: int = 1

    def __post_init__(self):
        self._init_cursor()

    @property
    def num_examples(self) -> int:
        return len(self.images)

    def take(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return self.images[idx], self.labels[idx]

    def examples(self, lo: int, hi: int) -> tuple[np.ndarray, np.ndarray]:
        return self.images[lo:hi], self.labels[lo:hi]

    def epoch_batches(self, batch_size: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        for _ in range(self.num_examples // batch_size):
            yield self.next_batch(batch_size)

    def shard(self, process_index: int, process_count: int) -> "Dataset":
        """Disjoint per-host partition with an independent shuffle stream:
        process k keeps examples ``k::process_count`` — strided, so class
        structure survives sorted storage — with a per-shard shuffle seed.
        The trailing remainder (< process_count examples) is dropped so
        every shard has equal length (collectives need equal local batch
        sizes).  Unlike :meth:`process_shard` the resulting trajectory
        differs from the global-batch path (different batch composition)."""
        n = (self.num_examples // process_count) * process_count
        sel = np.arange(process_index, n, process_count)
        return Dataset(self.images[sel], self.labels[sel],
                       seed=self.seed + 7919 * process_index)


@dataclasses.dataclass
class TokenDataset(_ShuffledSplit):
    """Token sequences (N, T) int32 under the same ``next_batch`` contract,
    producing ``{"tokens": (B, T)}`` batches — the LM/seq2seq counterpart of
    :class:`Dataset`, so the ONE trainer loop (checkpoint/resume/preemption/
    watchdog) drives every model family."""

    tokens: np.ndarray
    seed: int = 1

    def __post_init__(self):
        self.tokens = np.asarray(self.tokens, np.int32)
        self._init_cursor()

    @property
    def num_examples(self) -> int:
        return len(self.tokens)

    def take(self, idx: np.ndarray) -> dict:
        return {"tokens": self.tokens[idx]}

    def examples(self, lo: int, hi: int) -> dict:
        return {"tokens": self.tokens[lo:hi]}

    def shard(self, process_index: int, process_count: int) -> "TokenDataset":
        n = (self.num_examples // process_count) * process_count
        sel = np.arange(process_index, n, process_count)
        return TokenDataset(self.tokens[sel],
                            seed=self.seed + 7919 * process_index)


class ProcessShard:
    """Per-host view of a split for true multi-host data loading.

    Serves this process's CONTIGUOUS rows of each global batch — the rows
    ``put_process_batch`` expects process k to contribute — by advancing the
    SAME shuffle stream as the global path and gathering only its own slice.
    The union of all processes' slices at step i is exactly the global batch
    at step i, so the optimization trajectory is bitwise-identical to
    ``put_global_batch`` while each host materializes 1/nproc of the data.
    """

    def __init__(self, base: _ShuffledSplit, process_index: int,
                 process_count: int):
        self.base = base
        self.k = process_index
        self.n = process_count
        # Mirror the base's consumption so resume bookkeeping (trainer's
        # `behind` computation) survives wrapping mid-stream.
        self.batches_consumed = base.batches_consumed

    @property
    def num_examples(self) -> int:
        # Global count: batch_count math must match the global path.
        return self.base.num_examples

    def next_batch(self, local_batch: int):
        idx = self.base._advance(local_batch * self.n)
        self.base.batches_consumed += 1
        self.batches_consumed += 1
        return self.base.take(idx[self.k * local_batch:
                                  (self.k + 1) * local_batch])

    def fast_forward(self, n_batches: int, local_batch: int) -> None:
        self.base.fast_forward(n_batches, local_batch * self.n)
        self.batches_consumed += n_batches

    def examples(self, lo: int, hi: int):
        raise NotImplementedError(
            "ProcessShard is a train-only per-host view; eval should read "
            "sequential rows from the unwrapped split (splits.test) so each "
            "host sees its own disjoint share, not the global rows")


@dataclasses.dataclass
class DataSplits:
    train: "Dataset"
    test: Optional["Dataset"] = None     # None: trainer skips evaluation
    synthetic: bool = False


class CallableDataset:
    """Adapter giving a ``batch_index -> host batch`` callable the
    ``next_batch`` contract (benchmark workloads that synthesize batches on
    the fly, e.g. seq2seq source/target pairs).  Fixed batch size; no
    shuffling of its own (the callable owns batch composition)."""

    def __init__(self, fn, batch_size: int, num_batches: int):
        self.fn = fn
        self.batch_size = batch_size
        self.num_batches = num_batches
        self._i = 0
        self.batches_consumed = 0

    @property
    def num_examples(self) -> int:
        return self.batch_size * self.num_batches

    def next_batch(self, batch_size: int):
        if batch_size != self.batch_size:
            raise ValueError(f"CallableDataset serves fixed batches of "
                             f"{self.batch_size}, asked for {batch_size}")
        out = self.fn(self._i)
        self._i += 1
        self.batches_consumed += 1
        return out

    def fast_forward(self, n_batches: int, batch_size: int) -> None:
        self._i += n_batches
        self.batches_consumed += n_batches


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _one_hot(y: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(y), n), np.float32)
    out[np.arange(len(y)), y] = 1.0
    return out


def _synthetic_classification(n: int, feat_shape: tuple, num_classes: int,
                              seed: int, split_seed: int,
                              noise: float = 0.40, modes: int = 3,
                              label_noise: float = 0.08,
                              spread: float = 0.20) -> tuple:
    """Deterministic synthetic data, shaped like the real dataset — built to
    be UNSATURABLE so recorded accuracies are falsifiable.

    Round-2's prototype+noise task was near-linearly-separable: the
    reference 784-100-10 MLP hit 1.00 test accuracy, which proved the
    format readers worked but could never regress if optimization broke.
    Three ingredients make this task hard (measured with the reference
    MLP; see BASELINE.md round 3 for the recorded rows):

    * **multimodal classes** — each class is a mixture of ``modes``
      prototypes, so no linear boundary separates it;
    * **label noise** — ``label_noise`` of labels are resampled
      uniformly, an irreducible ceiling of ~1 - p·(C-1)/C ≈ 0.93 and a
      train/test gap once a high-capacity model memorizes flips;
    * **class overlap** — prototype ``spread`` relative to the noise
      floor sets boundary difficulty.  The default 0.20 keeps small-n
      test fixtures trainable (0.91 test at n=2048) while staying under
      the flip ceiling; spread 0.09 is the measured cliff where
      optimization quality dominates (20k examples, 12 epochs adam:
      0.12 → 0.91 test, 0.09 → 0.82 with a +0.024 train/test gap,
      0.07 → 0.57) — the BASELINE stress row uses it.

    Class prototypes come from ``seed`` only, so train and test splits
    (which differ in ``split_seed``) are samples of the SAME task."""
    proto_rng = np.random.default_rng(seed)
    rng = np.random.default_rng((seed, split_seed))
    dim = int(np.prod(feat_shape))
    protos = (proto_rng.normal(0, 1, (num_classes, modes, dim))
              .astype(np.float32) * spread)
    y = rng.integers(0, num_classes, n)
    mode = rng.integers(0, modes, n)
    x = protos[y, mode] + rng.normal(0, noise, (n, dim)).astype(np.float32)
    if label_noise > 0.0:
        flip = rng.random(n) < label_noise
        y = y.copy()
        y[flip] = rng.integers(0, num_classes, int(flip.sum()))
    x = (x - x.min()) / (x.max() - x.min())   # [0,1] like pixel data
    return x.reshape((n, *feat_shape)).astype(np.float32), _one_hot(y, num_classes)


def load_mnist(data_dir: str = "MNIST_data", seed: int = 1,
               flat: bool = True,
               native_train_batch: Optional[int] = None) -> DataSplits:
    """MNIST as the reference consumed it: 784-dim flat float images in
    [0,1], one-hot labels (tf_distributed.py:27-28,42-46).  Falls back to
    synthetic data (same shapes) when the IDX files are absent.

    ``native_train_batch``: serve the TRAIN split through the C++
    prefetching loader (dtf_tpu/native) at this fixed batch size; falls
    back silently to the Python loader when the native build or the raw
    (non-gzip) IDX files are unavailable.
    """
    names = {
        "train_x": ("train-images-idx3-ubyte", 0), "train_y": ("train-labels-idx1-ubyte", 0),
        "test_x": ("t10k-images-idx3-ubyte", 0), "test_y": ("t10k-labels-idx1-ubyte", 0),
    }

    def find(base):
        for suffix in ("", ".gz"):
            p = os.path.join(data_dir, base + suffix)
            if os.path.exists(p):
                return p
        return None

    paths = {k: find(base) for k, (base, _) in names.items()}
    if all(paths.values()):
        def imgs(p):
            x = _read_idx(p).astype(np.float32) / 255.0
            return x.reshape(len(x), -1) if flat else x[..., None]
        train = None
        if (native_train_batch and flat
                and not paths["train_x"].endswith(".gz")
                and not paths["train_y"].endswith(".gz")):
            from dtf_tpu.data.native_loader import NativeDataset
            train = NativeDataset.from_idx(
                paths["train_x"], paths["train_y"],
                batch_size=native_train_batch, seed=seed)
            # Multi-process SPMD requires every process to build IDENTICAL
            # global batches (see module docstring).  The native loader's
            # shuffle stream differs from numpy's, so a per-host build/file
            # failure would silently desynchronize the batch streams.  Use
            # native only if EVERY process succeeded; otherwise all fall
            # back together.
            import jax
            if jax.process_count() > 1:
                import numpy as _np
                from jax.experimental import multihost_utils
                ok = _np.asarray([1 if train is not None else 0], _np.int32)
                all_ok = _np.asarray(multihost_utils.process_allgather(ok))
                if not all_ok.all():
                    if train is not None:
                        train.close()
                    train = None
        if train is None:
            train = Dataset(imgs(paths["train_x"]),
                            _one_hot(_read_idx(paths["train_y"]), 10), seed)
        test = Dataset(imgs(paths["test_x"]), _one_hot(_read_idx(paths["test_y"]), 10), seed)
        return DataSplits(train, test, synthetic=False)

    shape = (784,) if flat else (28, 28, 1)
    xtr, ytr = _synthetic_classification(12800, shape, 10, seed, split_seed=0)
    xte, yte = _synthetic_classification(2560, shape, 10, seed, split_seed=1)
    return DataSplits(Dataset(xtr, ytr, seed), Dataset(xte, yte, seed), synthetic=True)


def load_cifar10(data_dir: str = "cifar-10-batches-py", seed: int = 1) -> DataSplits:
    """CIFAR-10 (32x32x3) from the standard pickle batches if present, else
    synthetic with identical shapes."""
    import pickle

    def batch_files():
        return ([os.path.join(data_dir, f"data_batch_{i}") for i in range(1, 6)],
                os.path.join(data_dir, "test_batch"))

    train_files, test_file = batch_files()
    if all(os.path.exists(p) for p in train_files) and os.path.exists(test_file):
        def load(files):
            xs, ys = [], []
            for p in files if isinstance(files, list) else [files]:
                with open(p, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(np.asarray(d[b"data"], np.float32) / 255.0)
                ys.append(np.asarray(d[b"labels"]))
            x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return np.ascontiguousarray(x), _one_hot(np.concatenate(ys), 10)
        xtr, ytr = load(train_files)
        xte, yte = load(test_file)
        return DataSplits(Dataset(xtr, ytr, seed), Dataset(xte, yte, seed), synthetic=False)

    xtr, ytr = _synthetic_classification(12800, (32, 32, 3), 10, seed, split_seed=0)
    xte, yte = _synthetic_classification(2560, (32, 32, 3), 10, seed, split_seed=1)
    return DataSplits(Dataset(xtr, ytr, seed), Dataset(xte, yte, seed), synthetic=True)


def synthetic_text(n_seqs: int, seq_len: int, vocab_size: int,
                   seed: int = 1) -> np.ndarray:
    """Deterministic token streams for LM pretraining benchmarks (BERT-base
    config, BASELINE.md).  Markov-ish so masked-LM has learnable structure."""
    rng = np.random.default_rng(seed)
    # Each token depends on the previous via a sparse transition table.
    trans = rng.integers(0, vocab_size, (vocab_size, 4))
    toks = np.empty((n_seqs, seq_len), np.int32)
    toks[:, 0] = rng.integers(0, vocab_size, n_seqs)
    for t in range(1, seq_len):
        choice = rng.integers(0, 4, n_seqs)
        follow = trans[toks[:, t - 1], choice]
        noise = rng.integers(0, vocab_size, n_seqs)
        use_noise = rng.random(n_seqs) < 0.1
        toks[:, t] = np.where(use_noise, noise, follow)
    return toks
