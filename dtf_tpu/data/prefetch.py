"""Async device-prefetch input pipeline: take host data time off the hot path.

The trainer's serial data path pays fetch -> chaos poison -> sharded
``device_put`` on the main thread every step, so the device idles for the
full host round-trip between dispatches (the goodput "data" bucket books
it, but booking is not fixing).  :class:`DevicePrefetcher` moves that work
to a background producer thread that runs ahead of the training loop into
a bounded queue of *device-resident* batches — classic double buffering
(``--prefetch 2`` default; input-pipeline overlap was a top bottleneck in
scaling MLPerf models on TPU-v3 pods, PAPERS.md arxiv 1909.09756).

Contracts the wrapper must not break (and tests/test_prefetch.py proves):

* **Exact trajectory.**  The producer calls ``produce(step)`` for steps
  ``start_step, start_step+1, ...`` in order; ``produce`` owns fetch,
  chaos poisoning and device placement keyed by that step index, so batch
  bytes and order are bitwise-identical to the serial path (the per-step
  rng is folded from the same step index by the consumer and never moves).
* **Errors surface at the consuming step.**  A ``produce(step)`` failure
  (loader crash, ``RetryExhausted``) is queued *as* step ``step``'s item
  and re-raised by :meth:`get` when the loop reaches that step — never
  earlier, never from the wrong thread.
* **Bounded production.**  The producer stops after ``num_batches`` items
  (the trainer computes exactly how many steps this fit will consume), so
  a completed fit leaves the underlying dataset cursor exactly where the
  serial path would.  Only an *early* exit (preemption, crash) can leave
  up to ``depth`` produced-but-unconsumed batches; :meth:`close` reports
  that overrun so the caller can warn that the dataset object is no
  longer positionally aligned (a fresh dataset + ``--resume`` — the
  canonical restart path — is always exact).
* **Honest goodput.**  The producer thread books nothing (its wall-clock
  overlaps the step pipeline); the consumer books "data" time only while
  it actually blocks on an empty queue, under a ``data/prefetch_stall``
  span, and publishes queue occupancy as the ``data/prefetch_depth``
  gauge — so the report shows true residual input cost, not overlapped
  work.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

from dtf_tpu import telemetry as tel


class DevicePrefetcher:
    """Run ``produce(step)`` for ``num_batches`` steps ahead of the
    consumer on a daemon thread, ``depth`` device batches deep.

    ``produce(step) -> device batch`` runs entirely on the producer
    thread; it must be self-contained (fetch + poison + device_put) and
    keyed by the global step so faults and rng stay step-aligned.
    """

    def __init__(self, produce: Callable[[int], Any], *,
                 start_step: int, num_batches: int, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if num_batches < 0:
            raise ValueError(f"num_batches must be >= 0, got {num_batches}")
        self._produce = produce
        self._start = start_step
        self._n = num_batches
        self._depth = depth
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._next = start_step            # next step the consumer may get
        self.produced = 0                  # dataset batches consumed upstream
        self.delivered = 0                 # batches handed to the loop
        self._thread: Optional[threading.Thread] = None
        # Cumulative consumer-blocked seconds, initialized so the
        # instrument always lands in telemetry.json when prefetch ran —
        # 0.0 is the best possible reading ("input fully overlapped"),
        # and an absent row is indistinguishable from "never measured".
        tel.gauge("data/prefetch_stall_s").add(0.0)
        if num_batches > 0:
            self._thread = threading.Thread(
                target=self._run, name="dtf-device-prefetch", daemon=True)
            self._thread.start()

    # -- producer -----------------------------------------------------------

    def _run(self) -> None:
        step, end = self._start, self._start + self._n
        while step < end and not self._stop.is_set():
            try:
                item = (step, self._produce(step), None)
                self.produced += 1
            except BaseException as exc:   # delivered, not swallowed
                item = (step, None, exc)
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.05)
                    break
                except queue.Full:
                    continue
            if item[2] is not None:
                return   # terminal: the error IS step `step`'s batch
            step += 1

    # -- consumer -----------------------------------------------------------

    def get(self, step: int) -> Any:
        """The device batch for ``step`` (must be the next step in order).
        Blocks when the producer is behind — that wait, and only that
        wait, books as goodput "data" time under ``data/prefetch_stall``.
        Re-raises the producer's error at the step that would have
        consumed the failed batch."""
        if step != self._next:
            raise RuntimeError(
                f"prefetch consumed out of order: expected step "
                f"{self._next}, got {step} (the prefetcher serves the "
                f"exact serial batch order)")
        tel.gauge("data/prefetch_depth").set(self._q.qsize())
        if self._q.empty():
            _t0 = time.perf_counter()
            with tel.span("data/prefetch_stall", step=step), \
                    tel.get_tracker().measure("data"):
                item = self._wait()
            tel.gauge("data/prefetch_stall_s").add(
                time.perf_counter() - _t0)
        else:
            item = self._wait()
        got_step, batch, exc = item
        if got_step != step:               # cannot happen unless _run broke
            raise RuntimeError(
                f"prefetch queue misaligned: wanted step {step}, "
                f"queue held {got_step}")
        if exc is not None:
            raise exc
        self._next += 1
        self.delivered += 1
        return batch

    def _wait(self) -> tuple:
        while True:
            try:
                return self._q.get(timeout=1.0)
            except queue.Empty:
                if self._thread is None or not self._thread.is_alive():
                    raise RuntimeError(
                        "prefetch producer thread died without delivering "
                        f"step {self._next}") from None

    @property
    def overrun(self) -> int:
        """Batches the producer pulled from the dataset that the loop never
        consumed (> 0 only after an early exit; a completed fit is 0)."""
        return self.produced - self.delivered

    def close(self, timeout_s: float = 10.0) -> int:
        """Stop the producer, drain the queue, join the thread.  Safe on
        every exit path (completion, preemption, crash); idempotent.
        Returns the overrun (see :attr:`overrun`).

        Bounded: a producer wedged inside a foreign call (a dead native
        loader, a hung device transfer) cannot be interrupted from here —
        after ``timeout_s`` the daemon thread is abandoned to process
        teardown (and the trainer's hang watchdog owns the true-hang
        verdict) rather than letting close() hang a crash path's
        finally block."""
        self._stop.set()
        if self._thread is not None:
            # Drain so a producer blocked on a full queue observes _stop.
            deadline = time.monotonic() + timeout_s
            while self._thread.is_alive() and time.monotonic() < deadline:
                try:
                    while True:
                        self._q.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.2)
            if self._thread.is_alive():
                import logging
                logging.getLogger("dtf_tpu").warning(
                    "prefetch producer did not stop within %.0fs; "
                    "abandoning the daemon thread", timeout_s)
        return self.overrun
