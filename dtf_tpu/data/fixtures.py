"""Real-format dataset fixture writers.

The reference trained on real MNIST bytes (IDX files read by
``input_data.read_data_sets``, tf_distributed.py:27-28).  This image has
zero egress, so the real datasets cannot be downloaded — but the FORMATS
can still be exercised end to end: these writers emit deterministic
synthetic data in the genuine on-disk formats (IDX for MNIST, the python
pickle batches for CIFAR-10), so ``load_mnist``/``load_cifar10`` take their
real-bytes parsing path (magic numbers, big-endian dims, gzip variants,
uint8 -> float scaling) instead of the in-memory fallback.  Drop real
dataset files in the same directories and nothing changes but the bytes.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from dtf_tpu.data.datasets import _synthetic_classification


def _to_uint8_images(x: np.ndarray) -> np.ndarray:
    """[0,1] float -> uint8 pixel bytes."""
    return np.clip(np.round(x * 255.0), 0, 255).astype(np.uint8)


def write_mnist_idx(data_dir: str, n_train: int = 4096, n_test: int = 1024,
                    seed: int = 1, compress: bool = False,
                    **task_kw) -> None:
    """Write train/test image+label IDX files (optionally .gz) into
    ``data_dir`` using the exact header layout of the published files
    (magic 0x803 for rank-3 images, 0x801 for rank-1 labels, big-endian
    dims).  ``task_kw`` forwards to ``_synthetic_classification`` (e.g.
    ``spread=0.09`` for the BASELINE stress row)."""
    os.makedirs(data_dir, exist_ok=True)

    def dump(path, arr, magic):
        op = gzip.open if compress else open
        with op(path + (".gz" if compress else ""), "wb") as f:
            f.write(struct.pack(">I", magic))
            f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
            f.write(arr.tobytes())

    for split, n, split_seed in (("train", n_train, 0), ("t10k", n_test, 1)):
        x, y1h = _synthetic_classification(n, (28, 28), 10, seed,
                                           split_seed=split_seed, **task_kw)
        imgs = _to_uint8_images(x)
        labels = np.argmax(y1h, axis=1).astype(np.uint8)
        dump(os.path.join(data_dir, f"{split}-images-idx3-ubyte"),
             imgs, 0x803)
        dump(os.path.join(data_dir, f"{split}-labels-idx1-ubyte"),
             labels, 0x801)


def write_cifar_batches(data_dir: str, n_per_batch: int = 800,
                        n_test: int = 800, seed: int = 1,
                        **task_kw) -> None:
    """Write data_batch_1..5 + test_batch pickles into ``data_dir`` in the
    published CIFAR-10 python layout (dict with b"data" (N, 3072) uint8
    row-major RGB planes and b"labels")."""
    os.makedirs(data_dir, exist_ok=True)

    def dump(path, x, y):
        # (N, 32, 32, 3) [0,1] -> (N, 3072) uint8 channel-planar
        planar = _to_uint8_images(x).transpose(0, 3, 1, 2).reshape(len(x), -1)
        with open(path, "wb") as f:
            pickle.dump({b"data": planar, b"labels": y.tolist()}, f)

    for i in range(1, 6):
        x, y1h = _synthetic_classification(n_per_batch, (32, 32, 3), 10,
                                           seed, split_seed=i * 10,
                                           **task_kw)
        dump(os.path.join(data_dir, f"data_batch_{i}"), x,
             np.argmax(y1h, axis=1))
    x, y1h = _synthetic_classification(n_test, (32, 32, 3), 10, seed,
                                       split_seed=99, **task_kw)
    dump(os.path.join(data_dir, "test_batch"), x, np.argmax(y1h, axis=1))
