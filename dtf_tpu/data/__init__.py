from dtf_tpu.data.datasets import Dataset, DataSplits, load_mnist, load_cifar10, synthetic_text  # noqa: F401
from dtf_tpu.data.prefetch import DevicePrefetcher  # noqa: F401
