"""Self-tuning control plane (DESIGN.md §9): the runtime knob registry,
the SLO-driven knob controller with safety rails, and the standard
serving-knob wiring.  Jax-free by construction — everything here is
host-side bookkeeping on the engine-iteration cadence."""

from dtf_tpu.control.controller import (KnobController,  # noqa: F401
                                        default_policy)
from dtf_tpu.control.knobs import Knob, KnobRegistry  # noqa: F401
from dtf_tpu.control.wire import (arm_controller,  # noqa: F401
                                  wire_serve_knobs)

__all__ = ["Knob", "KnobRegistry", "KnobController", "default_policy",
           "wire_serve_knobs", "arm_controller"]
