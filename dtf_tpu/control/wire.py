"""Standard serving-knob registrations: one place where the engine,
scheduler, brownout controller and speculative drafter expose their
tunables to the control plane.

Bounds/quanta are deliberately conservative: each knob's pinned default
is whatever the engine was CONSTRUCTED with (the operating point the
operator chose), and the controller may walk at most one quantum per
decision inside a range that every subsystem tolerates — e.g. the
brownout ratio knobs' ranges are disjoint (exit <= 0.7 < 0.8 <= enter),
so no sequence of audited mutations can violate the hysteresis
invariant ``0 < exit < enter`` the BrownoutController's constructor
enforces.
"""

from __future__ import annotations

from dtf_tpu.control.knobs import KnobRegistry


def wire_serve_knobs(registry: KnobRegistry, engine) -> KnobRegistry:
    """Register the serving tunables on ``registry`` with
    apply-callbacks into ``engine`` (a :class:`~dtf_tpu.serve.engine.
    ServingEngine`).  Defaults pin to the engine's constructed values.
    Returns the registry for chaining."""
    sched = engine.scheduler
    registry.register(
        "spec_k", lo=0, hi=8, quantum=1, max_step=1,
        default=engine.spec_k, cooldown_iters=16,
        apply=lambda v: setattr(engine, "spec_k", int(v)))
    registry.register(
        "prefill_token_budget",
        lo=max(engine.block_size, 16), hi=8192,
        quantum=max(engine.block_size, 16),
        max_step=2 * max(engine.block_size, 16),
        default=sched.prefill_token_budget, cooldown_iters=16,
        apply=lambda v: setattr(sched, "prefill_token_budget", int(v)))
    registry.register(
        "aging_s", lo=0.25, hi=8.0, quantum=0.25, max_step=0.5,
        default=min(max(sched.aging_s, 0.25), 8.0), cooldown_iters=32,
        apply=lambda v: setattr(sched, "aging_s", float(v)))
    if engine.brownout is not None:
        b = engine.brownout
        registry.register(
            "brownout_enter_ratio", lo=0.8, hi=2.0, quantum=0.05,
            max_step=0.1,
            default=min(max(b.enter_ratio, 0.8), 2.0),
            cooldown_iters=32,
            apply=lambda v: setattr(b, "enter_ratio", float(v)))
        registry.register(
            "brownout_exit_ratio", lo=0.2, hi=0.7, quantum=0.05,
            max_step=0.1,
            default=min(max(b.exit_ratio, 0.2), 0.7),
            cooldown_iters=32,
            apply=lambda v: setattr(b, "exit_ratio", float(v)))
        registry.register(
            "degrade_max_new", lo=2, hi=64, quantum=2, max_step=4,
            default=min(max(b.degrade_max_new, 2), 64),
            cooldown_iters=16,
            apply=lambda v: setattr(b, "degrade_max_new", int(v)))
    return registry


def arm_controller(engine, *, policy=None, **controller_kwargs):
    """Build the full control plane for a serving engine: registry +
    standard knob wiring + :class:`~dtf_tpu.control.controller.
    KnobController` reading the engine's own SLO monitor / brownout /
    spec counters, attached so ``engine.step()`` drives the loop.
    Returns the controller.  The engine must carry a BurnRateMonitor
    (``slo=``) — the controller's objective is the SLO."""
    from dtf_tpu.control.controller import KnobController, default_policy
    registry = KnobRegistry()
    wire_serve_knobs(registry, engine)
    ctl = KnobController(
        registry, slo=engine.slo, brownout=engine.brownout,
        acceptance_fn=lambda: (engine.spec_proposed,
                               engine.spec_accepted),
        policy=policy or default_policy, **controller_kwargs)
    engine.controller = ctl
    return ctl
