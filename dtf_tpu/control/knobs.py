"""Runtime knob registry: every tunable, settable mid-run, through ONE
audited path.

A :class:`Knob` is a declared tunable — name, bounds, step quantum,
pinned default, an apply-callback that pushes the value into the owning
subsystem (engine attribute, scheduler field, brownout threshold) — and
:class:`KnobRegistry` is the single mutation path: :meth:`~KnobRegistry.
set` clamps to bounds, quantizes to the knob's quantum, bounds the
per-decision step size, enforces the per-knob cooldown, applies the
callback, and books the mutation (``control/sets_total`` + the per-knob
``control/knob_*`` gauge + a ``control/set`` instant carrying
knob/old/new/reason/actor) — all under one lock, so a concurrent
``/controlz`` or ``/statz`` scrape never reads a knob value without its
matching audit entry (the same torn-pair discipline the engine's shed
booking uses).

:meth:`~KnobRegistry.reset_to_defaults` is the safety-rail primitive:
snap every knob back to its pinned default, bypassing cooldowns (a
safety action must never be rate-limited by the policy it is undoing),
idempotent (already-at-default knobs book nothing).

Deliberately jax-free and engine-agnostic: apply callbacks are plain
callables, so the registry works identically under the seeded
VirtualClock (the scenario cells' determinism) and on a live server.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from dtf_tpu import telemetry as tel


class Knob:
    """One declared tunable.  ``quantum`` is the resolution every value
    snaps to (anchored at ``lo``); ``max_step`` bounds how far a single
    decision may move the value (safety rail: a runaway policy cannot
    teleport a knob across its range); ``cooldown_iters`` is the minimum
    engine-iteration gap between accepted mutations."""

    __slots__ = ("name", "lo", "hi", "quantum", "max_step", "default",
                 "apply", "cooldown_iters", "value", "last_set_iteration")

    def __init__(self, name: str, *, lo: float, hi: float, quantum: float,
                 default: float, apply: Callable[[float], None],
                 max_step: Optional[float] = None,
                 cooldown_iters: int = 0):
        if not lo <= default <= hi:
            raise ValueError(f"knob {name!r}: default {default} outside "
                             f"bounds [{lo}, {hi}]")
        if quantum <= 0:
            raise ValueError(f"knob {name!r}: quantum must be > 0, got "
                             f"{quantum}")
        self.name = name
        self.lo = float(lo)
        self.hi = float(hi)
        self.quantum = float(quantum)
        self.max_step = (float(max_step) if max_step is not None
                         else self.quantum)
        self.default = float(default)
        self.apply = apply
        self.cooldown_iters = int(cooldown_iters)
        self.value = float(default)
        self.last_set_iteration: Optional[int] = None

    def snap(self, v: float) -> float:
        """Clamp to bounds and quantize (round to the nearest multiple
        of ``quantum`` anchored at ``lo``)."""
        v = min(max(float(v), self.lo), self.hi)
        steps = round((v - self.lo) / self.quantum)
        return min(max(self.lo + steps * self.quantum, self.lo), self.hi)


#: Audit-trail capacity: bounded so a long-lived server's /controlz
#: payload stays scrape-sized (every mutation ALSO lands in the span
#: file as a control/set instant, which is the unbounded record).
AUDIT_CAPACITY = 256


class KnobRegistry:
    """See module docstring.  Thread-safe: the engine thread sets, admin
    handler threads snapshot."""

    def __init__(self):
        self._lock = threading.RLock()
        self._knobs: Dict[str, Knob] = {}
        self.audit: Deque[dict] = deque(maxlen=AUDIT_CAPACITY)

    def register(self, name: str, *, lo: float, hi: float, quantum: float,
                 default: float, apply: Callable[[float], None],
                 max_step: Optional[float] = None,
                 cooldown_iters: int = 0) -> Knob:
        """Declare a tunable.  The per-knob gauge registers eagerly so
        the knob is visible in telemetry (at its default) from the
        moment it exists, not from its first mutation."""
        with self._lock:
            if name in self._knobs:
                raise ValueError(f"knob {name!r} already registered")
            knob = Knob(name, lo=lo, hi=hi, quantum=quantum,
                        default=default, apply=apply, max_step=max_step,
                        cooldown_iters=cooldown_iters)
            self._knobs[name] = knob
            tel.gauge(f"control/knob_{name}").set(knob.value)
            return knob

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._knobs

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._knobs)

    def get(self, name: str) -> float:
        with self._lock:
            return self._knobs[name].value

    # -- the ONE mutation path ----------------------------------------------

    def set(self, name: str, value: float, *, iteration: int,
            reason: str, actor: str = "controller",
            bypass_rails: bool = False) -> Optional[Tuple[float, float]]:
        """Audited mutation.  Returns ``(old, new)`` when the value
        actually changed, ``None`` when the proposal was refused
        (cooldown) or collapsed to a no-op (already at the target after
        clamp/quantize).  ``bypass_rails`` is for safety actions
        (rollback): skips cooldown and the max_step clamp — undoing a
        bad excursion must never be rate-limited by the rails that
        failed to prevent it."""
        with self._lock:
            knob = self._knobs.get(name)
            if knob is None:
                raise ValueError(f"unknown knob {name!r}; one of "
                                 f"{sorted(self._knobs)}")
            if (not bypass_rails and knob.cooldown_iters > 0
                    and knob.last_set_iteration is not None
                    and iteration - knob.last_set_iteration
                    < knob.cooldown_iters):
                tel.counter("control/cooldown_skips_total").inc()
                return None
            target = knob.snap(value)
            if not bypass_rails and abs(target - knob.value) \
                    > knob.max_step + 1e-12:
                step = knob.max_step if target > knob.value \
                    else -knob.max_step
                target = knob.snap(knob.value + step)
                tel.counter("control/clamped_total").inc()
            if target == knob.value:
                return None
            old = knob.value
            knob.value = target
            knob.last_set_iteration = int(iteration)
            knob.apply(target)
            entry = {"iteration": int(iteration), "knob": name,
                     "old": old, "new": target, "reason": reason,
                     "actor": actor}
            self.audit.append(entry)
            # gauge + counter + instant as ONE group under the registry
            # lock: a concurrent /statz scrape must never see the new
            # knob value without its booked mutation (or vice versa)
            with tel.get_registry().locked():
                tel.counter("control/sets_total").inc()
                tel.gauge(f"control/knob_{name}").set(target)
            tel.instant("control/set", **entry)
            return old, target

    def nudge(self, name: str, delta: float, *, iteration: int,
              reason: str, actor: str = "controller"
              ) -> Optional[Tuple[float, float]]:
        """Relative mutation — the controller's native verb."""
        with self._lock:
            knob = self._knobs.get(name)
            if knob is None:
                raise ValueError(f"unknown knob {name!r}; one of "
                                 f"{sorted(self._knobs)}")
            return self.set(name, knob.value + delta,
                            iteration=iteration, reason=reason,
                            actor=actor)

    def reset_to_defaults(self, *, iteration: int, reason: str,
                          actor: str = "controller") -> List[str]:
        """Snap every knob back to its pinned default (the safety-rail
        snap-back).  Idempotent: knobs already at default book nothing;
        returns the names that actually moved."""
        moved = []
        with self._lock:
            for name, knob in sorted(self._knobs.items()):
                if knob.value != knob.default:
                    res = self.set(name, knob.default,
                                   iteration=iteration,
                                   reason=f"rollback:{reason}",
                                   actor=actor, bypass_rails=True)
                    if res is not None:
                        moved.append(name)
        return moved

    def at_defaults(self) -> bool:
        with self._lock:
            return all(k.value == k.default
                       for k in self._knobs.values())

    # -- consistent reads ----------------------------------------------------

    def snapshot(self) -> dict:
        """One consistent cut: every knob's (value, default, bounds,
        cooldown state) plus the bounded audit trail — taken under the
        registry lock, so no ``set`` can tear a knob value from its
        audit entry mid-scrape."""
        with self._lock:
            return {
                "knobs": {
                    name: {"value": k.value, "default": k.default,
                           "lo": k.lo, "hi": k.hi,
                           "quantum": k.quantum,
                           "max_step": k.max_step,
                           "cooldown_iters": k.cooldown_iters,
                           "last_set_iteration": k.last_set_iteration}
                    for name, k in sorted(self._knobs.items())},
                "at_defaults": all(k.value == k.default
                                   for k in self._knobs.values()),
                "audit": list(self.audit),
            }
