"""The SLO-driven knob controller: rule-based policies with hysteresis,
wrapped in safety rails.

Closes the observe->act loop PRs 9-14 left open: the brownout ladder,
SLO burn alerts, KV-pool gauges and speculative-acceptance counters all
existed, but a human still turned the dials.  :class:`KnobController`
runs on the engine-iteration cadence (jax-free — one host-side method
call per iteration, decisions every ``period`` iterations), reads ONE
consistent cut of its inputs (knob registry snapshot + SLO burn state +
brownout state + the ``serve/kv_*`` / queue gauges, the gauge reads
under the metric-registry lock), and actuates through the
:class:`~dtf_tpu.control.knobs.KnobRegistry`'s single audited path.

The default policy is deliberately boring — small hysteretic rules, one
quantum per decision:

* raise ``spec_k`` while draft acceptance is high (or unprobed) and
  there is latency pressure to spend it on; lower it when acceptance
  collapses (the verify premium stops paying);
* widen ``prefill_token_budget`` under queue pressure while the KV pool
  has room; shrink it under pool pressure or fast burn;
* cheapen brownout-degraded answers (``degrade_max_new``) while burn is
  high and the ladder is engaged; restore when calm;
* engage the brownout earlier (``brownout_enter_ratio`` down) under
  sustained slow burn; relax back toward the default when quiet.

Safety rails (the headline robustness property):

* per-decision step sizes and per-knob cooldowns are enforced by the
  registry, not trusted to the policy;
* **fast-burn guard** — a NEW fast-burn alert (the monitor's
  edge-triggered alert count advancing) while knobs are off their
  pinned defaults snaps every knob back (``control/rollback_total`` +
  a ``control/rollback`` instant).  Edge-triggered on purpose: an
  alert that was already firing BEFORE any knob moved is background
  load the policy should fight, not evidence against the knobs — only
  an alert that arrives after a mutation indicts it;
* **no-improvement guard** — each decision records the pre-decision
  SLO bad-event fraction; if, ``improve_window`` iterations later, the
  post-decision window's bad fraction got WORSE by more than
  ``improve_margin``, the decision is judged harmful and everything
  snaps back.  An injected always-worsening policy therefore rolls the
  system back to its pinned operating point within one window (pinned
  by tests/test_control.py — the falsifiability half of "self-tuning");
* after any rollback the controller holds off (``hold_iters``) before
  proposing again, so a persistently hostile environment degenerates to
  the pinned-knob baseline instead of thrashing.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from dtf_tpu import telemetry as tel
from dtf_tpu.control.knobs import KnobRegistry

#: A policy maps a signal dict to [(knob, delta, reason), ...].
Policy = Callable[[dict, dict], List[Tuple[str, float, str]]]


def default_policy(signals: dict, knobs: dict
                   ) -> List[Tuple[str, float, str]]:
    """The rule table above.  ``knobs`` is the registry snapshot's knob
    map (value/default/quantum per name); rules propose at most one
    quantum each — the registry's max_step clamp is the rail, this is
    just the polite default."""
    props: List[Tuple[str, float, str]] = []
    fast = signals.get("fast_burn_max", 0.0)
    slow = signals.get("slow_burn_max", 0.0)
    kv = signals.get("kv_frac", 0.0)
    queue = signals.get("queue_depth", 0.0)
    level = signals.get("brownout_level", 0)
    acc = signals.get("spec_acceptance")     # None until first proposals
    pressure = queue > 0 or fast >= 0.5 or slow >= 0.5

    k = knobs.get("spec_k")
    if k is not None:
        if (k["value"] < k["hi"] and pressure
                and (acc is None or acc >= 0.5)):
            props.append(("spec_k", +k["quantum"],
                          "probe" if acc is None else "accept_high"))
        elif k["value"] > 0 and acc is not None and acc < 0.2:
            props.append(("spec_k", -k["quantum"], "accept_low"))

    k = knobs.get("prefill_token_budget")
    if k is not None:
        if kv > 0.85 or fast >= 1.0:
            if k["value"] > k["lo"]:
                props.append(("prefill_token_budget", -k["quantum"],
                              "kv_pressure" if kv > 0.85 else "fast_burn"))
        elif queue > signals.get("slots", 4) and kv < 0.6 \
                and k["value"] < k["hi"]:
            props.append(("prefill_token_budget", +k["quantum"],
                          "queue_pressure"))

    k = knobs.get("degrade_max_new")
    if k is not None:
        if level >= 1 and slow >= 1.0 and k["value"] > k["lo"]:
            props.append(("degrade_max_new", -k["quantum"],
                          "brownout_cheapen"))
        elif level == 0 and slow < 0.25 and k["value"] < k["default"]:
            props.append(("degrade_max_new", +k["quantum"], "recover"))

    k = knobs.get("brownout_enter_ratio")
    if k is not None:
        if slow >= 2.0 and k["value"] > k["lo"]:
            props.append(("brownout_enter_ratio", -k["quantum"],
                          "sustained_burn"))
        elif slow < 0.25 and k["value"] < k["default"]:
            props.append(("brownout_enter_ratio", +k["quantum"], "relax"))
    return props


class KnobController:
    """See module docstring.  ``slo`` is a :class:`~dtf_tpu.telemetry.
    slo.BurnRateMonitor` (required — the controller's objective IS the
    SLO), ``brownout`` a :class:`~dtf_tpu.serve.brownout.
    BrownoutController` or None, ``acceptance_fn`` an optional callable
    returning cumulative ``(proposed, accepted)`` draft counts (the
    engine's spec counters)."""

    def __init__(self, registry: KnobRegistry, *, slo,
                 brownout=None,
                 acceptance_fn: Optional[Callable[[], Tuple[int, int]]]
                 = None,
                 policy: Policy = default_policy,
                 period: int = 8, improve_window: int = 32,
                 improve_margin: float = 0.10, min_window_events: int = 4,
                 hold_iters: int = 64):
        if slo is None:
            raise ValueError("KnobController needs a BurnRateMonitor — "
                             "its objective is the SLO")
        self.registry = registry
        self.slo = slo
        self.brownout = brownout
        self.acceptance_fn = acceptance_fn
        self.policy = policy
        self.period = int(period)
        self.improve_window = int(improve_window)
        self.improve_margin = float(improve_margin)
        self.min_window_events = int(min_window_events)
        self.hold_iters = int(hold_iters)

        self._last_eval: Optional[int] = None
        self._hold_until: Optional[int] = None
        #: fast-alert count at the last decision (edge detector for
        #: rail 1; None until the first sense)
        self._alerts_seen: Optional[int] = None
        #: open decision under the no-improvement guard:
        #: {"iteration", "bad", "events", "bad_frac"} at decision time
        self._pending: Optional[dict] = None
        self.decisions = 0
        self.rollbacks = 0
        self.rollback_reasons: dict = {}
        # rollback_total registers EAGERLY: "armed, zero rollbacks"
        # (counter present at 0) must be distinguishable from
        # "controller never ran" (counter absent) — the
        # --max_control_rollbacks gate fails on absence by design
        tel.counter("control/rollback_total")
        tel.counter("control/decisions_total")
        tel.counter("control/sets_total")

    # -- sensing -------------------------------------------------------------

    def _sense(self) -> dict:
        """One consistent cut of the controller's inputs.  Gauge reads
        group under the metric-registry lock (torn-pair discipline);
        the SLO monitor and brownout controller snapshot under their own
        locks — each source is internally consistent, which is the same
        contract /statz gives scrapers."""
        with tel.get_registry().locked():
            # gauges read None until the engine's first step sets them
            kv = tel.gauge("serve/kv_pool_frac").value or 0.0
            queue = tel.gauge("serve/queue_depth").value or 0.0
            slots = tel.gauge("serve/slots").value or 4.0
        slo_state = self.slo.state()
        bad = events = alerts_fast = 0
        fast_max = slow_max = 0.0
        firing_fast = False
        for obj in slo_state["objectives"].values():
            bad += obj["bad_total"]
            events += obj["events_total"]
            alerts_fast += obj["alerts_fast"]
            firing_fast = firing_fast or obj["firing_fast"]
        # burns from the live gauges the monitor's update() maintains
        with tel.get_registry().locked():
            for name in slo_state["objectives"]:
                for speed in ("fast", "slow"):
                    g = tel.gauge(
                        f"serve/slo_burn_{name}_{speed}").value or 0.0
                    if speed == "fast":
                        fast_max = max(fast_max, g)
                    else:
                        slow_max = max(slow_max, g)
        signals = {"kv_frac": kv, "queue_depth": queue, "slots": slots,
                   "bad_total": bad, "events_total": events,
                   "bad_frac": (bad / events if events else 0.0),
                   "fast_burn_max": fast_max, "slow_burn_max": slow_max,
                   "fast_firing": firing_fast,
                   "alerts_fast": alerts_fast,
                   "brownout_level": (self.brownout.level
                                      if self.brownout is not None
                                      else 0)}
        if self.acceptance_fn is not None:
            proposed, accepted = self.acceptance_fn()
            signals["spec_acceptance"] = (accepted / proposed
                                          if proposed else None)
        else:
            signals["spec_acceptance"] = None
        return signals

    # -- safety rails --------------------------------------------------------

    def _rollback(self, reason: str, iteration: int) -> None:
        moved = self.registry.reset_to_defaults(
            iteration=iteration, reason=reason)
        self.rollbacks += 1
        self.rollback_reasons[reason] = \
            self.rollback_reasons.get(reason, 0) + 1
        tel.counter("control/rollback_total").inc()
        tel.instant("control/rollback", iteration=int(iteration),
                    reason=reason, knobs_restored=sorted(moved))
        self._pending = None
        self._hold_until = iteration + self.hold_iters

    def _check_pending(self, signals: dict, iteration: int) -> bool:
        """The no-improvement guard.  Returns True when it rolled
        back."""
        p = self._pending
        if p is None or iteration - p["iteration"] < self.improve_window:
            return False
        d_events = signals["events_total"] - p["events"]
        if d_events < self.min_window_events:
            # not enough post-decision evidence yet; keep waiting
            return False
        d_bad = signals["bad_total"] - p["bad"]
        frac_after = d_bad / d_events
        if frac_after > p["bad_frac"] + self.improve_margin:
            self._rollback("no_improvement", iteration)
            return True
        self._pending = None          # decision survived its window
        return False

    # -- the loop ------------------------------------------------------------

    def decide(self, now: float, iteration: int) -> None:
        """Called once per engine iteration (the engine's step tail);
        evaluates every ``period`` iterations.  ``now`` rides the
        engine's own clock, so the loop is deterministic under the
        seeded VirtualClock."""
        if (self._last_eval is not None
                and iteration - self._last_eval < self.period):
            return
        self._last_eval = iteration
        signals = self._sense()
        self.decisions += 1
        tel.counter("control/decisions_total").inc()
        # rail 1: a NEW fast-burn alert (edge, not level — see module
        # docstring) while knobs are off their pins
        new_alert = (self._alerts_seen is not None
                     and signals["alerts_fast"] > self._alerts_seen)
        self._alerts_seen = signals["alerts_fast"]
        if new_alert and not self.registry.at_defaults():
            self._rollback("fast_burn", iteration)
            return
        # rail 2: the open decision's improvement window
        if self._check_pending(signals, iteration):
            return
        if self._hold_until is not None \
                and iteration < self._hold_until:
            return                     # post-rollback hold-off
        snap = self.registry.snapshot()
        applied = False
        for knob, delta, reason in self.policy(signals, snap["knobs"]):
            if self.registry.nudge(knob, delta, iteration=iteration,
                                   reason=reason) is not None:
                applied = True
        if applied and self._pending is None:
            self._pending = {"iteration": iteration,
                             "bad": signals["bad_total"],
                             "events": signals["events_total"],
                             "bad_frac": signals["bad_frac"]}

    # -- reporting -----------------------------------------------------------

    def state(self) -> dict:
        """The ``/controlz`` payload: registry snapshot (knobs + audit
        trail) plus the controller's own loop state."""
        doc = self.registry.snapshot()
        doc["controller"] = {
            "period": self.period,
            "improve_window": self.improve_window,
            "decisions": self.decisions,
            "rollbacks": self.rollbacks,
            "rollback_reasons": dict(sorted(
                self.rollback_reasons.items())),
            "pending_decision": self._pending,
            "hold_until": self._hold_until,
        }
        return doc

    def summary(self) -> dict:
        """Compact per-run aggregate for ``engine.summary()`` /
        telemetry.json."""
        snap = self.registry.snapshot()
        return {"decisions": self.decisions,
                "sets": sum(1 for e in snap["audit"]
                            if not e["reason"].startswith("rollback:")),
                "rollbacks": self.rollbacks,
                "rollback_reasons": dict(sorted(
                    self.rollback_reasons.items())),
                "at_defaults": snap["at_defaults"],
                "knobs": {name: k["value"]
                          for name, k in snap["knobs"].items()},
                "knob_defaults": {name: k["default"]
                                  for name, k in snap["knobs"].items()}}
