from dtf_tpu.train.trainer import Trainer, TrainState, make_train_step, put_global_batch  # noqa: F401
from dtf_tpu.train.metrics import MetricLogger  # noqa: F401
