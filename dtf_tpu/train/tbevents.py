"""Dependency-free TensorBoard event-file writer (and reader).

The reference merged `cost`/`accuracy` scalar summaries into the graph and
wrote them to a TensorBoard logdir every step (tf_distributed.py:84-88,97,
111-112).  This module restores that capability TPU-side without depending
on TensorFlow: it emits the TFRecord-framed ``events.out.tfevents.*`` format
directly —

* record framing: ``<Q length, <I masked-crc32c(length), payload,
  <I masked-crc32c(payload)`` (the TFRecord wire format);
* payload: a hand-encoded ``tensorboard.Event`` protobuf holding either the
  ``file_version`` header or ``(wall_time, step, Summary{tag,simple_value})``.

Scalars only — exactly the reference's usage.  Files are readable by any
stock TensorBoard (validated against tensorboard 2.20's EventFileLoader in
tests/test_tbevents.py).  A reader for the same subset is included so runs
can be inspected programmatically without TensorBoard installed.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Iterator, Optional

# ---------------------------------------------------------------- crc32c --

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """TFRecord's rotated+offset crc32c (guards against crc-of-crc)."""
    c = _crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------ protobuf encoding --

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _field_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _field_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _field_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _field_varint(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def _scalar_event(wall_time: float, step: int, name: str,
                  value: float) -> bytes:
    """Event{wall_time=1, step=2, summary=5{value=1{tag=1, simple_value=2}}}"""
    summary_value = (_field_bytes(1, name.encode()) +
                     _field_float(2, float(value)))
    summary = _field_bytes(1, summary_value)
    return (_field_double(1, wall_time) + _field_varint(2, int(step)) +
            _field_bytes(5, summary))


def _version_event(wall_time: float) -> bytes:
    """Event{wall_time=1, file_version=3}: every event file starts with it."""
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


def _node_def(name: str, op: str, inputs=(), device: str = "") -> bytes:
    """NodeDef{name=1, op=2, input=3*, device=4}."""
    out = _field_bytes(1, name.encode()) + _field_bytes(2, op.encode())
    for i in inputs:
        out += _field_bytes(3, i.encode())
    if device:
        out += _field_bytes(4, device.encode())
    return out


def _graph_event(wall_time: float, nodes) -> bytes:
    """Event{wall_time=1, graph_def=4}: the reference wrote its graph once
    at Supervisor startup (tf_distributed.py:97).  ``nodes``: iterable of
    (name, op, inputs) tuples; slash-separated names become TensorBoard's
    graph-tab name scopes.  GraphDef{node=1*, versions=4{producer=1}}."""
    gd = b"".join(_field_bytes(1, _node_def(*n)) for n in nodes)
    gd += _field_bytes(4, _field_varint(1, 22))     # VersionDef.producer
    return _field_double(1, wall_time) + _field_bytes(4, gd)


# ------------------------------------------------------------- the writer --

class TBEventWriter:
    """Append scalar events to ``<logdir>/events.out.tfevents.<ts>.<host>``."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        path = os.path.join(
            logdir,
            f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}")
        self._f = open(path, "ab")
        self.path = path
        self._write(_version_event(time.time()))

    def _write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header + struct.pack("<I", _masked_crc(header)) +
                      payload + struct.pack("<I", _masked_crc(payload)))

    def scalar(self, step: int, name: str, value: float,
               wall_time: Optional[float] = None) -> None:
        self._write(_scalar_event(wall_time or time.time(), step, name,
                                  value))

    def graph(self, nodes, wall_time: Optional[float] = None) -> None:
        """Write a GraphDef event (once, at startup — the reference's
        ``writer.add_graph`` usage).  ``nodes``: [(name, op, inputs)]."""
        self._write(_graph_event(wall_time or time.time(), list(nodes)))

    def graph_from_params(self, params, root: str = "model") -> None:
        """Model-structure graph from a params pytree: every leaf becomes a
        Parameter node under its tree path; interior dicts become name
        scopes; ``root`` gathers the top level.  Enough for TensorBoard's
        graph tab to render the module hierarchy."""
        import jax

        nodes = []
        tops = set()
        for path, leaf in jax.tree_util.tree_leaves_with_path(params):
            parts = [_keystr(p) for p in path]
            name = "/".join([root] + parts)
            shape = "x".join(str(d) for d in getattr(leaf, "shape", ()))
            nodes.append((name, f"Parameter[{shape}]", ()))
            tops.add(f"{root}/{parts[0]}" if parts else name)
        nodes.append((root, "Model", sorted(tops)))
        self.graph(nodes)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


def _keystr(entry) -> str:
    """One pytree path entry -> a name-scope segment."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


# ------------------------------------------------------------- the reader --

def _read_varint(buf: bytes, i: int) -> tuple:
    """Decode one varint at ``buf[i:]`` -> (value, next_index)."""
    v, shift = 0, 0
    while True:
        b = buf[i]; i += 1
        v |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return v, i


def _decode_fields(buf: bytes) -> Iterator[tuple]:
    """Minimal protobuf walk: yields (field_number, wire_type, value)."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
            yield field, wire, v
        elif wire == 1:
            yield field, wire, buf[i:i + 8]; i += 8
        elif wire == 5:
            yield field, wire, buf[i:i + 4]; i += 4
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            yield field, wire, buf[i:i + ln]; i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")


def read_scalars(path: str) -> list:
    """Parse an event file written by :class:`TBEventWriter` (or TensorFlow)
    into ``[(step, tag, value), ...]``, verifying every record's crc.

    A truncated final record (torn tail — e.g. the process was hard-killed
    mid-write by the fail-fast watchdog) is treated as EOF, like stock
    TensorBoard does, so the records already on disk survive post-mortem.
    A crc mismatch on a *complete* record still raises (real corruption).
    """
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return out
            hcrc_bytes = f.read(4)
            if len(hcrc_bytes) < 4:
                return out
            if struct.unpack("<I", hcrc_bytes)[0] != _masked_crc(header):
                raise ValueError("corrupt record header crc")
            (ln,) = struct.unpack("<Q", header)
            payload = f.read(ln)
            pcrc_bytes = f.read(4)
            if len(payload) < ln or len(pcrc_bytes) < 4:
                return out                      # torn tail: stop at EOF
            if struct.unpack("<I", pcrc_bytes)[0] != _masked_crc(payload):
                raise ValueError("corrupt record payload crc")
            step, summary = 0, None
            for field, wire, v in _decode_fields(payload):
                if field == 2 and wire == 0:
                    step = v
                elif field == 5 and wire == 2:
                    summary = v
            if summary is None:
                continue   # file_version header etc.
            for field, wire, sv in _decode_fields(summary):
                if field != 1 or wire != 2:
                    continue
                tag, value = None, None
                for f2, w2, vv in _decode_fields(sv):
                    if f2 == 1 and w2 == 2:
                        tag = vv.decode()
                    elif f2 == 2 and w2 == 5:
                        (value,) = struct.unpack("<f", vv)
                if tag is not None and value is not None:
                    out.append((step, tag, value))
