"""Training driver: jitted sync-DP train step + the reference's epoch loop.

Replaces the reference's L4 layer (Supervisor session + epoch/step loop,
tf_distributed.py:92-131).  Differences by design (SURVEY.md §2.14, §7):

* the step is ONE compiled XLA program over the whole mesh — forward,
  backward, gradient all-reduce and update fused; no per-step host round
  trips for parameters (the reference moved all params+grads over gRPC
  every step, §3.2);
* gradient sync is a psum/pmean over the ``data`` axis.  Two interchangeable
  implementations are provided and tested equal:
  - ``implicit`` (default): ``jit`` + shardings; GSPMD inserts the
    all-reduce from the sharded-batch mean;
  - ``explicit``: ``shard_map`` per-device code calling ``lax.pmean`` — the
    literal "psum data-parallel" form (BASELINE.json north star);
* deterministic: same seed -> same params on every process, same batches,
  same updates (the reference's async PS was nondeterministic by design).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu import optim as optim_lib
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import TrainConfig
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.train.metrics import MetricLogger
from dtf_tpu.utils.timing import StepTimer, block

TrainState = dict  # {"params": pytree, "opt_state": pytree, "step": i32}


def init_state(model, optimizer: optim_lib.Optimizer, seed: int,
               mesh: Mesh, param_shardings: Optional[Any] = None) -> TrainState:
    """Deterministic same-seed init on all processes — the SPMD replacement
    for the reference's chief-runs-init_op + non-chief-polls protocol
    (tf_distributed.py:92-96; SURVEY.md §2.13 'coordinated init')."""
    params = model.init(jax.random.key(seed))
    if param_shardings is None:
        params = sh.replicate(mesh, params)
    else:
        params = jax.tree_util.tree_map(jax.device_put, params, param_shardings)
    opt_state = optimizer.init(params)
    return {"params": params, "opt_state": opt_state,
            "step": sh.replicate(mesh, jnp.zeros((), jnp.int32))}


def put_global_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host global batch onto the mesh, leading dim sharded over the
    data axes.  Single-process: plain device_put.  Multi-process: each
    process holds the same global batch and contributes its addressable
    shards (processes feed disjoint slices by construction since they build
    identical global batches from the same seed)."""
    if jax.process_count() == 1:
        return sh.shard_batch(mesh, batch)

    def put(x):
        x = np.asarray(x)
        sharding = (sh.batch_spec(mesh, x.ndim) if np.ndim(x) > 0
                    else sh.replicate(mesh))
        return jax.make_array_from_process_local_data(sharding, x)
    return jax.tree_util.tree_map(put, batch)


def make_train_step(loss_fn: Callable, optimizer: optim_lib.Optimizer,
                    mesh: Mesh, mode: str = "implicit",
                    donate: bool = True) -> Callable:
    """Build the compiled train step: (state, batch, rng) -> (state, metrics).

    ``loss_fn(params, batch, rng) -> (loss, aux_dict)`` must reduce with
    *means* over the batch dim so both modes agree.
    """

    def grads_and_update(params, opt_state, step, batch, rng, grad_sync):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, rng)
        grads, loss, aux = grad_sync(grads, loss, aux)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optim_lib.apply_updates(params, updates)
        metrics = {"loss": loss, **aux}
        return {"params": params, "opt_state": opt_state, "step": step + 1}, metrics

    if mode == "implicit":
        # Global-batch program; the loss mean over the sharded batch makes
        # GSPMD emit the gradient all-reduce.
        @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
        def step_fn(state, batch, rng):
            return grads_and_update(
                state["params"], state["opt_state"], state["step"], batch, rng,
                grad_sync=lambda g, l, a: (g, l, a))

        return step_fn

    if mode == "explicit":
        # Literal psum data-parallel: per-device code, explicit collectives.
        data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)

        def per_device(state, batch, rng):
            rng = jax.random.fold_in(rng, lax.axis_index(data_axes[0]))

            def sync(grads, loss, aux):
                grads = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, data_axes), grads)
                loss = lax.pmean(loss, data_axes)
                aux = jax.tree_util.tree_map(
                    lambda v: lax.pmean(v, data_axes), aux)
                return grads, loss, aux

            return grads_and_update(state["params"], state["opt_state"],
                                    state["step"], batch, rng, sync)

        batch_p = P(data_axes)
        mapped = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), batch_p, P()), out_specs=(P(), P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    raise ValueError(f"mode must be 'implicit' or 'explicit', got {mode!r}")


def make_eval_fn(model, mesh: Mesh) -> Callable:
    """Batched full-test-set eval (the reference ran the 10k test set in one
    feed_dict pass on every worker, tf_distributed.py:126; here it is a
    jitted sharded forward, coordinator reads the scalar)."""

    @jax.jit
    def eval_batch(params, batch):
        return model.eval_metrics(params, batch)

    def evaluate(params, dataset, batch_size: int = 2048) -> dict:
        n = (dataset.num_examples // batch_size) or 1
        bs = min(batch_size, dataset.num_examples)
        totals = None
        for i in range(n):
            batch = (dataset.images[i * bs:(i + 1) * bs],
                     dataset.labels[i * bs:(i + 1) * bs])
            m = eval_batch(params, put_global_batch(mesh, batch))
            totals = m if totals is None else jax.tree_util.tree_map(
                jnp.add, totals, m)
        return {k: float(v) / n for k, v in totals.items()}

    return evaluate


@dataclasses.dataclass
class Trainer:
    """The reference's training cycle (tf_distributed.py:100-128), driven by
    a compiled step."""

    cluster: Cluster
    model: Any
    optimizer: optim_lib.Optimizer
    cfg: TrainConfig
    mode: str = "implicit"
    logger: Optional[MetricLogger] = None

    def __post_init__(self):
        mesh = self.cluster.mesh
        self.logger = self.logger or MetricLogger(
            self.cfg.logdir, self.cluster.is_coordinator)
        self.step_fn = make_train_step(self.model.loss, self.optimizer, mesh,
                                       mode=self.mode)
        self.eval_fn = make_eval_fn(self.model, mesh)
        self.state = init_state(self.model, self.optimizer, self.cfg.seed, mesh)
        self.ckpt = None
        if self.cfg.checkpoint_every > 0 or self.cfg.resume:
            from dtf_tpu.train.checkpoint import CheckpointManager
            self.ckpt = CheckpointManager(
                f"{self.cfg.logdir}/checkpoints")
            if self.cfg.resume:
                self.state, step = self.ckpt.restore(self.state)
                if step is not None:
                    self.logger.print(f"[dtf_tpu] resumed from step {step}")
        # Host-side mirror of state["step"]: reading the device scalar every
        # step would sync the async dispatch pipeline.
        self._host_step = int(self.state["step"])

    @property
    def global_batch_size(self) -> int:
        if self.cfg.per_device_batch:
            return self.cfg.per_device_batch * self.cluster.num_devices
        return self.cfg.batch_size

    def fit(self, splits, epochs: Optional[int] = None) -> dict:
        """Epoch loop with the reference's exact console contract."""
        mesh = self.cluster.mesh
        cfg = self.cfg
        epochs = epochs if epochs is not None else cfg.epochs
        rng = jax.random.key(cfg.seed + 17)
        bs = self.global_batch_size
        timer = StepTimer()
        last_cost = float("nan")

        for epoch in range(epochs):
            batch_count = splits.train.num_examples // bs   # :104
            count = 0
            for i in range(batch_count):
                batch = put_global_batch(mesh, splits.train.next_batch(bs))
                rng, step_rng = jax.random.split(rng)
                self.state, metrics = self.step_fn(self.state, batch, step_rng)
                count += 1
                self._host_step += 1
                if (self.ckpt is not None and self.cfg.checkpoint_every > 0
                        and self._host_step % self.cfg.checkpoint_every == 0):
                    self.ckpt.save(self._host_step, self.state)
                if count % cfg.log_frequency == 0 or i + 1 == batch_count:
                    # Sync point: read back the metrics (the reference paid
                    # this every step via sess.run; we pay it only when
                    # logging).
                    cost = float(metrics["loss"])
                    step = int(self.state["step"])
                    avg_ms = timer.window_avg_ms(count)
                    self.logger.step_line(step, epoch + 1, i + 1, batch_count,
                                          cost, avg_ms)
                    self.logger.scalar(step, "cost", cost)
                    self.logger.scalar(step, "avg_ms", avg_ms)
                    count = 0
                    last_cost = cost
            ev = self.eval_fn(self.state["params"], splits.test)
            self.logger.epoch_summary(ev["accuracy"], timer.total_s(), last_cost)
            self.logger.scalar(int(self.state["step"]), "test_accuracy",
                               ev["accuracy"])
        block(self.state)
        if self.ckpt is not None:
            if (self.cfg.checkpoint_every > 0
                    and self.ckpt.latest_step() != self._host_step):
                self.ckpt.save(self._host_step, self.state, force=True)
            self.ckpt.wait()
        return {"test_accuracy": ev["accuracy"], "final_cost": last_cost,
                "steps": int(self.state["step"]), "total_s": timer.total_s()}
