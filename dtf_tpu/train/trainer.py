"""Training driver: jitted sync-DP train step + the reference's epoch loop.

Replaces the reference's L4 layer (Supervisor session + epoch/step loop,
tf_distributed.py:92-131).  Differences by design (SURVEY.md §2.14, §7):

* the step is ONE compiled XLA program over the whole mesh — forward,
  backward, gradient all-reduce and update fused; no per-step host round
  trips for parameters (the reference moved all params+grads over gRPC
  every step, §3.2);
* gradient sync is a psum/pmean over the ``data`` axis.  Two interchangeable
  implementations are provided and tested equal:
  - ``implicit`` (default): ``jit`` + shardings; GSPMD inserts the
    all-reduce from the sharded-batch mean;
  - ``explicit``: ``shard_map`` per-device code calling ``lax.pmean`` — the
    literal "psum data-parallel" form (BASELINE.json north star);
* deterministic: same seed -> same params on every process, same batches,
  same updates (the reference's async PS was nondeterministic by design).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu import optim as optim_lib
from dtf_tpu import telemetry as tel
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import TrainConfig
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.train.metrics import MetricLogger
from dtf_tpu.utils.timing import StepTimer, block

TrainState = dict  # {"params": pytree, "opt_state": pytree, "step": i32}


class TrainingDiverged(RuntimeError):
    """Persistent non-finite loss/gradients the in-step guard could not
    heal: ``bad_step_limit`` consecutive skipped steps with no checkpoint
    to roll back to, or the rollback budget spent.  Deterministic by
    construction — batches and rng are keyed by the global step, and the
    rollback already retried from the last good checkpoint — so an outer
    restart replays the identical divergence: the supervisor
    (resilience/supervisor.classify_exit) fails fast on it instead of
    consuming its restart budget in an unwinnable loop."""

    no_restart = True


def init_state(model, optimizer: optim_lib.Optimizer, seed: int,
               mesh: Mesh, param_shardings: Optional[Any] = None,
               guard: bool = False,
               grad_sync: Optional[Any] = None) -> TrainState:
    """Deterministic same-seed init on all processes — the SPMD replacement
    for the reference's chief-runs-init_op + non-chief-polls protocol
    (tf_distributed.py:92-96; SURVEY.md §2.13 'coordinated init').

    Models exposing ``init_model_state()`` (e.g. BatchNorm running stats in
    ResNet) get a ``model_state`` entry threaded through the train step.

    ``grad_sync``: a prepared :class:`~dtf_tpu.parallel.grad_sync.
    GradSyncEngine` routes the optimizer state through the partition-aware
    init — the moments are born SHARDED over the data axis (1/N HBM per
    device) instead of replicated.
    """
    params = model.init(jax.random.key(seed))
    if param_shardings is None:
        params = sh.replicate(mesh, params)
    else:
        params = jax.tree_util.tree_map(jax.device_put, params, param_shardings)
    if grad_sync is not None:
        opt_state = grad_sync.init_opt_state(params)
    else:
        opt_state = optimizer.init(params)
    # Per-param leaves (m/v/...) inherit the params' committed shardings,
    # but fresh scalar leaves (e.g. adam's step counter) are uncommitted
    # single-device arrays — a checkpoint restore would pin them to device
    # 0 (the template's sharding) and poison the next step_fn call with
    # mixed device sets.  Commit every uncommitted leaf as mesh-replicated.
    rep = sh.replicate(mesh)
    opt_state = jax.tree_util.tree_map(
        lambda x: x if getattr(x, "committed", False)
        else jax.device_put(x, rep), opt_state)
    state = {"params": params, "opt_state": opt_state,
             "step": sh.replicate(mesh, jnp.zeros((), jnp.int32))}
    if guard:
        # Non-finite-guard counters (replicated i32 scalars): total updates
        # skipped, and the current consecutive-bad streak the rollback
        # policy watches.  Present iff the step was built with guard=True
        # so unguarded states keep their seed pytree structure.
        state["skipped"] = sh.replicate(mesh, jnp.zeros((), jnp.int32))
        state["bad_streak"] = sh.replicate(mesh, jnp.zeros((), jnp.int32))
    if hasattr(model, "init_model_state"):
        state["model_state"] = sh.replicate(mesh, model.init_model_state())
    return state


def put_global_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host global batch onto the mesh, leading dim sharded over the
    data axes.  Single-process: plain device_put.  Multi-process: each
    process holds the same global batch and contributes its addressable
    shards (processes feed disjoint slices by construction since they build
    identical global batches from the same seed)."""
    data_size = sh.data_axis_size(mesh)
    for x in jax.tree_util.tree_leaves(batch):
        if np.ndim(x) > 0 and x.shape[0] % data_size:
            raise ValueError(
                f"global batch dim {x.shape[0]} is not divisible by the "
                f"mesh's data-axis size {data_size}; pick --batch_size as "
                f"a multiple of {data_size}, or use --per_device_batch "
                f"(global = per_device x devices by construction)")
    if jax.process_count() == 1:
        return sh.shard_batch(mesh, batch)

    def put(x):
        x = np.asarray(x)
        sharding = (sh.batch_spec(mesh, x.ndim) if np.ndim(x) > 0
                    else sh.replicate(mesh))
        return jax.make_array_from_process_local_data(sharding, x)
    return jax.tree_util.tree_map(put, batch)


def put_process_batch(mesh: Mesh, local_batch: Any) -> Any:
    """True multi-host data loading: each process contributes ITS OWN
    disjoint slice of the global batch (leading dim = global/process_count)
    instead of redundantly materializing the whole global batch everywhere
    (:func:`put_global_batch`'s identical-batches contract).  Rank-0 leaves
    are replicated from the local value (callers must pass identical
    scalars).  Pair with :meth:`dtf_tpu.data.datasets.Dataset.shard` so
    each host reads only its partition.

    Assumes the data axis tiles the processes (process k's addressable
    devices hold a contiguous 1/nproc of the batch dim — the default
    device order for a leading ``data`` axis); the local leading dim must
    be divisible by this process's share of the data-axis size."""
    nproc = jax.process_count()
    if nproc == 1:
        # local == global by definition; keep single-process placement
        # policy in exactly one place.
        return put_global_batch(mesh, local_batch)
    data_size = sh.data_axis_size(mesh)
    if data_size % nproc:
        raise ValueError(
            f"put_process_batch requires the data axis (size {data_size}) "
            f"to tile the {nproc} processes (each process owns "
            f"data_size/nproc contiguous shards); re-factor the mesh or "
            f"use put_global_batch")
    local_share = data_size // nproc
    for x in jax.tree_util.tree_leaves(local_batch):
        if np.ndim(x) > 0 and np.shape(x)[0] % local_share:
            raise ValueError(
                f"local batch dim {np.shape(x)[0]} is not divisible by "
                f"this process's share of the data axis "
                f"({data_size}/{nproc} = {local_share}); pick a local "
                f"batch that is a multiple of {local_share}")

    def put(x):
        x = np.asarray(x)
        if x.ndim == 0:
            return jax.make_array_from_process_local_data(
                sh.replicate(mesh), x)
        sharding = sh.batch_spec(mesh, x.ndim)
        global_shape = (x.shape[0] * nproc, *x.shape[1:])
        return jax.make_array_from_process_local_data(sharding, x,
                                                      global_shape)
    return jax.tree_util.tree_map(put, local_batch)


def make_train_step(loss_fn: Callable, optimizer: optim_lib.Optimizer,
                    mesh: Mesh, mode: str = "implicit",
                    donate: bool = True, stateful: bool = False,
                    grad_accum: int = 1,
                    grad_compression: Optional[str] = None,
                    grads_fn: Optional[Callable] = None,
                    guard: bool = False,
                    grad_sync: Optional[Any] = None,
                    grad_comm_dtype: Optional[str] = None,
                    quant_rounding: str = "nearest") -> Callable:
    """Build the compiled train step: (state, batch, rng) -> (state, metrics).

    ``guard=True`` adds the in-step non-finite guard (DESIGN.md §5): an
    isfinite scan over the loss and every gradient leaf, all-reduced across
    the data axes (computed BEFORE gradient sync so int8-compressed rings
    can't launder a NaN into finite garbage, then pmean'd in explicit mode
    so every device takes the same branch).  A bad step runs the update
    under ``lax.cond``'s skip branch — params, optimizer state and model
    state pass through untouched — and bumps the replicated ``skipped`` /
    ``bad_streak`` counters in the state (``init_state(guard=True)``).
    Metrics gain ``nonfinite`` (this step's flag), ``skipped_total`` and
    ``bad_streak``; the trainer's rollback policy reads them at its
    logging sync points, never per step.

    ``loss_fn(params, batch, rng) -> (loss, aux_dict)`` must reduce with
    *means* over the batch dim so both modes agree.  With ``stateful=True``
    the signature is ``loss_fn(params, model_state, batch, rng) ->
    (loss, (aux_dict, new_model_state))`` and the state threads through
    ``state["model_state"]``.

    ``grad_accum > 1`` splits the batch's leading dim into that many
    microbatches inside the compiled step (``lax.scan``), averaging
    gradients/metrics before the single optimizer update — activation
    memory scales with the microbatch.  For rng-independent stateless
    losses the optimization trajectory is identical to the full batch
    (grad of a mean == mean of microbatch grads); losses that consume the
    rng (e.g. MLM masking, dropout) see per-microbatch ``fold_in`` streams,
    and stateful models compute per-microbatch batch statistics, so those
    match the full-batch step only in expectation.  Stateful models thread
    their running statistics through the microbatches sequentially.

    BatchNorm semantics differ between modes by construction: in implicit
    mode the batch mean over the data-sharded axis is a *global* mean (GSPMD
    all-reduces it), i.e. synchronized BN; in explicit (shard_map) mode each
    shard normalizes with its *local* batch statistics (the classic
    non-sync-BN data-parallel semantics) and the running stats are pmean'd
    across shards.  The two converge as per-shard batch grows.
    """

    if grads_fn is not None and (mode != "implicit" or stateful):
        raise ValueError(
            "grads_fn (a model that produces its own gradients, e.g. the "
            "1F1B pipeline schedule) requires implicit mode and a "
            "stateless model — the schedule owns the backward pass")
    if grad_compression not in (None, "int8"):
        raise ValueError(f"grad_compression must be None or 'int8', got "
                         f"{grad_compression!r}")
    if grad_compression and mode != "explicit":
        raise ValueError("grad_compression requires mode='explicit' (the "
                         "quantized ring is a hand-scheduled collective; "
                         "GSPMD owns the collectives in implicit mode)")
    if grad_compression and len(sh.data_axes(mesh)) != 1:
        raise ValueError(
            f"grad_compression='int8' runs its ring over a single data "
            f"axis; mesh has data axes {sh.data_axes(mesh)}")
    if grad_sync is not None:
        # grad_sync is a prepared GradSyncEngine (zero1 / zero1_overlap):
        # the reduce-scatter + sharded update + all-gather is hand-
        # scheduled per-device code, so it lives in the explicit step.
        if mode != "explicit":
            raise ValueError(
                "grad_sync zero1/zero1_overlap is a hand-scheduled "
                "shard_map schedule; it requires mode='explicit' (the "
                "Trainer auto-switches)")
        if grad_compression:
            raise ValueError(
                "grad_sync zero1 and grad_compression='int8' are both "
                "gradient wire formats; pick one (zero1 composes with "
                "--grad_comm_dtype bf16 instead)")
        if grads_fn is not None:
            raise ValueError("grad_sync zero1 requires jax.grad-produced "
                             "gradients (no custom grads_fn schedules)")
    if grad_comm_dtype is not None:
        if mode != "explicit":
            raise ValueError(
                "grad_comm_dtype changes the collective wire format; that "
                "requires mode='explicit' (GSPMD owns the collectives in "
                "implicit mode)")
        if grad_compression:
            raise ValueError("grad_comm_dtype and grad_compression='int8' "
                             "are both wire formats; pick one")
    # The engine owns its comm dtype (set at construction); the flag here
    # only drives the dense explicit pmean path.  "int8" resolves to the
    # block-scaled wire (parallel/quantize.py), not a cast.
    from dtf_tpu.parallel.grad_sync import comm_dtype_of
    from dtf_tpu.parallel.quantize import check_rounding
    _dense_comm_dtype = (comm_dtype_of(grad_comm_dtype)
                         if grad_sync is None else None)
    check_rounding(quant_rounding)
    # Decorrelate quantization draws from the loss/dropout stream: the
    # quant rng is a constant-salted fold of the (already per-device)
    # step rng, and the microbatch/bucket indices fold in downstream.
    _QSALT = 0x51_8008

    def value_and_grads(params, model_state, batch, rng):
        if stateful:
            (loss, (aux, new_ms)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, model_state, batch, rng)
        else:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, rng)
            new_ms = None
        return loss, aux, new_ms, grads

    # zero1_overlap: each microbatch's bucket gradients reduce-scatter
    # IMMEDIATELY inside the accumulation scan, so bucket i's collective
    # is independent of microbatch i+1's backward and the scheduler can
    # overlap them (on TPU, arm --xla_overlap so it actually does).  The
    # accumulator then holds 1/N-size mean shards instead of full
    # gradients — N× less accumulator HBM as a side effect.
    overlap_stage = None
    if (grad_sync is not None and grad_sync.strategy == "zero1_overlap"
            and grad_accum > 1):
        # (grads, mb_rng) -> mean shards; the per-microbatch rng seeds
        # stochastic rounding so no two microbatches share a draw.
        overlap_stage = lambda g, r: grad_sync.scatter(
            g, jax.random.fold_in(r, _QSALT))

    def accumulated(step_of_mb, model_state, batch, rng):
        """THE grad-accumulation skeleton, shared by the value_and_grad
        and custom-grads_fn paths: ``step_of_mb(ms, mb, rng) -> (loss,
        aux, new_ms, grads)`` runs per microbatch; gradients accumulate
        in FLOAT32 regardless of param dtype (bf16 summation rounds away
        small contributions as grad_accum grows).  With ``overlap_stage``
        the per-microbatch gradients are reduce-scatter'd to mean shards
        before accumulation (sum of per-microbatch means == mean of the
        summed gradients, so the trajectory is unchanged up to float
        association).

        Strided split (microbatch i = rows i::grad_accum): each device's
        contiguous data-sharded rows contribute equally to every
        microbatch, so the split is a local slice — a contiguous split
        would misalign microbatches with the batch sharding and make
        GSPMD reshard inside the step.  Equally correct: the loss is a
        mean, so microbatch membership doesn't matter.
        """
        micro = jax.tree_util.tree_map(
            lambda x: jnp.moveaxis(
                x.reshape(x.shape[0] // grad_accum, grad_accum,
                          *x.shape[1:]), 1, 0), batch)
        f32 = lambda t: jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), t)

        def body(carry, inp):
            g_sum, l_sum, aux_sum, ms = carry
            i, mb = inp
            mb_rng = jax.random.fold_in(rng, i)
            loss, aux, new_ms, grads = step_of_mb(ms, mb, mb_rng)
            if overlap_stage is not None:
                grads = overlap_stage(grads, mb_rng)
            g_sum = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), g_sum, grads)
            aux_sum = jax.tree_util.tree_map(jnp.add, aux_sum, aux)
            return (g_sum, l_sum + loss, aux_sum, new_ms), None

        first = jax.tree_util.tree_map(lambda x: x[0], micro)
        rng0 = jax.random.fold_in(rng, 0)
        loss0, aux0, ms0, grads0 = step_of_mb(model_state, first, rng0)
        if overlap_stage is not None:
            grads0 = overlap_stage(grads0, rng0)
        rest = jax.tree_util.tree_map(lambda x: x[1:], micro)
        (g_sum, l_sum, aux_sum, ms), _ = lax.scan(
            body, (f32(grads0), loss0, aux0, ms0),
            (jnp.arange(1, grad_accum), rest))
        inv = 1.0 / grad_accum
        scale = lambda t: jax.tree_util.tree_map(lambda x: x * inv, t)
        return l_sum * inv, scale(aux_sum), ms, scale(g_sum)

    def grads_and_update(state, batch, rng, sync):
        params, opt_state, step = state["params"], state["opt_state"], state["step"]
        model_state = state.get("model_state")
        if grads_fn is not None:
            if grad_accum > 1:
                # the schedule owns each microbatch's backward; the
                # accumulation happens OUTSIDE it (mean of per-microbatch
                # grads == grads of the mean loss)
                def gf_step(ms, mb, r):
                    loss, aux, grads = grads_fn(params, mb, r)
                    return loss, aux, ms, grads
                loss, aux, _, grads = accumulated(
                    gf_step, None, batch, rng)
            else:
                loss, aux, grads = grads_fn(params, batch, rng)
            new_ms = None
        elif grad_accum > 1:
            loss, aux, new_ms, grads = accumulated(
                lambda ms, mb, r: value_and_grads(params, ms, mb, r),
                model_state, batch, rng)
        else:
            loss, aux, new_ms, grads = value_and_grads(
                params, model_state, batch, rng)
        ok = None
        if guard:
            # Pre-sync isfinite: a NaN here is still a NaN (an int8-
            # quantized ring could turn it into finite garbage on the
            # wire); sync() all-reduces the verdict in explicit mode.
            ok = jnp.isfinite(loss)
            for g in jax.tree_util.tree_leaves(grads):
                ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
        grads, loss, aux, new_ms, ok = sync(grads, loss, aux, new_ms, ok)
        qerr = None
        if guard:
            if grad_sync is not None:
                # zero1: the collectives are FUSED with the update
                # (reduce-scatter -> shard update -> all-gather), and
                # collectives inside a lax.cond branch are off the table —
                # so compute unconditionally and where-select against the
                # old values.  A bad step pays the (wasted) comm, but bad
                # steps are the rare path and the semantics match dense's
                # skip exactly: params/opt state/model state pass through.
                up_params, up_opt, qerr = grad_sync.sync_and_update(
                    grads, opt_state, params,
                    prescattered=overlap_stage is not None,
                    rng=jax.random.fold_in(rng, _QSALT))
                sel = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(ok, a, b), new, old)
                new_params = sel(up_params, params)
                new_opt = sel(up_opt, opt_state)
                kept_ms = (sel(new_ms, model_state) if stateful else ())
            else:
                def apply_update(_):
                    updates, new_opt = optimizer.update(grads, opt_state,
                                                        params)
                    return (optim_lib.apply_updates(params, updates),
                            new_opt, new_ms if stateful else ())

                def skip_update(_):
                    # Skip semantics: values pass through untouched —
                    # including model_state, whose "new" batch statistics
                    # came from the same poisoned batch as the gradients.
                    return (params, opt_state,
                            model_state if stateful else ())

                new_params, new_opt, kept_ms = lax.cond(
                    ok, apply_update, skip_update, None)
            bad = 1 - ok.astype(jnp.int32)
            skipped = state["skipped"] + bad
            streak = (state["bad_streak"] + 1) * bad  # +1 if bad else reset
            new_state = {"params": new_params, "opt_state": new_opt,
                         "step": step + 1, "skipped": skipped,
                         "bad_streak": streak}
            if stateful:
                new_state["model_state"] = kept_ms
            metrics = {"loss": loss, "nonfinite": bad,
                       "skipped_total": skipped, "bad_streak": streak, **aux}
            if qerr is not None:
                metrics["quant_error"] = qerr
            return new_state, metrics
        if grad_sync is not None:
            params, opt_state, qerr = grad_sync.sync_and_update(
                grads, opt_state, params,
                prescattered=overlap_stage is not None,
                rng=jax.random.fold_in(rng, _QSALT))
        else:
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optim_lib.apply_updates(params, updates)
        new_state = {"params": params, "opt_state": opt_state, "step": step + 1}
        if stateful:
            new_state["model_state"] = new_ms
        metrics = {"loss": loss, **aux}
        if qerr is not None:
            metrics["quant_error"] = qerr
        return new_state, metrics

    if mode == "implicit":
        # Global-batch program; the loss mean over the sharded batch makes
        # GSPMD emit the gradient all-reduce.
        @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
        def step_fn(state, batch, rng):
            # Global-batch program: loss/grads (and the guard verdict) are
            # already global values; sync is the identity.
            return grads_and_update(
                state, batch, rng,
                sync=lambda g, l, a, ms, ok: (g, l, a, ms, ok))

        return step_fn

    if mode == "explicit":
        # Literal psum data-parallel: per-device code, explicit collectives.
        # Params stay fully replicated in this mode, so a mesh with model
        # axes (fsdp/tensor/pipe/expert/...) would silently degrade to
        # replicated compute — reject it up front (README: "Implicit vs
        # explicit mode").
        model_axes = [a for a in mesh.axis_names
                      if a != "data" and mesh.shape[a] > 1]
        if model_axes:
            raise ValueError(
                f"mode='explicit' is data-parallel only (params replicated "
                f"under shard_map); mesh axes {model_axes} require the "
                f"implicit (GSPMD) mode")
        data_axes = sh.data_axes(mesh)

        def per_device(state, batch, rng):
            rng = jax.random.fold_in(rng, lax.axis_index(data_axes[0]))

            def sync(grads, loss, aux, new_ms, ok):
                pmean = lambda t: jax.tree_util.tree_map(
                    lambda v: lax.pmean(v, data_axes), t)
                if ok is not None:
                    # All devices must take the SAME cond branch or params
                    # diverge across replicas: all-reduce the local verdict
                    # (mean of {0,1} flags == 1.0 iff every shard is clean).
                    ok = lax.pmean(ok.astype(jnp.float32), data_axes) == 1.0
                if grad_sync is not None:
                    # zero1: gradients stay LOCAL here — the engine fuses
                    # their reduce-scatter with the sharded update
                    # (grads_and_update calls sync_and_update).
                    g = grads
                elif grad_compression == "int8":
                    # int8-wire ring all-reduce for the bandwidth-heavy
                    # gradients; scalars stay exact.  (Single data axis
                    # validated at make_train_step entry.)
                    from dtf_tpu.parallel.collectives import (
                        quantized_ring_all_reduce_mean)
                    g = jax.tree_util.tree_map(
                        lambda v: quantized_ring_all_reduce_mean(
                            v, data_axes[0]), grads)
                elif _dense_comm_dtype in ("int8", "int8_ring"):
                    # Block-scaled int8 wire for the DENSE strategy
                    # (parallel/quantize.py): quantized reduce-scatter +
                    # quantized all-gather over the whole flattened tree,
                    # mean-preserving 1/N pre-scale, two roundings per
                    # value ("int8_ring" schedules the scatter as the
                    # per-hop requantizing segmented ring instead — n-1
                    # roundings, (n-1)/n the wire).  The local encode
                    # error psums into the replica-uniform quant_error
                    # metric.
                    from dtf_tpu.parallel import quantize as qz
                    g, qe = qz.all_reduce_mean_quantized(
                        grads, data_axes[0], rounding=quant_rounding,
                        rng=jax.random.fold_in(rng, _QSALT),
                        ring=_dense_comm_dtype == "int8_ring")
                    aux = dict(aux)
                    aux["quant_error"] = qz.error_ratio(
                        lax.psum(qe, data_axes[0]))
                elif _dense_comm_dtype is not None:
                    # Reduced-precision wire for the dense strategy:
                    # psum of (g/N).astype(bf16) — the 1/N pre-scaling is
                    # mean-preserving (the wire sum IS the mean; no second
                    # rounding from a post-divide).
                    inv = 1.0 / sh.data_axis_size(mesh)
                    g = jax.tree_util.tree_map(
                        lambda v: lax.psum(
                            (v * inv).astype(_dense_comm_dtype),
                            data_axes).astype(v.dtype), grads)
                else:
                    g = pmean(grads)
                return (g, pmean(loss), pmean(aux),
                        pmean(new_ms) if new_ms is not None else None, ok)

            return grads_and_update(state, batch, rng, sync)

        batch_p = P(data_axes)
        from dtf_tpu.parallel.collectives import shard_map_fn
        if grad_sync is not None:
            # The sharded optimizer state maps over the data axis; every
            # other state entry is replicated.  The spec tree must mirror
            # the state dict exactly (shard_map prefix matching is
            # per-key for dicts).
            state_spec = {"params": P(), "step": P(),
                          "opt_state": grad_sync.opt_state_spec}
            if guard:
                state_spec["skipped"] = P()
                state_spec["bad_streak"] = P()
            if stateful:
                state_spec["model_state"] = P()
        else:
            state_spec = P()
        mapped = shard_map_fn(
            per_device, mesh=mesh,
            in_specs=(state_spec, batch_p, P()),
            out_specs=(state_spec, P()))
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    raise ValueError(f"mode must be 'implicit' or 'explicit', got {mode!r}")


def make_eval_fn(model, mesh: Mesh, stateful: bool = False) -> Callable:
    """Batched full-test-set eval (the reference ran the 10k test set in one
    feed_dict pass on every worker, tf_distributed.py:126; here it is a
    jitted sharded forward, coordinator reads the scalar).  Takes the full
    TrainState so stateful models evaluate with their running statistics."""

    @jax.jit
    def eval_batch(state, batch):
        if stateful:
            return model.eval_metrics(state["params"], state["model_state"],
                                      batch)
        return model.eval_metrics(state["params"], batch)

    data_size = sh.data_axis_size(mesh)

    def evaluate(state, dataset, batch_size: int = 2048) -> dict:
        """Covers the FULL test set, example-weighted.  Batches are rounded
        down to a multiple of the data-axis device count and run sharded;
        only the sub-``data_size`` tail runs *replicated* (same compute on
        every device, exact result) — one extra compile for its shape,
        once.  Datasets expose sequential rows via ``examples(lo, hi)``
        (any batch pytree the model's eval accepts); the legacy
        ``.images``/``.labels`` pair is a fallback."""
        n_total = dataset.num_examples
        totals, i = None, 0
        while i < n_total:
            take = min(batch_size, n_total - i)
            if take >= data_size:
                take -= take % data_size
            if hasattr(dataset, "examples"):
                batch = dataset.examples(i, i + take)
            else:
                batch = (dataset.images[i:i + take],
                         dataset.labels[i:i + take])
            if take % data_size == 0:
                batch = put_global_batch(mesh, batch)
            elif jax.process_count() == 1:
                batch = sh.replicate(mesh, batch)
            else:
                rep = sh.replicate(mesh)
                batch = jax.tree_util.tree_map(
                    lambda x: jax.make_array_from_process_local_data(
                        rep, np.asarray(x)), batch)
            m = eval_batch(state, batch)
            m = jax.tree_util.tree_map(lambda v: v * take, m)
            totals = m if totals is None else jax.tree_util.tree_map(
                jnp.add, totals, m)
            i += take
        return {k: float(v) / n_total for k, v in totals.items()}

    return evaluate


@dataclasses.dataclass
class Trainer:
    """The reference's training cycle (tf_distributed.py:100-128), driven by
    a compiled step."""

    cluster: Cluster
    model: Any
    optimizer: optim_lib.Optimizer
    cfg: TrainConfig
    mode: str = "implicit"
    grad_compression: Optional[str] = None   # "int8" (explicit mode only)
    logger: Optional[MetricLogger] = None
    # Fault injection: a resilience.chaos.FaultPlan (or a spec string;
    # cfg.chaos is the CLI path).  Pass ONE shared plan object through a
    # supervisor's restart attempts so each fault still fires exactly once
    # across the whole supervised run.
    chaos: Optional[Any] = None

    def __post_init__(self):
        mesh = self.cluster.mesh
        # Telemetry spine: close any supervisor down-window into the
        # restart bucket, bind the span tracer to this run's logdir, and
        # — in a FRESH process resuming an interrupted run — pick up the
        # previous attempt's goodput books plus the dead time since its
        # last telemetry.json write (in-process restarts keep the live
        # tracker; accounted_s()>0 detects that and skips the load).
        tracker = tel.get_tracker()
        tracker.mark_up()
        _t_init = time.perf_counter()
        # Fleet plane (telemetry/fleet.py): --fleet_dir arms it with
        # jax's process identity; a plane the caller configured FIRST
        # (the mp rigs, whose hosts are independent jax processes that
        # all read process_index 0) wins, exactly like their explicit
        # HealthMonitor.
        from dtf_tpu.telemetry import fleet as _fleet
        if self.cfg.fleet_dir and _fleet.get_plane() is None:
            _fleet.configure(self.cfg.fleet_dir, jax.process_index(),
                             jax.process_count(),
                             spans_dir=self.cfg.logdir)
        self._fleet = _fleet.get_plane()
        # Disabled telemetry must UNINSTALL any tracer a previous run in
        # this process configured, or this run's spans would pollute the
        # earlier run's span file.  Under a fleet plane the span stream
        # goes to the SHARED fleet logdir under the plane's host index —
        # cross-host trace merge needs one collection point and real
        # per-host file names (per-process files never interleave).
        _span_dir = (self.cfg.logdir
                     if self.cfg.telemetry and self.cfg.logdir else None)
        _span_proc = jax.process_index()
        if self._fleet is not None:
            _span_proc = self._fleet.process
            if self._fleet.spans_dir and _span_dir:
                _span_dir = self._fleet.spans_dir
        tel.configure(_span_dir, _span_proc)
        # Live introspection window (telemetry/live.py): one admin
        # server per PROCESS life — a supervisor's next attempt rebinds
        # its probe onto the same server, so the operator's curl never
        # drops across restarts.  Coordinator only: simulated multi-host
        # rigs share one machine, and N processes cannot share one port.
        self._admin_probe = None
        if self.cfg.admin_port is not None and jax.process_index() == 0:
            from dtf_tpu.telemetry.live import LivenessProbe, start_admin
            # generous staleness: a training "beat" is one step, and a
            # legitimate first step may spend minutes in compile
            self._admin_probe = LivenessProbe(stale_after_s=600.0)
            _admin = start_admin(self.cfg.admin_port,
                                 probe=self._admin_probe,
                                 fleet_fn=(self._fleet.fleetz
                                           if self._fleet is not None
                                           else None))
            import logging as _logging
            _logging.getLogger("dtf_tpu").info(
                "admin endpoint on http://127.0.0.1:%s "
                "(/statz /healthz /tracez /slo /memz)", _admin.port)
        if (self.cfg.resume and self.cfg.logdir
                and self.cluster.is_coordinator
                and tracker.accounted_s() == 0):
            import json as _json
            import os as _os
            tpath = _os.path.join(self.cfg.logdir, tel.TELEMETRY_FILE)
            if _os.path.exists(tpath):
                try:
                    with open(tpath) as f:
                        doc = _json.load(f)
                    tracker.load_previous(doc)
                    # Lifetime counters (restarts, saves, events) carry
                    # across the relaunch too, or the resumed process's
                    # first snapshot would atomically replace the file
                    # with counts regressed to zero while the goodput
                    # books correctly remember the history.
                    tel.get_registry().load_counters(
                        doc.get("metrics", {}))
                except (OSError, ValueError):
                    pass               # a torn file must not block a resume
        # Checkpoint watermark for the init booking below — sampled AFTER
        # load_previous, whose merged-in previous-run checkpoint_s must
        # not be subtracted from THIS ctor's elapsed time.
        _ck0 = tracker.buckets["checkpoint"]
        # Attempt tag for metrics.csv rows: resumed runs (in-process
        # supervisor restarts AND scheduler-driven --resume relaunches)
        # auto-continue past the file's last recorded attempt; an explicit
        # cfg.attempt from an external scheduler overrides.
        self.logger = self.logger or MetricLogger.for_config(
            self.cfg, self.cluster.is_coordinator)
        # Persistent compile cache (train/compile_cache.py): enabled
        # BEFORE the first trace so this attempt's compiles read/write the
        # shared directory — supervisor restarts and elastic relaunches
        # hit the cache instead of re-paying the backend compile.
        if self.cfg.compile_cache:
            from dtf_tpu.train import compile_cache
            compile_cache.enable(self.cfg.compile_cache)
        self._chaos = self.chaos if self.chaos is not None else self.cfg.chaos
        if isinstance(self._chaos, str):
            from dtf_tpu.resilience.chaos import FaultPlan
            self._chaos = FaultPlan.parse(self._chaos)
        # Incident plane (telemetry/anomaly.py): armed eagerly — a run
        # with zero anomalies books 'armed, zero', never silence.  Fed
        # from the fit loop (step time, checkpoint-save duration).
        from dtf_tpu.telemetry import anomaly as _anomaly
        from dtf_tpu.telemetry import diagnose as _diagnose
        self._anomaly = _anomaly.get_monitor().arm()
        _diagnose.install()
        self._guarded = self.cfg.nonfinite_guard
        self._rollbacks = 0
        stateful = hasattr(self.model, "init_model_state")
        # Models that must produce their own gradients (1F1B pipeline
        # schedules interleave fwd/bwd and cannot be expressed as jax.grad
        # of a forward pass) expose custom_grads_fn.
        grads_fn = getattr(self.model, "custom_grads_fn", None)
        # Sharding planner (parallel/planner.py): --plan auto derives the
        # gradient-path knobs the operator left FREE (strategy, wire
        # dtype, bucket size, remat, activation sharding) from the model
        # template + mesh + HBM budget.  Pinned flags — any knob set away
        # from its TrainConfig default — always win; the planner only
        # fills in the rest.  Infeasible (model, budget) pairs raise
        # PlanInfeasibleError here, BEFORE any compile.
        self._plan = None
        if self.cfg.plan == "auto":
            import dataclasses as _dc
            from dtf_tpu.parallel import planner as _planner
            _defaults = {f.name: f.default
                         for f in _dc.fields(type(self.cfg))}
            pinned = {k: getattr(self.cfg, k)
                      for k in ("grad_sync", "grad_comm_dtype",
                                "grad_bucket_mb", "quant_rounding")
                      if getattr(self.cfg, k) != _defaults.get(k)}
            _mcfg = getattr(self.model, "cfg", None)
            if _mcfg is not None and getattr(_mcfg, "remat", False):
                pinned["remat"] = True
                pinned["remat_policy"] = getattr(_mcfg, "remat_policy",
                                                 "full")
            plan = _planner.make_plan(
                self.model, mesh, batch_size=self.cfg.batch_size,
                hbm_budget_bytes=(self.cfg.plan_hbm_gb * 2.0**30
                                  if self.cfg.plan_hbm_gb else None),
                optimizer=self.optimizer,
                logdir=(self.cfg.logdir
                        if self.cfg.telemetry and self.cfg.logdir
                        else None),
                pinned=pinned)
            self._plan = plan
            self.cfg = _dc.replace(
                self.cfg, grad_sync=plan.grad_sync,
                grad_comm_dtype=plan.grad_comm_dtype,
                grad_bucket_mb=plan.grad_bucket_mb,
                quant_rounding=plan.quant_rounding)
            if _mcfg is not None and hasattr(_mcfg, "remat"):
                _mcfg.remat = plan.remat
                _mcfg.remat_policy = plan.remat_policy
            # Activation sharding constraint (models honoring
            # act_sharding pin the (B, T, D) batch dim to the data axes,
            # suppressing SPMD's involuntary full rematerialization).
            if (_mcfg is not None and hasattr(_mcfg, "act_sharding")
                    and _mcfg.act_sharding is None):
                _mcfg.act_sharding = plan.activation_sharding(mesh)
            import logging as _logging
            _logging.getLogger("dtf_tpu").info(plan.summary())
            if self.cfg.telemetry and self.cfg.logdir:
                # recorded for report --explain's predicted-vs-measured
                # audit after the run captures cost cards
                _planner.write_plan(self.cfg.logdir, plan)
        # Gradient-sync strategy (parallel/grad_sync.py): zero1 strategies
        # are hand-scheduled shard_map code, so they run the explicit step
        # — an implicit-mode request auto-switches rather than failing
        # (the two modes are tested trajectory-equal on data-only meshes).
        self._grad_sync_engine = None
        if self.cfg.grad_sync != "dense":
            from dtf_tpu.parallel.grad_sync import GradSyncEngine
            if self.mode == "implicit":
                self.mode = "explicit"
                import logging as _logging
                _logging.getLogger("dtf_tpu").info(
                    "grad_sync=%s runs the explicit (shard_map) step; "
                    "switching mode implicit -> explicit",
                    self.cfg.grad_sync)
            self._grad_sync_engine = GradSyncEngine(
                self.cfg.grad_sync, self.optimizer, mesh,
                bucket_mb=self.cfg.grad_bucket_mb,
                comm_dtype=self.cfg.grad_comm_dtype,
                quant_rounding=self.cfg.quant_rounding)
            self._grad_sync_engine.prepare(
                jax.eval_shape(self.model.init,
                               jax.random.key(self.cfg.seed)))
        elif self.cfg.grad_comm_dtype and self.mode == "implicit":
            # The reduced-precision wire composes with the DENSE strategy
            # too — but it changes the collective wire format, which only
            # the explicit (shard_map) step owns; same auto-switch as
            # grad_sync instead of a crash at make_train_step.
            self.mode = "explicit"
            import logging as _logging
            _logging.getLogger("dtf_tpu").info(
                "grad_comm_dtype=%s changes the collective wire format; "
                "switching mode implicit -> explicit",
                self.cfg.grad_comm_dtype)
        self.step_fn = make_train_step(self.model.loss, self.optimizer, mesh,
                                       mode=self.mode, stateful=stateful,
                                       grad_accum=self.cfg.grad_accum,
                                       grad_compression=self.grad_compression,
                                       grads_fn=grads_fn,
                                       guard=self._guarded,
                                       grad_sync=self._grad_sync_engine,
                                       grad_comm_dtype=self.cfg.grad_comm_dtype,
                                       quant_rounding=self.cfg.quant_rounding)
        self.eval_fn = make_eval_fn(self.model, mesh, stateful=stateful)
        # Parameter placement from the model's logical axes: FSDP when the
        # mesh has an 'fsdp' axis, tensor/expert/... sharding per the rule
        # table; pure-data meshes resolve every axis to None = replicated
        # (the previous behavior).  Explicit shard_map mode keeps fully
        # replicated params (its per-device code assumes P() params).
        shardings = None
        if self.mode == "implicit":
            rules = (sh.fsdp_rules() if "fsdp" in mesh.axis_names
                     else sh.DEFAULT_RULES)
            try:
                shardings = sh.apply_rules(self.model.axes(), mesh, rules)
            except NotImplementedError:   # model without logical axes
                pass
        self.state = init_state(self.model, self.optimizer, self.cfg.seed,
                                mesh, param_shardings=shardings,
                                guard=self._guarded,
                                grad_sync=self._grad_sync_engine)
        # Gradient-sync observability (telemetry/names.py comm/*): the
        # strategy, the data-axis width, the measured per-device optimizer-
        # state footprint (off the real arrays — the zero1 memory claim is
        # checked, not asserted), and the engine's static wire facts.
        from dtf_tpu.parallel.grad_sync import (STRATEGIES, WIRE_DTYPES,
                                                comm_dtype_of,
                                                opt_state_bytes_per_device,
                                                wire_bytes_per_elem,
                                                wire_dtype_name)
        tel.gauge("comm/strategy_idx").set(
            STRATEGIES.index(self.cfg.grad_sync))
        tel.gauge("comm/wire_dtype_idx").set(WIRE_DTYPES.index(
            wire_dtype_name(comm_dtype_of(self.cfg.grad_comm_dtype))))
        tel.gauge("comm/data_axis_size").set(sh.data_axis_size(mesh))
        tel.gauge("comm/optimizer_state_bytes").set(
            opt_state_bytes_per_device(self.state["opt_state"]))
        if self._grad_sync_engine is not None:
            stats = self._grad_sync_engine.comm_stats(self.cfg.grad_accum)
            tel.gauge("comm/grad_sync_bytes").set(stats["grad_sync_bytes"])
            tel.gauge("comm/wire_bytes").set(stats["wire_bytes"])
            tel.gauge("comm/bucket_count").set(stats["bucket_count"])
            tel.gauge("comm/hops").set(stats["hops"])
        else:
            # Dense: the pmean/all-reduce payload is the full gradient
            # tree at the wire format's bytes-per-element.
            n_elems = int(sum(
                np.prod(l.shape)
                for l in jax.tree_util.tree_leaves(self.state["params"])))
            resolved = comm_dtype_of(self.cfg.grad_comm_dtype)
            n_dev = sh.data_axis_size(mesh)
            if resolved in ("int8", "int8_ring"):
                # all_reduce_mean_quantized ships TWO quantized legs
                # (reduce-scatter + all-gather), each with per-chunk
                # block round-up — mirror zero1's split: wire_bytes is
                # the gradient scatter leg (the ring wire ships n-1
                # chunks instead of n — quantize.ring_wire_elems),
                # grad_sync_bytes adds the gather leg (here quantized
                # too, unlike zero1's f32 param gather; the gather is
                # one-shot on both wires).
                from dtf_tpu.parallel import quantize as qz
                flat = -(-n_elems // n_dev) * n_dev   # _flatten_tree pad
                elems = (qz.ring_wire_elems if resolved == "int8_ring"
                         else qz.wire_elems)
                scatter_leg = float(elems(flat, n_dev)
                                    * qz.WIRE_BYTES_PER_ELEM["int8"])
                gather_leg = float(qz.wire_elems(flat, n_dev)
                                   * qz.WIRE_BYTES_PER_ELEM["int8"])
                tel.gauge("comm/grad_sync_bytes").set(
                    scatter_leg + gather_leg)
                tel.gauge("comm/wire_bytes").set(scatter_leg)
            else:
                wire = float(n_elems) * wire_bytes_per_elem(resolved)
                tel.gauge("comm/grad_sync_bytes").set(wire)
                tel.gauge("comm/wire_bytes").set(wire)
            tel.gauge("comm/bucket_count").set(0)
            tel.gauge("comm/hops").set(
                n_dev - 1 if resolved == "int8_ring" else 1)
        # Planner instruments: 0/absent when --plan is off, so the gate
        # "plan/active == 1" can assert a run actually planned itself.
        if self._plan is not None:
            from dtf_tpu.parallel.planner import PLAN_SOURCES
            tel.gauge("plan/active").set(1)
            tel.gauge("plan/source_idx").set(
                PLAN_SOURCES.index(self._plan.source))
            tel.gauge("plan/predicted_hbm_bytes").set(
                self._plan.predicted_hbm_bytes)
            tel.gauge("plan/predicted_step_ms").set(
                self._plan.predicted_step_ms)
            tel.gauge("plan/hbm_budget_bytes").set(
                self._plan.hbm_budget_bytes)
        # Model-structure graph to TensorBoard, once at startup — the
        # reference's writer.add_graph (tf_distributed.py:97).
        self.logger.graph(self.state["params"],
                          root=type(self.model).__name__)
        # Last train-step metrics (device values; reading defers the sync
        # to the caller) — benchmark drivers report these after fit().
        self.last_metrics: dict = {}
        self.ckpt = None
        if self.cfg.checkpoint_every > 0 or self.cfg.resume:
            from dtf_tpu.train.checkpoint import CheckpointManager
            from dtf_tpu.parallel.grad_sync import (comm_dtype_of,
                                                    wire_dtype_name)
            self.ckpt = CheckpointManager(
                f"{self.cfg.logdir}/checkpoints",
                # Manifests record the weight-update strategy, data-axis
                # width, bucket size AND gradient wire format so
                # restore_robust can see (and log) a dense<->zero1,
                # elastic, or wire-dtype change — post-mortems attribute
                # trajectory deltas to the wire — and so a cross-strategy
                # restore can rebuild the WRITER's bucket layout.  The
                # wire format does NOT affect that layout (block padding
                # lives inside the collective); it is recorded purely for
                # attribution.
                run_meta={"grad_sync": self.cfg.grad_sync,
                          "data_axis": sh.data_axis_size(mesh),
                          "grad_bucket_mb": self.cfg.grad_bucket_mb,
                          # canonical spelling ("f32"|"bf16"|"int8"|
                          # "int8_ring"), so "bfloat16" vs "bf16" can't
                          # fake a wire change in the restore warning
                          "grad_comm_dtype": wire_dtype_name(
                              comm_dtype_of(self.cfg.grad_comm_dtype)),
                          # planned runs additionally record the plan's
                          # provenance, so restore_robust logs a planned
                          # <-> manual (or re-planned) transition
                          **({"plan": self._plan.summary()}
                             if self._plan is not None else {})})
            if self.cfg.resume:
                with tracker.measure("checkpoint"):
                    if self._chaos is not None:
                        # corrupt_ckpt@latest models bit rot / a crash
                        # mid-save discovered only when the restart tries
                        # to restore.
                        self._chaos.maybe_corrupt_latest(self.ckpt)
                    had_steps = self.ckpt.all_steps()
                    try:
                        self.state, step = self.ckpt.restore_robust(
                            self.state)
                    except Exception as exc:
                        from dtf_tpu.train.checkpoint import (
                            CheckpointMismatchError)
                        if not isinstance(exc, CheckpointMismatchError):
                            raise
                        # A verified-intact step that won't restore: the
                        # template mismatch may be a grad_sync strategy
                        # change (dense<->zero1 optimizer-state layouts
                        # differ) — the manifest records the writer's
                        # strategy, so reshard through the other layout
                        # before concluding schema breakage.
                        cross = self._restore_cross_strategy()
                        if cross is not None:
                            self.state, step = cross
                        elif not self._guarded:
                            raise
                        else:
                            # Legacy checkpoints (saved before the guard
                            # existed / with --no-nonfinite_guard) lack the
                            # counter leaves.  Backfill: restore without
                            # them, re-attach the fresh zeros from init —
                            # the trajectory is too valuable to discard
                            # over two scalar counters.
                            legacy = {k: v for k, v in self.state.items()
                                      if k not in ("skipped", "bad_streak")}
                            restored, step = self.ckpt.restore_robust(legacy)
                            if step is None:
                                raise
                            restored["skipped"] = self.state["skipped"]
                            restored["bad_streak"] = self.state["bad_streak"]
                            self.state = restored
                            self.logger.print(
                                f"[dtf_tpu] resumed a pre-guard checkpoint "
                                f"(step {step}); guard counters start at "
                                f"zero")
                if step is not None:
                    self.logger.print(f"[dtf_tpu] resumed from step {step}")
                elif had_steps:
                    # A silent cold start would discard the trajectory the
                    # user explicitly asked to resume (e.g. legacy
                    # checkpoints without manifests that mismatch the
                    # current guard schema).  Deleting the directory is the
                    # intentional way to start over.
                    err = RuntimeError(
                        f"--resume requested but none of checkpoint steps "
                        f"{had_steps} under {self.ckpt.directory} could be "
                        f"restored (corrupt, partial, or saved with a "
                        f"different model/optimizer/nonfinite_guard "
                        f"schema); refusing to silently start fresh — "
                        f"delete the checkpoint directory to start over")
                    # Deterministic: a supervisor restart replays this
                    # identically, so it must not burn the restart budget.
                    err.no_restart = True
                    raise err
        # Host-side mirror of state["step"]: reading the device scalar every
        # step would sync the async dispatch pipeline.
        self._host_step = int(self.state["step"])
        self._profiler = None
        if self.cfg.profile_dir is not None:
            from dtf_tpu.utils.profiling import StepWindowProfiler
            self._profiler = StepWindowProfiler(
                self.cfg.profile_dir, self.cfg.profile_start,
                self.cfg.profile_steps)
        # Armed at fit() start, disarmed in its finally (arming here would
        # let slow pre-fit host work trip a hard exit).
        self._watchdog = None
        # MFU/throughput numerators (telemetry/goodput.py): model FLOPs for
        # one training example and its token count — reported from the
        # logging sync points so every workload (not just the benchmark
        # driver) gets tokens/sec and, when the chip peak is known, MFU.
        self._tokens_per_example = tel.goodput.tokens_per_example(self.model)
        try:
            self._flops_per_example = tel.goodput.train_flops_per_example(
                self.model, self.state["params"])
        except Exception:              # a model without countable params
            self._flops_per_example = None
        try:
            self._peak_flops, _ = tel.goodput.peak_flops_for_model(
                self.model, mesh.devices.flat[0])
        except Exception:
            self._peak_flops = None
        # One compiled-step flag: the FIRST dispatch pays trace+compile
        # synchronously, so its wall time books as "compile", not
        # "productive" (goodput category table).
        self._compile_seen = False
        # AOT warmup (fit() start): .lower().compile() of the train step,
        # so the compile lands in an explicit goodput bucket (and, with
        # --compile_cache, a warm attempt's warmup is a cache read)
        # instead of hiding inside the first step's dispatch.
        self._compiled_step = None
        self._compiled_ok = False      # set after the first successful call
        self._compiled_batch_sig = None
        self._fit_step_call = None     # per-fit dispatch choice (see fit)
        tracker.add("init", max(
            time.perf_counter() - _t_init
            - (tracker.buckets["checkpoint"] - _ck0), 0.0))
        # fit() books the ctor->fit gap (data loading by the caller) so
        # the goodput columns keep summing to wall-clock; the accounted
        # watermark keeps phases booked in between (e.g. the benchmark
        # driver's measured warmup steps) from being counted twice.
        self._ctor_done = time.perf_counter()
        self._ctor_acc = tracker.accounted_s()

    def _restore_cross_strategy(self):
        """Cross-layout checkpoint reshard: restore a checkpoint whose
        manifest records a DIFFERENT optimizer-state layout than this
        run's — a ``--grad_sync`` strategy change (dense<->zero1) or a
        zero1 ``--grad_bucket_mb`` change — by restoring through the
        WRITER's layout (strategy + bucket size from the manifest, never
        this run's assumptions) and converting via the bucket
        flatten/unflatten (parallel/grad_sync.py).  Returns (state,
        step), or None when the mismatch is not a layout change (caller
        keeps its own fallback chain).  zero1 <-> zero1_overlap at the
        same bucket size share a layout and never get here."""
        latest = self.ckpt.latest_step()
        if latest is None:
            return None
        run = self.ckpt.manifest_meta(latest).get("run") or {}
        saved = run.get("grad_sync")
        cur = self.cfg.grad_sync
        if saved is None:
            return None
        saved_dense = saved == "dense"
        cur_dense = cur == "dense"
        saved_mb = run.get("grad_bucket_mb", self.cfg.grad_bucket_mb)
        if saved_dense == cur_dense and (
                saved_dense or saved_mb == self.cfg.grad_bucket_mb):
            # Same layout: not our mismatch.  (A --grad_comm_dtype change
            # is NOT a layout change — block alignment for the int8 wire
            # lives inside the collective, so checkpoints restore across
            # wire dtypes through the ordinary template; restore_robust
            # logs the wire change for trajectory attribution.)
            return None
        mesh = self.cluster.mesh

        def writer_engine():
            from dtf_tpu.parallel.grad_sync import GradSyncEngine
            return GradSyncEngine(
                "zero1", self.optimizer, mesh, bucket_mb=saved_mb).prepare(
                    jax.eval_shape(self.model.init,
                                   jax.random.key(self.cfg.seed)))

        # 1. restore through the WRITER's layout; 2. normalize to dense;
        # 3. re-shard through THIS run's engine if it has one.
        tmpl = dict(self.state)
        if saved_dense:
            dense_opt = self.optimizer.init(self.state["params"])
            rep = sh.replicate(mesh)
            tmpl["opt_state"] = jax.tree_util.tree_map(
                lambda x: x if getattr(x, "committed", False)
                else jax.device_put(x, rep), dense_opt)
            restored, step = self.ckpt.restore_robust(tmpl)
            if step is None:
                return None
            dense_state = restored["opt_state"]
        else:
            eng = writer_engine()
            tmpl["opt_state"] = eng.init_opt_state(self.state["params"])
            restored, step = self.ckpt.restore_robust(tmpl)
            if step is None:
                return None
            dense_state = eng.unshard_opt_state(restored["opt_state"])
        restored["opt_state"] = (
            dense_state if self._grad_sync_engine is None
            else self._grad_sync_engine.shard_opt_state(dense_state))
        self.logger.print(
            f"[dtf_tpu] optimizer state resharded across grad_sync "
            f"layouts: checkpoint step {step} was saved with '{saved}' "
            f"(bucket {saved_mb:g} MB), restored under '{cur}' "
            f"(bucket {self.cfg.grad_bucket_mb:g} MB)")
        return restored, step

    def _print_trace_summary(self, steps_traced: int) -> None:
        from dtf_tpu.utils.profiling import summarize_trace

        try:
            # steps= makes summarize_trace itself normalize to per-step
            # seconds (callers no longer divide by hand).
            rows = summarize_trace(self.cfg.profile_dir, top=10,
                                   steps=steps_traced)
        except Exception as exc:       # a summary must never fail a run
            self.logger.print(f"[trace] summary unavailable: {exc}")
            return
        if not rows:
            # CPU traces have no device "XLA Ops" lane; the summary is a
            # TPU-run tool.
            self.logger.print("[trace] no device-op rows in the trace "
                              "(host-only backend?)")
            return
        # summarize_trace sums over every trace file in the newest run
        # dir — on shared storage that can be several hosts' files; the
        # denominator is this host's traced-step count.
        self.logger.print(
            f"[trace] device-op time per traced step ({steps_traced} "
            f"steps; durations summed over the run dir's trace files):")
        for name, per_step_s in rows:
            self.logger.print(
                f"[trace] {per_step_s * 1e3:9.3f} ms/step  {name}")

    def _suspended_watchdog(self):
        """Disarm the hang watchdog across a legitimately-slow blocking host
        call (eval, checkpoint save); no-op when it isn't armed."""
        import contextlib
        return (self._watchdog.suspend() if self._watchdog is not None
                else contextlib.nullcontext())

    def _rollback_or_fail(self, streak: int) -> None:
        """bad_step_limit consecutive non-finite steps: restore params and
        optimizer state from the last good checkpoint, or raise
        TrainingDiverged when there is nothing to restore / the rollback
        budget is spent.  The step counter and data cursor keep moving
        FORWARD — the bad window's updates were skipped (params untouched),
        so rolling back values while advancing past its batches is the
        standard spike-recovery move and keeps resume bookkeeping exact."""
        why = f"{streak} consecutive non-finite steps"
        if self.ckpt is None:
            raise TrainingDiverged(
                f"{why} and checkpointing is disabled — nothing to roll "
                f"back to (enable --checkpoint_every, or fix the "
                f"instability: lr/clipping/data)")
        if self._rollbacks >= self.cfg.max_rollbacks:
            raise TrainingDiverged(
                f"{why} after {self._rollbacks} rollback(s) — the "
                f"instability persists across restores; failing fast")
        cur_step = self.state["step"]
        cur_skipped = self.state["skipped"]
        with self._suspended_watchdog(), \
                tel.get_tracker().measure("rollback"):
            restored, good_step = self.ckpt.restore_robust(self.state)
        if good_step is None:
            raise TrainingDiverged(f"{why} and no restorable checkpoint")
        tel.counter("checkpoint/rollbacks_total").inc()
        # Values roll back; counters carry forward (eager elementwise ops
        # preserve the replicated sharding of their inputs).
        restored["step"] = cur_step
        restored["skipped"] = cur_skipped
        restored["bad_streak"] = restored["bad_streak"] * 0
        self.state = restored
        self._rollbacks += 1
        self.logger.event(
            int(cur_step), "rollback",
            f"{why}; restored params/opt state from checkpoint step "
            f"{good_step} ({self._rollbacks}/{self.cfg.max_rollbacks} "
            f"rollbacks used)")

    @staticmethod
    def _batch_signature(batch) -> tuple:
        """Shape/dtype signature of a batch pytree — the guard that keeps a
        Compiled train step from being fed a differently-shaped fit."""
        return tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree_util.tree_leaves(batch))

    def _aot_warmup(self, train_split, global_bs: int) -> None:
        """AOT-compile the train step (``.lower().compile()``) before the
        first loop dispatch.  Batch shapes are probed via the dataset's
        ``examples`` accessor (no cursor advance); datasets without one
        (callable/native streams) silently keep compile-on-first-dispatch.
        The compile books into the "compile" goodput bucket and — with
        ``--compile_cache`` — is a disk read on warm attempts, surfacing
        as ``compile/cache_hit``.  Runs while the prefetcher's producer
        fills its queue, so compile and the initial data fill overlap."""
        mesh = self.cluster.mesh
        base = getattr(train_split, "base", train_split)   # ProcessShard
        examples = getattr(base, "examples", None)
        if examples is None:
            return
        try:
            sample = examples(0, min(global_bs, base.num_examples))
        except Exception:
            return                     # probe-hostile dataset: not an error
        def sds(x):
            x = np.asarray(x)
            if x.ndim == 0:
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sh.replicate(mesh))
            return jax.ShapeDtypeStruct((global_bs,) + x.shape[1:], x.dtype,
                                        sharding=sh.batch_spec(mesh, x.ndim))
        batch_sds = jax.tree_util.tree_map(sds, sample)
        rng_like = jax.random.fold_in(jax.random.key(self.cfg.seed + 17),
                                      self._host_step)
        tracker = tel.get_tracker()
        _t0 = time.perf_counter()
        try:
            with tel.span("compile/aot_warmup"), tracker.measure("compile"):
                self._compiled_step = self.step_fn.lower(
                    self.state, batch_sds, rng_like).compile()
        except Exception as exc:       # lowering quirk -> jit path, loudly
            self._compiled_step = None
            self.logger.print(
                f"[dtf_tpu] AOT warmup failed ({type(exc).__name__}: "
                f"{exc}); compiling on first dispatch instead")
            return
        self._compiled_batch_sig = self._batch_signature(batch_sds)
        self._compile_seen = True      # the loop's first step is productive
        tel.gauge("compile/aot_s").set(time.perf_counter() - _t0)
        # Cost observatory (telemetry/costobs.py): the warmup holds the
        # one Compiled object the training hot loop will run — capture
        # its cost/memory analysis as the run's train/step CostCard
        # here, at compile time, so the hot path never pays a read.
        from dtf_tpu.telemetry import costobs
        costobs.observe("train/step", ("aot", global_bs),
                        self._compiled_step)

    def _dispatch_step(self, batch, step_rng):
        """One train-step dispatch: the AOT-compiled executable when its
        input signature matches this fit's batches, else the jit path
        (identical program, identical trajectory).  The signature check
        runs ONCE per fit (the first dispatch) — batch shapes are fixed
        for a whole fit, and this is the hot loop the PR exists to
        shrink.  The FIRST compiled call may be rejected at
        argument-check time (a sharding/layout the lowering didn't
        anticipate): only TypeError/ValueError are retried on the jit
        path, because those are raised by input validation BEFORE
        execution or donation; an execution failure (XlaRuntimeError —
        OOM, interconnect) propagates as-is rather than retrying on
        donated buffers and masking the real error."""
        call = self._fit_step_call
        if call is None:               # first dispatch of this fit
            call = self._compiled_step
            if call is not None and (
                    self._compiled_batch_sig
                    != self._batch_signature(batch)):
                call = None            # a differently-shaped fit: jit path
            call = self.step_fn if call is None else call
            self._fit_step_call = call
        if call is not self.step_fn:
            try:
                out = call(self.state, batch, step_rng)
            except (TypeError, ValueError) as exc:
                if self._compiled_ok:
                    raise              # it worked before: a real error
                self._compiled_step = None
                self._fit_step_call = self.step_fn
                # This retry pays the jit trace+compile the AOT warmup
                # was supposed to cover; the loop books it (and sets
                # compile/first_step_s) off this flag.
                self._compile_seen = False
                self.logger.print(
                    f"[dtf_tpu] AOT-compiled step rejected its inputs "
                    f"({type(exc).__name__}: {exc}); using the jit path")
                return self.step_fn(self.state, batch, step_rng)
            self._compiled_ok = True
            return out
        return self.step_fn(self.state, batch, step_rng)

    @property
    def global_batch_size(self) -> int:
        if self.cfg.per_device_batch:
            return self.cfg.per_device_batch * self.cluster.num_devices
        return self.cfg.batch_size

    def fit(self, splits, epochs: Optional[int] = None,
            max_steps: Optional[int] = None) -> dict:
        """Epoch loop with the reference's exact console contract.

        Resume-correct: the per-step rng is derived by folding the global
        step into a base key (not an advancing stream), and on resume the
        data cursor and epoch budget fast-forward to the restored step, so
        a resumed run continues the interrupted trajectory instead of
        re-feeding consumed batches.

        ``max_steps`` caps total optimizer steps across epochs (the
        benchmark workloads' fixed-step budget).  ``splits.test=None``
        skips evaluation.  Multi-process with ``cfg.shard_data`` (default):
        each host feeds only its contiguous slice of every global batch via
        ``Dataset.process_shard`` + ``put_process_batch`` — same trajectory
        as the global-batch path, 1/nproc the host-side data.
        """
        # Steps already captured before THIS fit (a second fit on the same
        # Trainer must not re-print the first run's summary).
        pre_traced = (self._profiler.captured_steps
                      if self._profiler is not None else 0)
        mesh = self.cluster.mesh
        cfg = self.cfg
        epochs = epochs if epochs is not None else cfg.epochs
        rng_base = jax.random.key(cfg.seed + 17)
        bs = self.global_batch_size
        timer = StepTimer()
        last_cost = float("nan")

        train, feed_bs, put = splits.train, bs, put_global_batch
        nproc = jax.process_count()
        if (cfg.shard_data and nproc > 1
                and hasattr(splits.train, "process_shard")
                and bs % nproc == 0
                and sh.data_axis_tiles_processes(mesh)):
            train = splits.train.process_shard(jax.process_index(), nproc)
            feed_bs, put = bs // nproc, put_process_batch

        batch_count = train.num_examples // bs              # :104
        start_epoch = (min(self._host_step // batch_count, epochs)
                       if batch_count else 0)
        skip_batches = self._host_step % batch_count if batch_count else 0
        # Fast-forward the shuffle cursor to where it was when the checkpoint
        # was written — but only by the batches this dataset hasn't already
        # served (a second fit() on the same dataset must not double-advance).
        behind = self._host_step - getattr(train, "batches_consumed", 0)
        if behind > 0 and start_epoch < epochs:
            if hasattr(train, "fast_forward"):
                train.fast_forward(behind, feed_bs)
            else:   # foreign dataset with only the next_batch contract
                for _ in range(behind):
                    train.next_batch(feed_bs)
        elif (behind < 0 and batch_count and start_epoch < epochs
                and (max_steps is None or self._host_step < max_steps)):
            # The stream is AHEAD of the trajectory: a prefetching fit
            # exited early on this dataset object (producer overrun) and
            # a shuffle cursor cannot rewind.  Serving shifted batches
            # would silently break the bitwise-exact trajectory contract
            # — fail loud; the canonical restart paths (--resume
            # relaunch, supervisor attempt) load a fresh stream and
            # never hit this.
            raise RuntimeError(
                f"data stream is {-behind} batch(es) ahead of the "
                f"trajectory (an earlier prefetching fit on this dataset "
                f"object exited early); reuse cannot be positionally "
                f"exact — resume from a fresh data stream instead")

        ev = {"accuracy": float("nan")}
        if cfg.hang_timeout_s > 0:
            from dtf_tpu.utils.watchdog import HangWatchdog
            self._watchdog = HangWatchdog(cfg.hang_timeout_s)
        # Multi-host failure domain (resilience/health.py): heartbeats +
        # poison-pill coordinated abort, armed for the duration of fit.
        # The monitor's daemon thread beats independently of step
        # progress, so a dead/partitioned PEER is detected (and this host
        # freed from the wedged collective, exit 71) within the miss
        # budget — while this host's own hang is still the watchdog's job.
        health = self.cluster.start_health(print_fn=self.logger.print)
        if health is not None and self._chaos is not None:
            self._chaos.bind_partition(health.partition)
        straggling = (cfg.straggler_factor > 1.0 and nproc > 1)
        if straggling:
            from jax.experimental import multihost_utils
            from dtf_tpu.resilience.health import flag_stragglers
        preempt = None
        if self.ckpt is not None and cfg.preemption_save:
            from dtf_tpu.utils.preemption import PreemptionHandler
            preempt = PreemptionHandler(
                signals=PreemptionHandler.signals_for(cfg.preempt_sigint))
        preempted = False
        # Data-path robustness: transient I/O errors (flaky filesystem,
        # chaos loader_error) get a bounded retry; ValueError and the
        # native loader's RetryExhausted stay terminal.  Chaos nan_grad
        # poisons the host batch AFTER the fetch so the injected NaNs
        # drive the compiled guard through the real path.
        from dtf_tpu.utils.retry import Backoff, retry_call
        # Jitter decorrelated by process index: hosts retrying a flaky
        # shared filesystem must not re-hit it in lockstep.
        fetch_backoff = Backoff(base_s=0.1, max_s=2.0,
                                seed=cfg.seed + jax.process_index())

        def produce(step: int):
            """fetch -> chaos poison -> sharded device_put for ``step`` —
            THE data path, shared verbatim by the serial loop (booked as
            "data" time) and the prefetcher's producer thread (overlapped
            with dispatched steps; only consumer stalls book).  Keyed by
            the global step so chaos faults and error propagation stay
            step-aligned however far ahead the producer runs."""
            def attempt():
                if self._chaos is not None:
                    self._chaos.maybe_loader_error(step)
                return train.next_batch(feed_bs)
            with tel.span("train/fetch"):
                host_batch = retry_call(
                    attempt, attempts=3, backoff=fetch_backoff,
                    retry_on=(OSError,), what="train batch fetch",
                    on_retry=lambda a, e: tel.counter(
                        "data/fetch_retries_total").inc())
            if self._chaos is not None:
                host_batch = self._chaos.maybe_poison_batch(step, host_batch)
            with tel.span("train/put"):
                return put(mesh, host_batch)

        # Async device prefetch (data/prefetch.py): the production budget
        # is EXACTLY the number of steps this fit will consume (epoch
        # budget minus the resumed offset, capped by max_steps), so a
        # completed fit leaves the dataset cursor precisely where the
        # serial path would have.
        planned = 0
        if batch_count:
            for _e in range(start_epoch, epochs):
                planned += batch_count - (skip_batches
                                          if _e == start_epoch else 0)
        if max_steps is not None:
            planned = min(planned, max(max_steps - self._host_step, 0))
        prefetcher = None
        # Re-resolve the compiled-vs-jit dispatch on this fit's first
        # step (a second fit may feed different shapes).
        self._fit_step_call = None

        fit_completed = False
        # Goodput attribution (telemetry/goodput.py): every host-side
        # phase of the loop books into a category; the ctor->fit gap
        # (caller-side data loading) and the loop's own residue (rng
        # folds, watchdog ticks, span bookkeeping) book as "other", so
        # productive + overhead sums to wall-clock.  Spans mirror the
        # same phases to the JSONL tracer for the Perfetto timeline.
        tracker = tel.get_tracker()
        if getattr(self, "_ctor_done", None) is not None:
            tracker.add("other", max(
                (time.perf_counter() - self._ctor_done)
                - (tracker.accounted_s() - self._ctor_acc), 0.0))
            self._ctor_done = None      # once: a second fit has no gap
        _fit_t0 = time.perf_counter()
        _fit_acc0 = tracker.accounted_s()
        _fit_span = tel.get_tracer().span("train/fit", epochs=epochs)
        _fit_span.__enter__()
        try:
            if cfg.prefetch > 0 and planned > 0:
                from dtf_tpu.data.prefetch import DevicePrefetcher
                prefetcher = DevicePrefetcher(
                    produce, start_step=self._host_step,
                    num_batches=planned, depth=cfg.prefetch)
            if cfg.aot_warmup and not self._compile_seen and planned > 0:
                # Overlaps the producer's initial queue fill: the main
                # thread compiles while the background thread stages the
                # first batches onto the devices.
                self._aot_warmup(splits.train, bs)
            hit_cap = False
            for epoch in range(start_epoch, epochs):
                count = 0
                first_batch = skip_batches if epoch == start_epoch else 0
                for i in range(first_batch, batch_count):
                    if max_steps is not None and self._host_step >= max_steps:
                        hit_cap = True
                        break
                    if self._chaos is not None:
                        # stall / slow_host faults sleep in here — injected
                        # non-productive time, booked as such.
                        with tracker.measure("stall"):
                            self._chaos.maybe_step_faults(self._host_step)
                    if prefetcher is not None:
                        # Already device-resident; only a genuine wait on
                        # an empty queue books as "data" (the
                        # data/prefetch_stall span inside get()).
                        batch = prefetcher.get(self._host_step)
                    else:
                        with tracker.measure("data"):
                            batch = produce(self._host_step)
                    step_rng = jax.random.fold_in(rng_base, self._host_step)
                    # Without AOT warmup the first dispatch pays
                    # trace+compile synchronously: that wall time is
                    # "compile", not "productive".  The category is
                    # decided AFTER the call: _dispatch_step clears
                    # _compile_seen when it abandons a rejected AOT
                    # executable, and that retry pays the jit
                    # trace+compile — booking it as productive would
                    # inflate goodput by whole compile seconds.
                    _pre_seen = self._compile_seen
                    _t_step = time.perf_counter()
                    # step-scoped span: --request-style drill-down and
                    # the Perfetto view can land on an exact step
                    with tel.span("train/step", step=self._host_step):
                        self.state, metrics = self._dispatch_step(batch,
                                                                  step_rng)
                    _dt_step = time.perf_counter() - _t_step
                    tracker.add("productive"
                                if _pre_seen and self._compile_seen
                                else "compile", _dt_step)
                    # incident plane: per-step time into the changepoint
                    # detector — compile-bearing steps excluded (a first
                    # step 100x the steady state is not an incident)
                    if _pre_seen and self._compile_seen:
                        self._anomaly.observe("train/step_ms",
                                              _dt_step * 1e3,
                                              tick=self._host_step)
                    if not self._compile_seen:
                        self._compile_seen = True
                        tel.gauge("compile/first_step_s").set(_dt_step)
                    self.last_metrics = metrics
                    count += 1
                    self._host_step += 1
                    if self._admin_probe is not None:
                        self._admin_probe.beat(self._host_step)
                    if self._watchdog is not None:
                        self._watchdog.tick()
                    if self._profiler is not None:
                        self._profiler.after_step(self._host_step, self.state)
                    if (cfg.determinism_every > 0
                            and self._host_step % cfg.determinism_every == 0):
                        from dtf_tpu.utils.profiling import assert_replicas_agree
                        assert_replicas_agree(
                            {"loss": metrics["loss"],
                             "step": self.state["step"]},
                            what=f"step {self._host_step} metrics")
                    if (self.ckpt is not None and self.cfg.checkpoint_every > 0
                            and self._host_step % self.cfg.checkpoint_every == 0):
                        if self._fleet is not None:
                            # checkpoint boundaries hit the same step on
                            # every host — a natural fleet-wide barrier
                            # mark (telemetry/fleet.py)
                            self._fleet.note_sync("ckpt", self._host_step)
                        _t_ckpt = time.perf_counter()
                        with self._suspended_watchdog(), \
                                tracker.measure("checkpoint"):
                            self.ckpt.save(self._host_step, self.state)
                            if self._chaos is not None:
                                # Inside the suspended window: the hooks
                                # drain the async save + checksum files /
                                # sleep out an injected write stall, which
                                # must not read as a training hang.
                                self._chaos.maybe_ckpt_stall(
                                    self._host_step)
                                self._chaos.maybe_corrupt_after_save(
                                    self._host_step, self.ckpt)
                        # incident plane: the measured window INCLUDES an
                        # injected write stall — a stalled store is an
                        # onset the correlator must explain
                        self._anomaly.observe(
                            "checkpoint/save_ms",
                            (time.perf_counter() - _t_ckpt) * 1e3,
                            tick=self._host_step)
                    # Preemption decision: single-process polls the local
                    # flag every step; multi-process agrees via allgather
                    # only at the logging sync boundaries (deterministic,
                    # identical on every process), because the save and the
                    # next step are both collectives — hosts must pick the
                    # SAME boundary or they deadlock (utils/preemption.py).
                    at_sync = (count % cfg.log_frequency == 0
                               or i + 1 == batch_count)
                    if preempt is not None and (
                            preempt.triggered if jax.process_count() == 1
                            else (at_sync and preempt.agreed())):
                        with self._suspended_watchdog(), \
                                tracker.measure("checkpoint"):
                            self.ckpt.save(self._host_step, self.state,
                                           force=True)
                            if self._chaos is not None:
                                # A slow store delays the preemption
                                # drain too — same measured window as
                                # the periodic save's stall hook.
                                self._chaos.maybe_ckpt_stall(
                                    self._host_step)
                        # logger.event, not a bare print: the agreed-save
                        # decision lands as an `event/preempted` scalar in
                        # the TensorBoard stream, so drains are countable
                        # on the same time axis as the loss they cut short.
                        self.logger.event(
                            self._host_step, "preempted",
                            f"checkpointed step {self._host_step}; exiting "
                            f"(resume with --resume)")
                        preempted = True
                        break
                    if at_sync:
                        # Sync point: read back the metrics (the reference
                        # paid this every step via sess.run; we pay it only
                        # when logging).  The read blocks on the whole
                        # dispatched step pipeline, so it books as
                        # productive time — the device was doing model
                        # work while the host waited.
                        with tracker.measure("productive"):
                            cost = float(metrics["loss"])
                            step = int(self.state["step"])
                        avg_ms = timer.window_avg_ms(count)
                        with tel.span("train/log", step=step):
                            self.logger.step_line(step, epoch + 1, i + 1,
                                                  batch_count, cost, avg_ms)
                            self.logger.scalar(step, "cost", cost)
                            self.logger.scalar(step, "avg_ms", avg_ms)
                        if straggling:
                            # Per-host step timing, allgathered at a
                            # boundary every process reaches together
                            # (same rule as the preemption allgather):
                            # hosts slower than median * straggler_factor
                            # are flagged to metrics and the published
                            # health snapshot.  The allgather waits on the
                            # slowest host, so it books as stall time.
                            # With a fleet plane armed, each host's
                            # barrier-arrival stamp RIDES this same
                            # allgather as a split (hi, lo) f32 pair —
                            # epoch seconds overflow f32's mantissa, and
                            # jax's x64-off canonicalization downcasts
                            # any f64 payload on the multi-process path,
                            # so fleet.split_unix/merge_unix carry the
                            # precision instead (µs-level after the f32
                            # wire).  Skew attribution thus adds no new
                            # collective; the span's dur is the
                            # in-barrier wait, i.e. the release edge the
                            # clock-offset estimator aligns hosts on.
                            if self._fleet is not None:
                                from dtf_tpu.telemetry.fleet import (
                                    merge_unix, split_unix)
                                _arrive = time.time()
                                _hi, _lo = split_unix(_arrive)
                                with tracker.measure("stall"):
                                    gathered = np.asarray(
                                        multihost_utils.process_allgather(
                                            np.asarray(
                                                [avg_ms, _hi, _lo],
                                                np.float32))
                                    ).reshape(-1, 3)
                                self._fleet.note_sync(
                                    "log", step, arrival_unix=_arrive,
                                    wait_s=max(time.time() - _arrive, 0.0))
                                self._fleet.note_barrier(
                                    "log", step,
                                    {i: merge_unix(row[1], row[2])
                                     for i, row in enumerate(gathered)})
                                per_host = gathered[:, 0]
                            else:
                                with tracker.measure("stall"):
                                    per_host = np.asarray(
                                        multihost_utils.process_allgather(
                                            np.asarray([avg_ms],
                                                       np.float32))
                                    ).reshape(-1)
                            flagged = flag_stragglers(
                                per_host, cfg.straggler_factor)
                            self.logger.stragglers(step, per_host, flagged)
                            if health is not None:
                                health.note_stragglers(step, per_host,
                                                       flagged)
                        elif self._fleet is not None:
                            # No straggler allgather to ride: the barrier
                            # mark travels through the fleet mesh (file
                            # or TCP) instead — the CPU-sim rig's path,
                            # whose jaxlib has no cross-process
                            # collectives.
                            self._fleet.note_sync("log", step)
                        # Telemetry sync point: steps/throughput/MFU
                        # gauges, then the registry->disk snapshot and the
                        # forced flush that keeps the crash-safety
                        # contract (metrics already on disk if the next
                        # instant is a SIGKILL).
                        tel.gauge("train/steps_total").set(step)
                        if "quant_error" in metrics:
                            # int8 wire: measured relative-RMS encode
                            # error of this step's gradients (already
                            # psum'd replica-uniform in the step).  A
                            # guard-skipped step's error pair is NaN by
                            # design (non-finite scale) — keep it out of
                            # the gauge so telemetry.json stays strict
                            # JSON and the last value reflects a real
                            # step.
                            qe = float(metrics["quant_error"])
                            if np.isfinite(qe):
                                tel.gauge("comm/quant_error").set(qe)
                        if avg_ms > 0:
                            tel.goodput.record_throughput(
                                examples_per_s=bs * 1000.0 / avg_ms,
                                tokens_per_example=self._tokens_per_example,
                                step_ms=avg_ms,
                                model_flops_per_example=(
                                    self._flops_per_example or 0.0),
                                n_chips=mesh.size,
                                peak_flops_per_chip=self._peak_flops)
                        count = 0
                        last_cost = cost
                        # Flush BEFORE the guard/rollback below: the rows
                        # explaining an imminent rollback must not sit in
                        # the batch buffer across a multi-second restore
                        # (a health abort's os._exit there would lose
                        # exactly the evidence the post-mortem needs).
                        self.logger.flush()
                        # Guard policy (DESIGN.md §5): the device-side
                        # streak counter means the hot loop never syncs
                        # per step; the sync boundary is where the host
                        # reads the verdict and decides.  A bad step is
                        # already a no-op to params, so acting a few
                        # steps late is harmless.
                        if self._guarded:
                            skipped_total = int(metrics["skipped_total"])
                            if skipped_total:
                                self.logger.scalar(step, "bad_steps_total",
                                                   skipped_total)
                            tel.gauge("train/bad_streak").set(
                                int(metrics["bad_streak"]))
                            if (cfg.bad_step_limit > 0
                                    and int(metrics["bad_streak"])
                                    >= cfg.bad_step_limit):
                                self._rollback_or_fail(
                                    int(metrics["bad_streak"]))
                        self.logger.flush()   # rollback event rows too
                        if (self.cfg.telemetry and self.cfg.logdir
                                and self.cluster.is_coordinator):
                            try:      # best-effort: a full disk must not
                                tel.write_telemetry_json(self.cfg.logdir)
                            except OSError:   # kill the training loop
                                pass
                        if self._fleet is not None:
                            # Every host ships its books into the fleet
                            # mesh; the coordinator folds them (plus the
                            # live skew attribution) into fleet.json —
                            # the /fleetz payload, persisted.
                            self._fleet.publish_books()
                            if self._fleet.is_coordinator:
                                self._fleet.write_rollup()
                if preempted or hit_cap:
                    break
                if splits.test is not None:
                    with self._suspended_watchdog(), \
                            tel.span("train/eval"), tracker.measure("eval"):
                        ev = self.eval_fn(self.state, splits.test)
                    self.logger.epoch_summary(ev["accuracy"], timer.total_s(),
                                              last_cost)
                    self.logger.scalar(int(self.state["step"]),
                                       "test_accuracy", ev["accuracy"])
                    # Epoch boundary is a crash-safety sync point too: the
                    # eval row must not sit in the batched-flush buffer
                    # until the NEXT logging sync (a watchdog os._exit
                    # skips finalizers).
                    self.logger.flush()
            if start_epoch >= epochs and splits.test is not None:
                # resumed past the budget: report eval
                with self._suspended_watchdog(), \
                        tel.span("train/eval"), tracker.measure("eval"):
                    ev = self.eval_fn(self.state, splits.test)
            fit_completed = True
        finally:
            if prefetcher is not None:
                overrun = prefetcher.close()
                if overrun:
                    # The producer ran ahead of an early exit (preemption,
                    # crash): this dataset OBJECT's cursor sits `overrun`
                    # batches past the trajectory, so reusing it in-place
                    # cannot be positionally exact.  The canonical restart
                    # paths (supervisor attempts, --resume relaunches)
                    # load a fresh stream and fast-forward — exact.
                    self.logger.print(
                        f"[dtf_tpu] prefetch: {overrun} produced-but-"
                        f"unconsumed batch(es) dropped on early exit; a "
                        f"resume must use a fresh data stream (supervisor "
                        f"attempts and --resume relaunches do)")
            _fit_span.__exit__(None, None, None)
            if health is not None:
                # A COMPLETED fit (incl. agreed preemption) departs
                # cleanly — peers still finishing their epoch must not
                # read the exit as a death.  A crash path must NOT write
                # DEPARTED: this host is going down mid-job, and the
                # peers' coordinated abort is the correct response.
                health.close(mark_departed=fit_completed)
            if preempt is not None:
                preempt.restore()
            # Disarm before post-loop host work — and on ANY exit path: a
            # raise out of the loop must not leave a daemon thread around to
            # os._exit(70) the caller's cleanup.
            if self._watchdog is not None:
                self._watchdog.close()
            if self._profiler is not None:
                # In the finally: a raise out of the loop must still
                # stop_trace, or the trace file is never written.
                self._profiler.close(self.state)
            # Residual sweep: whatever this fit's wall time the measured
            # phases didn't cover (rng folds, condition checks, span
            # bookkeeping) books as "other" — the accounted columns must
            # sum to wall-clock even on a crash path.
            tracker.add("other", max(
                (time.perf_counter() - _fit_t0)
                - (tracker.accounted_s() - _fit_acc0), 0.0))
            # A crash path must still leave the telemetry books — and any
            # buffered metric rows — on disk: they are exactly what the
            # post-mortem reads.
            try:
                self.logger.flush()
            except Exception:
                pass
            if self.cfg.telemetry and self.cfg.logdir:
                if self.cluster.is_coordinator:
                    try:
                        tel.write_telemetry_json(self.cfg.logdir)
                    except OSError:
                        pass
                tel.get_tracer().flush()
        if self._profiler is not None:
            steps_traced = self._profiler.captured_steps - pre_traced
            if (self.cfg.profile_summary and self.cluster.is_coordinator
                    and self._profiler.wrote_trace):
                if steps_traced <= 0:
                    # Never summarize a dir that may hold a PREVIOUS
                    # run's trace as if it were this run's.
                    self.logger.print(
                        "[trace] no summary: the window covered no "
                        "complete step this run (profile_start at or "
                        "beyond the last step?)")
                else:
                    self._print_trace_summary(steps_traced)
        with tracker.measure("productive"):   # drain the dispatch pipeline
            block(self.state)
        if self._chaos is not None and not preempted:
            pend = self._chaos.pending()
            if pend:
                # An injected-but-never-fired fault proves nothing — the
                # same accepted-but-ignored trap the benchmark driver warns
                # about for --max_restarts.
                self.logger.print(
                    f"[dtf_tpu] WARNING: chaos faults never fired: "
                    f"{','.join(str(f) for f in pend)} (step never "
                    f"reached, or a corrupt_ckpt/ckpt_stall step not a "
                    f"checkpoint boundary) — this run did NOT exercise "
                    f"them")
        if self.ckpt is not None:
            with tracker.measure("checkpoint"):
                if (not preempted and self.cfg.checkpoint_every > 0
                        and self.ckpt.latest_step() != self._host_step):
                    self.ckpt.save(self._host_step, self.state, force=True)
                self.ckpt.wait()
        if (self.cfg.telemetry and self.cfg.logdir
                and self.cluster.is_coordinator):
            # Final books: the tail (drain + last save) is now accounted.
            # Best-effort — a full disk at run end must not turn a
            # COMPLETED training run into a crash.
            try:
                tel.write_telemetry_json(self.cfg.logdir)
            except OSError:
                pass
        if self._fleet is not None:
            # Final fleet cut: the last barriers and the completed books
            # must be in fleet.json before the process exits.
            self._fleet.publish_books()
            if self._fleet.is_coordinator:
                self._fleet.write_rollup()
            tel.get_tracer().flush()
        return {"test_accuracy": ev["accuracy"], "final_cost": last_cost,
                "steps": int(self.state["step"]), "total_s": timer.total_s(),
                "preempted": preempted,
                "skipped_steps": (int(self.state["skipped"])
                                  if "skipped" in self.state else 0),
                "rollbacks": self._rollbacks}
