"""Observability: the reference's console contract + a metric writer.

Console parity (golden-output contract, SURVEY.md §4): the reference printed
every ``frequency`` steps (tf_distributed.py:118-122)

    Step: %d,  Epoch: %2d,  Batch: %3d of %3d,  Cost: %.4f,  AvgTime: %3.2fms

and per epoch (:126-128)

    Test-Accuracy: %2.2f
    Total Time: %3.2fs
    Final Cost: %.4f

Metrics are appended to ``<logdir>/metrics.csv`` AND to a TensorBoard
event file (``<logdir>/events.out.tfevents.*``, via the dependency-free
writer in :mod:`dtf_tpu.train.tbevents`) — the equivalent of the
reference's per-step summary writer (:84-88,112), but buffered, not a
per-step host sync.  Only the coordinator process writes (SPMD: every
process runs the same code; the reference instead relied on each worker
writing to its own local /tmp, :24).
"""

from __future__ import annotations

import csv
import os
import time
from typing import Optional

# TB/CSV flush batching: writes buffer until this much time or this many
# rows accumulate; logging sync points, event() and close() force a flush
# (the crash-safety contract — a fail-fast os._exit skips finalizers, so
# the post-mortem metrics must already be on disk at every sync point).
_FLUSH_INTERVAL_S = 2.0
_FLUSH_MAX_PENDING = 64


def format_step_line(step: int, epoch: int, batch: int, batch_count: int,
                     cost: float, avg_ms: float) -> str:
    """Byte-identical to the reference's print (tf_distributed.py:118-122,
    which joins print args with single spaces)."""
    return ("Step: %d, " % step +
            " Epoch: %2d, " % epoch +
            " Batch: %3d of %3d, " % (batch, batch_count) +
            " Cost: %.4f, " % cost +
            " AvgTime: %3.2fms" % avg_ms)


def _last_attempt(path: str) -> int:
    """Largest attempt recorded in an existing metrics.csv (-1 when the
    file is absent/empty or pre-dates the attempt column)."""
    last = -1
    try:
        with open(path, newline="") as f:
            for rec in csv.reader(f):
                if len(rec) > 3 and rec[3].lstrip("-").isdigit():
                    last = max(last, int(rec[3]))
                elif rec and rec[0] != "step":
                    last = max(last, 0)        # legacy row == attempt 0
    except OSError:
        pass
    return last


class MetricLogger:
    def __init__(self, logdir: Optional[str] = None, is_coordinator: bool = True,
                 quiet: bool = False, attempt: Optional[int] = 0):
        """``attempt`` tags every CSV row so a rollback or supervisor
        restart's overlapping step ranges stay distinguishable (the file
        is append-mode by design — one run's attempts share it, and the
        report CLI de-duplicates by latest attempt).  ``attempt=None``
        auto-resumes: one past the largest attempt already in the file —
        the scheduler-driven ``--resume`` path, where no in-process
        supervisor is counting."""
        self.is_coordinator = is_coordinator
        self.quiet = quiet
        self._csv = None
        self._writer = None
        self._tb = None
        self._pending = 0
        self._last_flush = time.monotonic()
        self.attempt = attempt if attempt is not None else 0
        if logdir and is_coordinator:
            os.makedirs(logdir, exist_ok=True)
            path = os.path.join(logdir, "metrics.csv")
            if attempt is None:
                self.attempt = _last_attempt(path) + 1
            self._csv = open(path, "a", newline="")
            self._writer = csv.writer(self._csv)
            if self._csv.tell() == 0:
                self._writer.writerow(["step", "metric", "value", "attempt"])
            from dtf_tpu.train.tbevents import TBEventWriter
            self._tb = TBEventWriter(logdir)

    @classmethod
    def for_config(cls, cfg, is_coordinator: bool = True,
                   quiet: bool = False) -> "MetricLogger":
        """THE attempt-tag rule, shared by the Trainer and the workload
        CLIs that build their logger up front: an explicit ``cfg.attempt``
        (an external scheduler counting its own relaunches) wins; any
        resumed run — in-process supervisor restart or ``--resume``
        relaunch — auto-continues past the file's last recorded attempt;
        a fresh run is attempt 0."""
        return cls(cfg.logdir, is_coordinator, quiet=quiet,
                   attempt=(cfg.attempt if cfg.attempt
                            else (None if cfg.resume else 0)))

    def print(self, msg: str) -> None:
        if self.is_coordinator and not self.quiet:
            print(msg, flush=True)

    def step_line(self, step: int, epoch: int, batch: int, batch_count: int,
                  cost: float, avg_ms: float) -> None:
        self.print(format_step_line(step, epoch, batch, batch_count, cost, avg_ms))

    def graph(self, params, root: str = "model") -> None:
        """Write the model-structure GraphDef event once (the reference
        wrote its graph at Supervisor startup, tf_distributed.py:97)."""
        if self._tb:
            self._tb.graph_from_params(params, root)
            self._tb.flush()

    def scalar(self, step: int, name: str, value: float) -> None:
        # Mirror into the telemetry registry (auto-registered gauge) so
        # telemetry.json carries the last value of every scalar stream; a
        # name already registered as a counter (event/*) keeps its type.
        from dtf_tpu import telemetry
        try:
            telemetry.gauge(name).set(float(value))
        except (ValueError, TypeError):
            pass
        if self._writer:
            self._writer.writerow([step, name, float(value), self.attempt])
        if self._tb:
            self._tb.scalar(step, name, float(value))
        self._pending += 1
        now = time.monotonic()
        if (self._pending >= _FLUSH_MAX_PENDING
                or now - self._last_flush >= _FLUSH_INTERVAL_S):
            self.flush()

    def flush(self) -> None:
        """Force buffered CSV/TB rows to disk — called by the trainer at
        every logging sync point (and by event()/close())."""
        if self._csv:
            self._csv.flush()
        if self._tb:
            self._tb.flush()
        self._pending = 0
        self._last_flush = time.monotonic()

    def stragglers(self, step: int, per_host_ms, flagged) -> None:
        """Cluster-health feed (resilience/health.flag_stragglers): each
        host's avg step time as ``health/step_ms_p<k>`` so TensorBoard
        overlays the whole fleet on one axis, plus a ``health/stragglers``
        count; flagged hosts get a console line (they are where the next
        host_down usually comes from)."""
        for k, ms in enumerate(per_host_ms):
            self.scalar(step, f"health/step_ms_p{k}", float(ms))
        self.scalar(step, "health/stragglers", float(len(flagged)))
        if flagged:
            from dtf_tpu.resilience.health import finite_median
            detail = ", ".join(
                f"p{k}={float(per_host_ms[k]):.1f}ms" for k in flagged)
            self.print(f"[dtf_tpu] straggler(s) at step {step}: {detail} "
                       f"(cluster median "
                       f"{finite_median(per_host_ms):.1f}ms/step)")

    def event(self, step: int, name: str, detail: str = "") -> None:
        """Resilience/lifecycle event: a REGISTERED ``event/<name>``
        counter (telemetry registry — the machine-readable count), a span
        instant (the timeline mark), one console line, and an
        ``event/<name>`` scalar carrying the cumulative count so
        rollbacks, retries and restarts stay visible on the same
        TensorBoard time axis as the loss they disturbed.  Flushed
        eagerly: events mark exactly the moments a post-mortem needs."""
        from dtf_tpu import telemetry
        count = telemetry.counter(f"event/{name}")
        count.inc()
        telemetry.instant(f"event/{name}", step=step,
                          **({"detail": detail} if detail else {}))
        self.print(f"[dtf_tpu] {name}" + (f": {detail}" if detail else ""))
        self.scalar(step, f"event/{name}", float(count.value))
        self.flush()

    def epoch_summary(self, test_accuracy: float, total_s: float,
                      final_cost: float) -> None:
        """The reference's per-epoch block (tf_distributed.py:126-128)."""
        self.print("Test-Accuracy: %2.2f" % test_accuracy)
        self.print("Total Time: %3.2fs" % total_s)
        self.print("Final Cost: %.4f" % final_cost)

    def close(self) -> None:
        self.flush()
        if self._csv:
            self._csv.close()
            self._csv = self._writer = None
        if self._tb:
            self._tb.close()
            self._tb = None
