"""Observability: the reference's console contract + a metric writer.

Console parity (golden-output contract, SURVEY.md §4): the reference printed
every ``frequency`` steps (tf_distributed.py:118-122)

    Step: %d,  Epoch: %2d,  Batch: %3d of %3d,  Cost: %.4f,  AvgTime: %3.2fms

and per epoch (:126-128)

    Test-Accuracy: %2.2f
    Total Time: %3.2fs
    Final Cost: %.4f

Metrics are appended to ``<logdir>/metrics.csv`` AND to a TensorBoard
event file (``<logdir>/events.out.tfevents.*``, via the dependency-free
writer in :mod:`dtf_tpu.train.tbevents`) — the equivalent of the
reference's per-step summary writer (:84-88,112), but buffered, not a
per-step host sync.  Only the coordinator process writes (SPMD: every
process runs the same code; the reference instead relied on each worker
writing to its own local /tmp, :24).
"""

from __future__ import annotations

import csv
import os
from typing import Optional


def format_step_line(step: int, epoch: int, batch: int, batch_count: int,
                     cost: float, avg_ms: float) -> str:
    """Byte-identical to the reference's print (tf_distributed.py:118-122,
    which joins print args with single spaces)."""
    return ("Step: %d, " % step +
            " Epoch: %2d, " % epoch +
            " Batch: %3d of %3d, " % (batch, batch_count) +
            " Cost: %.4f, " % cost +
            " AvgTime: %3.2fms" % avg_ms)


class MetricLogger:
    def __init__(self, logdir: Optional[str] = None, is_coordinator: bool = True,
                 quiet: bool = False):
        self.is_coordinator = is_coordinator
        self.quiet = quiet
        self._csv = None
        self._writer = None
        self._tb = None
        if logdir and is_coordinator:
            os.makedirs(logdir, exist_ok=True)
            self._csv = open(os.path.join(logdir, "metrics.csv"), "a", newline="")
            self._writer = csv.writer(self._csv)
            if self._csv.tell() == 0:
                self._writer.writerow(["step", "metric", "value"])
            from dtf_tpu.train.tbevents import TBEventWriter
            self._tb = TBEventWriter(logdir)

    def print(self, msg: str) -> None:
        if self.is_coordinator and not self.quiet:
            print(msg, flush=True)

    def step_line(self, step: int, epoch: int, batch: int, batch_count: int,
                  cost: float, avg_ms: float) -> None:
        self.print(format_step_line(step, epoch, batch, batch_count, cost, avg_ms))

    def graph(self, params, root: str = "model") -> None:
        """Write the model-structure GraphDef event once (the reference
        wrote its graph at Supervisor startup, tf_distributed.py:97)."""
        if self._tb:
            self._tb.graph_from_params(params, root)
            self._tb.flush()

    def scalar(self, step: int, name: str, value: float) -> None:
        if self._writer:
            self._writer.writerow([step, name, float(value)])
            self._csv.flush()
        if self._tb:
            self._tb.scalar(step, name, float(value))
            # Flush eagerly: scalar() is only called at logging sync points,
            # and a fail-fast os._exit (utils/watchdog.py) skips finalizers —
            # the post-mortem metrics must already be on disk.
            self._tb.flush()

    def stragglers(self, step: int, per_host_ms, flagged) -> None:
        """Cluster-health feed (resilience/health.flag_stragglers): each
        host's avg step time as ``health/step_ms_p<k>`` so TensorBoard
        overlays the whole fleet on one axis, plus a ``health/stragglers``
        count; flagged hosts get a console line (they are where the next
        host_down usually comes from)."""
        for k, ms in enumerate(per_host_ms):
            self.scalar(step, f"health/step_ms_p{k}", float(ms))
        self.scalar(step, "health/stragglers", float(len(flagged)))
        if flagged:
            from dtf_tpu.resilience.health import finite_median
            detail = ", ".join(
                f"p{k}={float(per_host_ms[k]):.1f}ms" for k in flagged)
            self.print(f"[dtf_tpu] straggler(s) at step {step}: {detail} "
                       f"(cluster median "
                       f"{finite_median(per_host_ms):.1f}ms/step)")

    def event(self, step: int, name: str, detail: str = "") -> None:
        """Resilience/lifecycle event: one console line + a unit-valued
        ``event/<name>`` scalar so rollbacks, retries and restarts are
        visible on the same TensorBoard time axis as the loss they
        disturbed (and countable from the CSV post-mortem)."""
        self.print(f"[dtf_tpu] {name}" + (f": {detail}" if detail else ""))
        self.scalar(step, f"event/{name}", 1.0)

    def epoch_summary(self, test_accuracy: float, total_s: float,
                      final_cost: float) -> None:
        """The reference's per-epoch block (tf_distributed.py:126-128)."""
        self.print("Test-Accuracy: %2.2f" % test_accuracy)
        self.print("Total Time: %3.2fs" % total_s)
        self.print("Final Cost: %.4f" % final_cost)

    def close(self) -> None:
        if self._csv:
            self._csv.close()
            self._csv = self._writer = None
        if self._tb:
            self._tb.close()
            self._tb = None
