"""Persistent XLA compilation cache: stop re-paying trace+compile on
every restart.

Every supervisor restart, elastic relaunch and scheduler-driven
``--resume`` builds a fresh ``jit`` and re-pays the full backend compile
of a program that is byte-identical to the last attempt's — pure restart
downtime.  jax's persistent compilation cache keys compiled executables
by HLO fingerprint in a shared directory, so any process (attempt,
relaunch, sibling host with the same program) gets a disk read instead
of a compile — standard practice in pjit-era TPU training (PAPERS.md:
arxiv 2204.06514).

:func:`enable` points jax at ``--compile_cache DIR`` and drops the
min-compile-time threshold so even fast CPU-test programs cache (the TPU
programs this exists for are all above any threshold).  It also installs
a ``jax.monitoring`` listener that mirrors the cache's hit/miss events
into the telemetry registry as ``compile/cache_hit`` /
``compile/cache_miss`` counters — so ``telemetry.json`` and the run
report show compile *reuse* across attempts, not just a shrinking
"compile" goodput bucket.  Idempotent: the supervisor's
fresh-Trainer-per-attempt path calls it once per attempt.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("dtf_tpu")

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_state = {"listener": False, "dir": None}


def _on_event(event: str, **kwargs) -> None:
    # Counters, not gauges: lifetime totals that survive telemetry.json
    # reloads across attempts (registry.load_counters).
    from dtf_tpu import telemetry as tel
    if event == _HIT_EVENT:
        tel.counter("compile/cache_hit").inc()
    elif event == _MISS_EVENT:
        tel.counter("compile/cache_miss").inc()


def enable(cache_dir: str) -> Optional[str]:
    """Enable the persistent compilation cache at ``cache_dir`` (created
    if absent) and install the hit/miss telemetry listener.  Returns the
    directory, or None when this jax build lacks the cache config (the
    run proceeds uncached — a missing optimization, not an error)."""
    import jax

    cache_dir = os.path.abspath(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache EVERYTHING: the default 1s threshold would skip the small
        # CPU-rig test programs, and the cache exists precisely for the
        # programs too expensive to rebuild.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as exc:           # feature-detect, don't crash a run
        log.warning("persistent compile cache unavailable in this jax "
                    "build (%s); continuing uncached", exc)
        return None
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass                           # older builds: size gate keeps default
    if not _state["listener"]:
        try:
            from jax._src import monitoring
            monitoring.register_event_listener(_on_event)
            _state["listener"] = True
        except Exception as exc:
            log.warning("compile-cache hit/miss telemetry unavailable "
                        "(%s); cache still active", exc)
    _state["dir"] = cache_dir
    return cache_dir
