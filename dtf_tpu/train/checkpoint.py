"""Checkpoint / resume.

The reference had none: its Supervisor was constructed without a ``logdir``
so the built-in Saver never ran, and ``global_step`` was never persisted — a
crash lost everything (tf_distributed.py:92; SURVEY.md §5.4).  Combined with
the coordination service's fail-fast failure propagation (SURVEY.md §5.3),
checkpoint+restart is this framework's recovery story.

Orbax-backed: async-capable, multi-host aware (each process writes its own
shards), preserves shardings on restore via the state template.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax

log = logging.getLogger("dtf_tpu")


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async save; returns True if a save was queued/performed."""
        saved = self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force)
        if saved:
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_template: Any,
                step: Optional[int] = None) -> tuple[Any, Optional[int]]:
        """Restore into the template's shapes/dtypes/shardings.  Returns
        (state, step) — (template, None) when nothing to restore."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return state_template, None
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(state_template))
        log.info("checkpoint restored from step %d", step)
        return restored, step

    def wait(self) -> None:
        """Block until pending async saves land (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()
