"""Checkpoint / resume.

The reference had none: its Supervisor was constructed without a ``logdir``
so the built-in Saver never ran, and ``global_step`` was never persisted — a
crash lost everything (tf_distributed.py:92; SURVEY.md §5.4).  Combined with
the coordination service's fail-fast failure propagation (SURVEY.md §5.3),
checkpoint+restart is this framework's recovery story.

Orbax-backed: async-capable, multi-host aware (each process writes its own
shards), preserves shardings on restore via the state template.

Hardening (DESIGN.md §5): a restart must never be wedged by the very crash
it is recovering from.  Each landed save gets a sidecar **manifest** —
per-file sizes + CRC32 under ``<dir>/manifests/<step>.json``, written by
the coordinator once the async save commits — and :meth:`restore_robust`
walks steps newest→oldest, skipping any step whose manifest doesn't verify
or whose orbax restore raises (partial write, bit rot, chaos-injected
corruption), so the newest *intact* checkpoint wins.  A corrupt latest
checkpoint costs ``checkpoint_every`` steps of progress, not the job.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from dtf_tpu import telemetry as tel

log = logging.getLogger("dtf_tpu")

_MANIFEST_DIR = "manifests"


class CheckpointMismatchError(RuntimeError):
    """A checkpoint verified INTACT failed to restore: the caller's state
    template doesn't match what was saved (different model, optimizer, or
    ``nonfinite_guard`` setting).  Deterministic — a restart replays it
    identically — so the supervisor must not burn its budget retrying
    (``no_restart``)."""

    no_restart = True


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _tree_manifest(root: str) -> dict:
    """{relpath: {size, crc32}} over every regular file under root."""
    files = {}
    for dirpath, _, names in os.walk(root):
        for name in names:
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            files[rel] = {"size": os.path.getsize(path),
                          "crc32": _file_crc32(path)}
    return files


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True,
                 run_meta: Optional[dict] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        # Facts about the WRITER the restore side needs to interpret the
        # state layout — the gradient-sync strategy (dense vs zero1
        # optimizer-state sharding) and the data-axis width.  Recorded in
        # every manifest; restore_robust compares against the current
        # run's values and logs the reshard (dense<->zero1 conversion,
        # elastic shrink) instead of leaving it silent.
        self._run_meta = dict(run_meta) if run_meta else {}
        self._async_save = async_save
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )
        # Steps saved but not yet manifested (async saves can't be
        # checksummed until they commit).  Committed steps are manifested
        # by a background thread at the NEXT save boundary — a hard kill
        # between saves must not leave the run's checkpoints unverifiable
        # — and synchronously on the wait()/restore paths.
        self._unmanifested: List[int] = []
        self._manifest_threads: List[threading.Thread] = []

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async save; returns True if a save was queued/performed."""
        import time as _time
        t0 = _time.perf_counter()
        if self._async_save and jax.default_backend() == "cpu":
            # On the CPU backend orbax's "transfer to host" is zero-copy
            # aliasing of the LIVE device buffers — and the train step
            # donates its state, so the next dispatched step reuses those
            # buffers in place while the async writer is still
            # serializing.  Observed (scenario matrix, loaded box): torn
            # checkpoints whose label-N tree holds step-N+1 bytes, which
            # the CRC manifest cannot catch (it checksums whatever
            # landed) and which silently forks the resumed trajectory.
            # Snapshot on-device first: one extra copy of the state,
            # sharding preserved, bytes pinned.  Real accelerators pay a
            # genuine D2H copy inside orbax before save() returns, and a
            # synchronous save finishes serializing before the next step
            # can dispatch — neither needs (or gets) the extra copy.
            state = jax.tree_util.tree_map(jnp.copy, state)
        with tel.span("checkpoint/save", step=step):
            saved = self._mgr.save(
                step, args=self._ocp.args.StandardSave(state), force=force)
        if saved:
            tel.counter("checkpoint/saves_total").inc()
            # Distribution, not just a last-value gauge: save latency is
            # spiky (the async save's hidden wait for its predecessor),
            # and the post-mortem wants min/max/mean.
            tel.histogram("checkpoint/save_ms").observe(
                (_time.perf_counter() - t0) * 1e3)
            # orbax's save just waited for the previous save internally,
            # so every EARLIER pending step is committed on disk; checksum
            # those on a background thread (pure file I/O — the hot loop
            # must not block on a full checkpoint read-back).
            committed, self._unmanifested = self._unmanifested, [step]
            if committed and jax.process_index() == 0:
                t = threading.Thread(target=self._write_manifests,
                                     args=(committed,), daemon=True,
                                     name="dtf_tpu-manifest")
                t.start()
                self._manifest_threads.append(t)
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def step_dir(self, step: int) -> Optional[str]:
        """The on-disk directory of a landed step, or None."""
        path = os.path.join(self.directory, str(step))
        return path if os.path.isdir(path) else None

    # -- integrity sidecar --------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, _MANIFEST_DIR, f"{step}.json")

    def _write_manifests(self, steps: List[int]) -> None:
        """Checksum COMMITTED steps to manifest sidecars (file I/O only —
        safe off-thread).  Must never raise: it also runs on the save hot
        path's background thread."""
        try:
            mdir = os.path.join(self.directory, _MANIFEST_DIR)
            os.makedirs(mdir, exist_ok=True)
            for step in steps:
                step_dir = self.step_dir(step)
                if step_dir is None:  # pruned by max_to_keep or failed
                    continue
                # nproc: elastic restarts restore on a DIFFERENT process
                # count than saved; recording the writer's makes the
                # reshard explicit (restore_robust logs it) instead of
                # silent.
                manifest = {"step": step, "nproc": jax.process_count(),
                            "files": _tree_manifest(step_dir)}
                if self._run_meta:
                    manifest["run"] = self._run_meta
                tmp = self._manifest_path(step) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(manifest, f)
                os.replace(tmp, self._manifest_path(step))
        except Exception as exc:      # missing manifest degrades, not fails
            log.warning("manifest write failed for steps %s: %s", steps, exc)

    def flush_manifests(self) -> None:
        """Synchronous settle point (wait()/restore paths): wait for
        pending async saves, join in-flight background manifest writers,
        manifest the remainder, prune stale sidecars.  Coordinator-only
        writes; every process waits so the barrier stays symmetric."""
        for t in self._manifest_threads:
            t.join()
        self._manifest_threads = []
        if self._unmanifested:
            self._mgr.wait_until_finished()
            pending, self._unmanifested = self._unmanifested, []
            if jax.process_index() == 0:
                self._write_manifests(pending)
        if jax.process_index() != 0:
            return
        # Prune sidecars whose checkpoint max_to_keep already deleted, so
        # manifests/ tracks the live steps instead of growing unbounded.
        mdir = os.path.join(self.directory, _MANIFEST_DIR)
        if not os.path.isdir(mdir):
            return
        live = {str(s) for s in self._mgr.all_steps()}
        for name in os.listdir(mdir):
            stem = name[:-len(".json")] if name.endswith(".json") else None
            if stem is not None and stem.isdigit() and stem not in live:
                try:
                    os.remove(os.path.join(mdir, name))
                except OSError:
                    pass

    def manifest_meta(self, step: int) -> dict:
        """The manifest's metadata (step, writer nproc) — {} when the
        manifest is missing or unreadable (legacy layout)."""
        try:
            with open(self._manifest_path(step)) as f:
                meta = json.load(f)
            meta.pop("files", None)
            return meta
        except (OSError, ValueError):
            return {}

    def _log_reshard(self, step: int) -> None:
        """Restoring under a different process count than the checkpoint's
        writer is the elastic-restart path: the state template just
        resharded the trajectory onto the current (usually shrunken) mesh.
        Loud by design — a silent topology change is how 'why is my step
        time different' mysteries are born."""
        meta = self.manifest_meta(step)
        saved_n = meta.get("nproc")
        if saved_n and saved_n != jax.process_count():
            log.warning(
                "elastic restore: checkpoint step %d was written by %d "
                "process(es), restored onto %d — state resharded onto the "
                "current mesh via the template", step, saved_n,
                jax.process_count())
        saved_run = meta.get("run") or {}
        cur_run = self._run_meta
        if saved_run and cur_run:
            if (saved_run.get("grad_sync") != cur_run.get("grad_sync")
                    and None not in (saved_run.get("grad_sync"),
                                     cur_run.get("grad_sync"))):
                log.warning(
                    "grad_sync restore: checkpoint step %d was saved under "
                    "--grad_sync %s, restoring under --grad_sync %s — "
                    "optimizer state converted between the dense and "
                    "sharded (zero1) layouts", step,
                    saved_run["grad_sync"], cur_run["grad_sync"])
            if (saved_run.get("data_axis") != cur_run.get("data_axis")
                    and None not in (saved_run.get("data_axis"),
                                     cur_run.get("data_axis"))):
                log.warning(
                    "grad_sync restore: checkpoint step %d was saved on a "
                    "%s-way data axis, restoring onto %s-way — sharded "
                    "optimizer state re-partitioned via the restore "
                    "template", step, saved_run["data_axis"],
                    cur_run["data_axis"])
            def _wire(run):
                # Canonicalize the manifest spelling ("bfloat16" and
                # "float32" were valid flag inputs, and manifests written
                # before the normalization recorded them raw) so alias
                # spellings can't fake a wire-format change.
                name = run.get("grad_comm_dtype")
                return {"bfloat16": "bf16", "float32": "f32"}.get(name, name)

            if (_wire(saved_run) != _wire(cur_run)
                    and None not in (_wire(saved_run), _wire(cur_run))):
                # Loud on purpose: the wire format changes the gradient
                # rounding noise, so a post-mortem comparing loss curves
                # across the restore needs this attribution line.
                log.warning(
                    "grad_comm_dtype restore: checkpoint step %d was "
                    "trained on a %s gradient wire, resuming on %s — "
                    "trajectory deltas past this point may be wire-format "
                    "noise, not regressions", step,
                    saved_run["grad_comm_dtype"],
                    cur_run["grad_comm_dtype"])
            if (saved_run.get("plan") != cur_run.get("plan")
                    and (saved_run.get("plan") is not None
                         or cur_run.get("plan") is not None)):
                # A planned<->manual transition (or a re-plan that chose
                # different knobs) changes the whole gradient path at
                # once; the attribution line names both sides.
                log.warning(
                    "plan restore: checkpoint step %d was saved under "
                    "plan %s, resuming under %s — the sharding plan "
                    "changed across the restore", step,
                    saved_run.get("plan") or "(manual)",
                    cur_run.get("plan") or "(manual)")

    def verify(self, step: int) -> tuple[bool, str]:
        """Check a landed step against its manifest.  (True, reason) means
        "no evidence of corruption" — a missing manifest (legacy layout or
        a crash before flush) passes here and relies on the restore
        try/except for protection."""
        step_dir = self.step_dir(step)
        if step_dir is None:
            return False, "step directory missing"
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            return True, "no manifest (unverified)"
        try:
            with open(mpath) as f:
                recorded = json.load(f)["files"]
        except (OSError, ValueError, KeyError) as exc:
            return True, f"unreadable manifest ({exc}); unverified"
        for rel, meta in recorded.items():
            path = os.path.join(step_dir, rel)
            if not os.path.exists(path):
                return False, f"missing file {rel}"
            if os.path.getsize(path) != meta["size"]:
                return False, f"size mismatch on {rel}"
            if _file_crc32(path) != meta["crc32"]:
                return False, f"crc mismatch on {rel}"
        return True, "manifest ok"

    # -- restore ------------------------------------------------------------

    def restore(self, state_template: Any,
                step: Optional[int] = None) -> tuple[Any, Optional[int]]:
        """Restore into the template's shapes/dtypes/shardings.  Returns
        (state, step) — (template, None) when nothing to restore."""
        self.flush_manifests()
        step = step if step is not None else self.latest_step()
        if step is None:
            return state_template, None
        with tel.span("checkpoint/restore", step=step):
            restored = self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(state_template))
        tel.counter("checkpoint/restores_total").inc()
        self._log_reshard(step)
        log.info("checkpoint restored from step %d", step)
        return restored, step

    def _first_verified(self, candidates: List[int]
                        ) -> tuple[Optional[int], Optional[str]]:
        """Newest candidate passing verification, with its verdict string;
        logs each rejected step."""
        for step in candidates:
            ok, why = self.verify(step)
            if ok:
                return step, why
            log.warning("checkpoint step %d failed verification (%s); "
                        "falling back to an older step", step, why)
        return None, None

    def restore_robust(self, state_template: Any,
                       max_step: Optional[int] = None
                       ) -> tuple[Any, Optional[int]]:
        """Restore the newest step that verifies AND restores cleanly,
        falling back past corrupt/partial steps (with a loud warning each
        time).  Returns (template, None) when no step survives — the
        caller decides whether a cold start is acceptable.

        A step whose manifest verifies INTACT but whose restore still
        raises is NOT corruption — it is a template mismatch (different
        model/optimizer, or a guard-counter schema change from toggling
        ``nonfinite_guard``): that error re-raises instead of silently
        cold-starting past a perfectly good trajectory.

        Multi-host: the orbax restore is a collective, so the step choice
        must be identical on every process — the coordinator verifies and
        broadcasts its pick (same rule as the preemption save's allgather;
        a process-local decision could deadlock hosts in different
        restores).  Manifests are written by the coordinator against the
        shared filesystem, so its verdict is the authoritative one."""
        self.flush_manifests()
        candidates = [s for s in reversed(self.all_steps())
                      if max_step is None or s <= max_step]
        had_any = bool(candidates)
        multi = jax.process_count() > 1
        if multi:
            import numpy as np
            from jax.experimental import multihost_utils
        while candidates:
            if multi:
                pick = np.asarray([-1, 0], np.int32)
                if jax.process_index() == 0:
                    s, why = self._first_verified(candidates)
                    if s is not None:
                        pick = np.asarray(
                            [s, 1 if why == "manifest ok" else 0], np.int32)
                pick = np.asarray(multihost_utils.broadcast_one_to_all(pick))
                step, verified = int(pick[0]), bool(pick[1])
                if step < 0:
                    break
            else:
                step, why = self._first_verified(candidates)
                if step is None:
                    break
                verified = (why == "manifest ok")
            def attempt_restore():
                restored, exc = None, None
                try:
                    with tel.span("checkpoint/restore", step=step):
                        restored = self._mgr.restore(
                            step,
                            args=self._ocp.args.StandardRestore(
                                state_template))
                except Exception as e:  # orbax raises many concrete types
                    exc = e
                deterministic = exc is not None
                if multi:
                    # The fallback decision must ALSO be symmetric: one
                    # host's per-shard read error while the others
                    # succeeded would desynchronize the loop into
                    # mismatched collectives.  Everyone agrees on this
                    # attempt's outcome; if any host failed, all discard
                    # together.  A template mismatch fails IDENTICALLY on
                    # every host, so a partial failure is by definition
                    # transient I/O, never schema.
                    oks = np.asarray(multihost_utils.process_allgather(
                        np.asarray([0 if exc is not None else 1],
                                   np.int32)))
                    deterministic = not oks.any()
                    if not oks.all() and exc is None:
                        exc = RuntimeError(
                            "restore failed on another process")
                        restored = None
                return restored, exc, deterministic

            restored, exc, deterministic = attempt_restore()
            if exc is not None and verified and deterministic:
                # An intact step that won't restore is ALMOST CERTAINLY a
                # template mismatch — but a transient I/O blip fails once
                # while a schema mismatch fails every time, so spend one
                # retry telling them apart before the no-restart raise.
                # (verified and deterministic agree on every host, so the
                # retry stays a symmetric collective.)
                log.warning("checkpoint step %d verified intact but failed "
                            "to restore (%s: %s); retrying once to rule "
                            "out a transient I/O error", step,
                            type(exc).__name__, exc)
                restored, exc, deterministic = attempt_restore()
            if exc is not None:
                if verified and deterministic:
                    raise CheckpointMismatchError(
                        f"checkpoint step {step} is verified intact "
                        f"(manifest checksums match) but failed to restore "
                        f"into the given state template — this is a "
                        f"template/schema mismatch (different model, "
                        f"optimizer, or nonfinite_guard setting than the "
                        f"run that saved it), not corruption; refusing to "
                        f"silently discard the trajectory") from exc
                log.warning("checkpoint step %d failed to restore (%s: %s); "
                            "falling back to an older step", step,
                            type(exc).__name__, exc)
                candidates = [s for s in candidates if s < step]
                continue
            tel.counter("checkpoint/restores_total").inc()
            self._log_reshard(step)
            log.info("checkpoint restored from step %d", step)
            return restored, step
        if had_any:
            log.error("no restorable checkpoint under %s", self.directory)
        return state_template, None

    def wait(self) -> None:
        """Block until pending async saves land (call before exit)."""
        self._mgr.wait_until_finished()
        self.flush_manifests()

    def close(self) -> None:
        self.wait()
        self._mgr.close()
