"""Fault-tolerant serving fleet: one acceptor, N engine replicas.

The single-engine TCP front end (serve/frontend.py) made the engine a
server; this module makes it a FLEET — the ROADMAP's "one front end, N
engine replicas" tier, built failure-first.  One acceptor fans client
requests out to N :class:`~dtf_tpu.serve.engine.ServingEngine` replicas
over the existing line-JSON TCP framing, and a replica is an EXPENDABLE
unit: the fleet serves through its death without losing one accepted
request.

Robustness layers (DESIGN.md §7.6):

* **Replica failure domains** — every replica beats per engine
  iteration (``resilience/health.py`` file transport and/or in-memory),
  and the acceptor detaches a replica on missed beats OR a
  response-stream timeout OR severed sockets.  Its accepted-but-
  unfinished requests are replayed on a survivor with the SAME
  fleet-minted rid, ``resubmit`` marked and the original ``trace_id``
  carried — replay is token-identical because per-request rng streams
  are (seed, rid)-keyed and every replica runs the same seed, and the
  acceptor skips (and VERIFIES) the tokens it already forwarded, so the
  client's stream is bitwise the uninterrupted one.
* **Routing as a control loop** — admission scores replicas on a
  composite of queue depth, brownout level, KV-pool pressure, SLO
  fast-burn (the ``{"stats": true}`` snapshot each replica's engine
  thread refreshes) and the acceptor's own in-flight count; transient
  connect errors retry with backoff; latency-critical priority classes
  get HEDGED dispatch — a duplicate leg on a second replica after a
  p99-derived delay, first stream wins, loser cancelled through the
  engine's real cancel path so its KV blocks free that iteration.
* **Fleet-level graceful degradation** — per-replica drain for rolling
  restarts (in-flight legs fail over on the ``drained`` terminal, the
  remainder checkpoints to ``drain.r<k>.jsonl``); when ALL live
  replicas are browned out, the acceptor itself sheds low-priority work
  (two-tier accounting: ``fleet/shed_acceptor_total`` vs
  ``fleet/shed_replica_total``); the rollup rides ``/fleetz``.
* **Replica-grade chaos** — ``replica_down@S[:P]`` /
  ``replica_wedge@S:DURms[:P]`` / ``conn_flake@S:P``
  (resilience/chaos.py), keyed on the acceptor's dispatch sequence.

Threading model: acceptor handler threads proxy requests and NEVER
touch an engine.  Local (in-process) replicas are all driven by ONE
round-robin driver thread calling each frontend's ``run_once`` — one
thread, because concurrently-booked goodput categories from N engine
threads would overcount wall-clock and fail the books gate on an honest
run.  Remote replicas are ``python -m dtf_tpu.serve --listen
--replica_index k`` processes reached by address; the acceptor carries
no model at all in that mode.

rid discipline (the latent collision this module fixes): rids are
per-engine, so two replicas' drain files merged naively can collide.
The acceptor mints FLEET-UNIQUE rids and maps them on the wire — a
client's own ``rid`` is echoed back to it, the fleet rid is what
replicas (and their ``drain.r<k>.jsonl`` namespaces) see.
:func:`merge_drain_docs` is the offline replay path's loud guard.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import json
import logging
import os
import queue
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dtf_tpu import telemetry as tel
from dtf_tpu.serve.frontend import MAX_LINE_BYTES, parse_request_line
from dtf_tpu.serve.paged_kv import chunk_digests

log = logging.getLogger("dtf_tpu")

#: Brownout ordinal at which a replica counts as degraded for the
#: acceptor-level brownout (serve/brownout.py LEVELS index of
#: "reject_low").
_DEGRADED_LEVEL = 2


@dataclasses.dataclass
class FleetConfig:
    """Acceptor policy knobs.  Defaults suit a production-ish wall-clock
    deployment; tests and the bench pin tighter timeouts."""
    #: priority >= this may hedge (duplicate dispatch after the delay)
    hedge_priority: int = 1
    #: fixed hedge delay; None = p99 of observed TTFT (floored below)
    hedge_delay_ms: Optional[float] = None
    hedge_min_delay_ms: float = 50.0
    #: per-event wait on a replica's response stream before the leg is
    #: declared wedged and failed over
    stream_timeout_s: float = 30.0
    connect_timeout_s: float = 2.0
    connect_retries: int = 2
    connect_backoff_s: float = 0.05
    #: a replica whose beat count has not advanced for this long is
    #: detached (observed-change discipline, same as resilience/health)
    beat_stale_s: float = 10.0
    monitor_interval_s: float = 0.25
    #: legs one request may burn before it fails loudly
    max_failovers: int = 3
    #: acceptor brownout sheds priority <= this when ALL replicas degrade
    shed_priority_max: int = 0
    #: grace window for a per-replica drain
    drain_timeout_s: float = 30.0
    #: prefix-affinity routing: leading chunks of the prompt hashed into
    #: a signature; a replica whose recent admissions share the longest
    #: signature prefix gets a small score bonus so same-prefix requests
    #: co-locate and hit the replica's prefix KV cache.  The bonus is a
    #: TIEBREAKER: max affinity_chunks * affinity_weight is far below
    #: the brownout/burn/pressure terms (25/15/10), so affinity never
    #: routes into a degraded replica.  0 chunks disables.
    affinity_chunks: int = 4
    affinity_chunk_tokens: int = 16
    affinity_weight: float = 1.0
    #: bound on each replica's hint table (recent admission signatures)
    affinity_hints: int = 64


class Replica:
    """One failure domain.  LOCAL replicas own an in-process engine +
    frontend (driven by the fleet's single driver thread); REMOTE
    replicas are an address only."""

    def __init__(self, index: int, address: Tuple[str, int], *,
                 frontend=None, engine=None, logdir: Optional[str] = None):
        self.index = index
        self.address = tuple(address)
        self.frontend = frontend
        self.engine = engine
        self.logdir = logdir
        self.state = "up"                  # up | draining | down
        self.down_reason: Optional[str] = None
        self.killed = False                # driver stops stepping it
        self.stats: dict = {}
        self.inflight = 0                  # acceptor-side live legs
        self.dispatched = 0
        self.failed_legs = 0
        self.leg_socks: set = set()
        self.beat_count: Optional[int] = None
        self.beat_changed = time.monotonic()
        self.beat_at_detach: Optional[int] = None
        # prefix-affinity hint table: chain digests of recent admissions'
        # leading prompt chunks, LRU-bounded (see FleetConfig.affinity_*).
        # Digests chain over ancestors, so membership of sig[i] implies a
        # recent admission shared the first i+1 chunks.
        self.prefix_hints: "collections.OrderedDict" = \
            collections.OrderedDict()

    def note_prefix(self, sig: Sequence[bytes], cap: int) -> None:
        """Record an admitted request's prefix signature (acceptor lock
        held by the caller)."""
        for d in sig:
            self.prefix_hints[d] = None
            self.prefix_hints.move_to_end(d)
        while len(self.prefix_hints) > cap:
            self.prefix_hints.popitem(last=False)

    def match_prefix(self, sig: Sequence[bytes]) -> int:
        """Longest signature prefix shared with a recent admission."""
        n = 0
        for d in sig:
            if d not in self.prefix_hints:
                break
            n += 1
        return n

    @property
    def local(self) -> bool:
        return self.frontend is not None

    def note_beat(self, count: int) -> None:
        """In-memory heartbeat sink for local replicas (the engine's
        per-iteration callback); remote beats arrive via the health-dir
        file transport instead."""
        if count != self.beat_count:
            self.beat_count = count
            self.beat_changed = time.monotonic()


class _LegError(OSError):
    """A dispatch leg could not be established."""


def merge_drain_docs(doc_sets: Sequence[Sequence[dict]]) -> List[dict]:
    """Merge per-replica drain namespaces (``drain.r<k>.jsonl``) into
    one replay set, FAILING LOUDLY on rid collisions.  Two standalone
    engines both mint rids from 0, so their drain files can collide —
    silently merging them would replay one request's rng stream under
    another's id and quietly break token identity.  An acceptor-run
    fleet never collides (rids are fleet-minted), so a collision here
    means the operator merged files from engines that were never behind
    one acceptor — exactly the mistake to refuse."""
    merged: Dict[int, dict] = {}
    for docs in doc_sets:
        for doc in docs:
            rid = int(doc["rid"])
            if rid in merged:
                raise ValueError(
                    f"rid collision merging drain docs: rid {rid} appears "
                    f"in more than one replica's namespace — these engines "
                    f"minted rids independently (not behind one acceptor); "
                    f"replay each drain.r<k>.jsonl separately, or re-serve "
                    f"through the fleet acceptor which mints fleet-unique "
                    f"rids")
            merged[rid] = doc
    return [merged[rid] for rid in sorted(merged)]


def read_drain_files(logdir: str) -> List[dict]:
    """Collect every ``drain.r<k>.jsonl`` under ``logdir`` through the
    collision guard — the cold-restart replay set."""
    sets = []
    for name in sorted(os.listdir(logdir) if os.path.isdir(logdir) else []):
        if name.startswith("drain.r") and name.endswith(".jsonl"):
            with open(os.path.join(logdir, name)) as f:
                sets.append([json.loads(ln) for ln in f if ln.strip()])
    return merge_drain_docs(sets)


class FleetAcceptor:
    """See module docstring.  Construct with replicas, :meth:`start`,
    point clients at :attr:`address`, :meth:`shutdown` when done."""

    def __init__(self, replicas: List[Replica], *,
                 host: str = "127.0.0.1", port: int = 0,
                 config: Optional[FleetConfig] = None,
                 chaos=None, logdir: Optional[str] = None,
                 health_dir: Optional[str] = None,
                 seed: int = 0):
        self.replicas = list(replicas)
        self.cfg = config or FleetConfig()
        self.chaos = chaos
        self.logdir = logdir
        self.seed = seed
        self._lock = threading.Lock()
        self._next_rid = 0
        self._seq = 0
        self._stop = threading.Event()
        self._flights: List[dict] = []
        self._inflight_count = 0
        self._ttft_ms: List[float] = []
        self._totals = {"accepted": 0, "completed": 0, "failovers": 0,
                        "replayed": 0, "hedged": 0, "hedge_wins": 0,
                        "hedge_cancelled": 0, "shed_acceptor": 0,
                        "shed_replica": 0, "lost_legs": 0}
        self._hb = None
        if health_dir:
            from dtf_tpu.resilience.health import FileHeartbeatTransport
            # index -1: the acceptor reads every hb_<k>, it never beats
            self._hb = FileHeartbeatTransport(health_dir, -1)
        # goodput booking: local replicas' engines book through the
        # driver thread; a pure-proxy acceptor (all replicas remote)
        # books its own wall in the monitor so report --check's books
        # gate holds on the acceptor logdir too
        self._book_wall = not any(r.local for r in self.replicas)
        tel.gauge("fleet/replicas").set(len(self.replicas))
        tel.gauge("fleet/replicas_up").set(len(self.replicas))

        acceptor = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                tel.counter("serve/conn_total").inc()
                try:
                    while not acceptor._stop.is_set():
                        line = self.rfile.readline(MAX_LINE_BYTES + 1)
                        if not line or len(line) > MAX_LINE_BYTES:
                            return
                        line = line.strip()
                        if not line:
                            continue
                        ctl = acceptor._maybe_control(line)
                        if ctl is not None:
                            self._send(ctl)
                            continue
                        try:
                            raw = json.loads(line.decode("utf-8"))
                            parsed = parse_request_line(line)
                        except (ValueError, UnicodeDecodeError) as exc:
                            tel.counter("serve/conn_errors_total").inc()
                            self._send({"error": str(exc)})
                            return
                        if not acceptor._proxy(self._send, raw, parsed):
                            return
                except (TimeoutError, OSError):
                    tel.counter("serve/conn_errors_total").inc()

            def _send(self, doc: dict) -> None:
                self.wfile.write(
                    (json.dumps(doc, sort_keys=True) + "\n").encode())
                self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.address = self.server.server_address
        self._threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetAcceptor":
        for r in self.replicas:
            if r.local:
                r.frontend._server_thread.start()
        self._threads = [
            threading.Thread(target=self.server.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             daemon=True, name="dtf-fleet-acceptor"),
            threading.Thread(target=self._monitor, daemon=True,
                             name="dtf-fleet-monitor"),
        ]
        if any(r.local for r in self.replicas):
            self._threads.append(
                threading.Thread(target=self._drive, daemon=True,
                                 name="dtf-fleet-driver"))
        for t in self._threads:
            t.start()
        return self

    def shutdown(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self.server.shutdown()
        self.server.server_close()
        for r in self.replicas:
            if r.local and not r.killed:
                try:
                    r.frontend.shutdown()
                except Exception:
                    pass
        for t in self._threads:
            t.join(timeout=5.0)

    # -- the single-thread local driver -------------------------------------

    def _drive(self) -> None:
        """Round-robin every live local replica from ONE thread (the
        goodput-books invariant; see module docstring)."""
        while not self._stop.is_set():
            progress = False
            for r in self.replicas:
                if not r.local or r.killed:
                    continue
                eng = r.engine
                try:
                    if eng._drain_requested and not eng.drained:
                        r.frontend._drain_mailbox()
                        eng.drain(self.cfg.drain_timeout_s)
                        self._finish_drain(r)
                        progress = True
                        continue
                    progress = r.frontend.run_once() or progress
                except Exception:
                    log.exception("[fleet] replica %d crashed in step",
                                  r.index)
                    self._kill_replica(r, reason="crashed")
            if not progress:
                t0 = time.perf_counter()
                self._stop.wait(0.004)
                tel.get_tracker().add("stall", time.perf_counter() - t0)

    def _finish_drain(self, r: Replica) -> None:
        """A drained replica leaves rotation; its unfinished requests
        checkpoint to the per-replica namespace AND fail over live (the
        ``drained`` terminals its legs just received)."""
        if self.logdir and r.engine.drain_docs:
            os.makedirs(self.logdir, exist_ok=True)
            path = os.path.join(self.logdir, f"drain.r{r.index}.jsonl")
            with open(path, "w") as f:
                for doc in r.engine.drain_docs:
                    f.write(json.dumps({**doc, "arrival_s": 0.0},
                                       sort_keys=True) + "\n")
        r.killed = True
        try:
            r.frontend.shutdown()       # abort_all -> "drained" terminals
        except Exception:
            pass
        self._mark_down(r, "drained")
        tel.counter("fleet/drains_total").inc()

    # -- replica state ------------------------------------------------------

    def _up_replicas(self, exclude=()) -> List[Replica]:
        return [r for r in self.replicas
                if r.state == "up" and r.index not in exclude]

    def _mark_down(self, r: Replica, reason: str) -> None:
        with self._lock:
            if r.state == "down":
                return
            r.state = "down"
            r.down_reason = reason
            r.beat_at_detach = r.beat_count
        tel.counter("fleet/detached_total").inc()
        tel.gauge("fleet/replicas_up").set(len(self._up_replicas()))
        # evidence instant for the incident correlator: a membership
        # change is a prime suspect for any latency anomaly that follows
        tel.instant("event/fleet_detach", replica=r.index, reason=reason)
        log.warning("[fleet] replica %d detached (%s)", r.index, reason)

    def _rejoin(self, r: Replica) -> None:
        with self._lock:
            r.state = "up"
            r.down_reason = None
        tel.counter("fleet/rejoined_total").inc()
        tel.gauge("fleet/replicas_up").set(len(self._up_replicas()))
        log.warning("[fleet] replica %d rejoined (beats resumed)", r.index)

    def _kill_replica(self, r: Replica, reason: str = "killed") -> None:
        """replica_down semantics: abrupt, no drain, no goodbye."""
        r.killed = True
        if r.local:
            try:
                r.frontend.kill()
            except Exception:
                pass
        self._sever_legs(r)
        self._mark_down(r, reason)

    def _wedge_replica(self, r: Replica, duration_s: float) -> None:
        tel.counter("fleet/replica_wedged_total").inc()
        if r.local:
            r.frontend.wedge_until = time.monotonic() + duration_s
            return
        try:
            self._control_roundtrip(r, {"wedge_ms": duration_s * 1e3})
        except OSError:
            log.warning("[fleet] replica %d unreachable for wedge",
                        r.index)

    def _flake_replica(self, r: Replica) -> None:
        tel.counter("fleet/conn_flakes_total").inc()
        self._sever_legs(r)

    def _sever_legs(self, r: Replica) -> None:
        with self._lock:
            socks = list(r.leg_socks)
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def drain_replica(self, index: int) -> None:
        """Rolling restart, step 1: freeze replica ``index``'s front
        door.  In-flight legs fail over on their ``drained`` terminals;
        the remainder checkpoints to ``drain.r<k>.jsonl``.  Remote
        replicas are drained by the operator (SIGTERM to the process) —
        the acceptor reacts identically either way."""
        r = self.replicas[index]
        if not r.local:
            raise ValueError(
                f"replica {index} is remote; send SIGTERM to its process "
                f"instead (the acceptor fails over on its drained "
                f"terminals either way)")
        with self._lock:
            if r.state == "up":
                r.state = "draining"
        r.engine.request_drain()

    # -- monitor: stats polling, beat staleness, wall booking ---------------

    def _monitor(self) -> None:
        last = time.perf_counter()
        while not self._stop.is_set():
            for r in self.replicas:
                if r.state == "down" or not r.local and r.killed:
                    continue
                if r.local:
                    # stats snapshot is a plain attribute the replica's
                    # engine thread refreshes — no socket needed
                    r.stats = dict(r.frontend.stats)
                else:
                    try:
                        doc = self._control_roundtrip(r, {"stats": True})
                        r.stats = doc.get("stats", {}) or {}
                    except OSError:
                        r.failed_legs += 1
            self._check_beats()
            # incident plane: fleet membership into the changepoint
            # detector.  A replica dropping out is a step the latency
            # planes may never see (a warm survivor absorbs the load
            # with no client-visible latency), so the monitor watches
            # the up-count itself; the correlator then decides whether
            # chaos or an innocent stale-beat detach owns the drop.
            from dtf_tpu.telemetry import anomaly as _anomaly
            _anomaly.observe("serve/fleet_up_replicas",
                             float(sum(1 for rr in self.replicas
                                       if rr.state == "up")))
            now = time.perf_counter()
            if self._book_wall:
                cat = "productive" if self._inflight_count else "stall"
                tel.get_tracker().add(cat, now - last)
            last = now
            self._stop.wait(self.cfg.monitor_interval_s)

    def _check_beats(self) -> None:
        """Missed-beat detachment + beat-resumption rejoin, observed-
        change discipline: only an ADVANCING count proves liveness."""
        file_beats: Dict[int, int] = {}
        if self._hb is not None:
            try:
                file_beats = self._hb.read_beats()
            except OSError:
                pass
        now = time.monotonic()
        for r in self.replicas:
            count = file_beats.get(r.index, r.beat_count)
            if count is not None and count != r.beat_count:
                r.beat_count = count
                r.beat_changed = now
            if r.killed:
                continue
            stale = (now - r.beat_changed) > self.cfg.beat_stale_s
            if r.state == "up" and stale and r.beat_count is not None:
                self._mark_down(r, "stale_beats")
            elif (r.state == "down"
                  and r.down_reason in ("stale_beats", "unreachable")
                  and r.beat_count is not None
                  and r.beat_count != r.beat_at_detach):
                self._rejoin(r)

    # -- routing ------------------------------------------------------------

    def _prefix_sig(self, raw: dict) -> List[bytes]:
        """Leading-chunk hash chain of the request's prompt — the
        prefix-affinity routing key.  Fixed chunk size (NOT the
        replicas' block size: the acceptor may carry no model at all),
        chained like serve/paged_kv.chunk_digests so a match on chunk i
        implies a match on every earlier chunk."""
        cfg = self.cfg
        if cfg.affinity_chunks <= 0:
            return []
        prompt = raw.get("prompt") or []
        if not isinstance(prompt, (list, tuple)):
            return []
        n = min(cfg.affinity_chunks,
                len(prompt) // cfg.affinity_chunk_tokens)
        if n <= 0:
            return []
        try:
            return chunk_digests([int(t) for t in prompt],
                                 cfg.affinity_chunk_tokens, n)
        except (TypeError, ValueError):
            return []

    def _score(self, r: Replica,
               prefix_sig: Sequence[bytes] = ()) -> float:
        s = r.stats or {}
        base = (float(s.get("queue_depth", 0))
                + 2.0 * float(s.get("active", 0))
                + 25.0 * float(s.get("brownout_level", 0))
                + 10.0 * float(s.get("kv_pool_frac", 0.0))
                + 15.0 * float(s.get("slo_fast_firing", 0))
                + 2.0 * r.inflight)
        if prefix_sig:
            with self._lock:
                matched = r.match_prefix(prefix_sig)
            base -= self.cfg.affinity_weight * matched
        return base

    def _route(self, exclude=(),
               prefix_sig: Sequence[bytes] = ()) -> Optional[Replica]:
        cands = self._up_replicas(exclude)
        if not cands:
            cands = self._up_replicas()
        if not cands:
            return None
        return min(cands, key=lambda r: self._score(r, prefix_sig))

    def _fleet_degraded(self) -> bool:
        up = self._up_replicas()
        return bool(up) and all(
            int((r.stats or {}).get("brownout_level", 0)) >= _DEGRADED_LEVEL
            for r in up)

    def _hedge_delay_s(self) -> float:
        if self.cfg.hedge_delay_ms is not None:
            return self.cfg.hedge_delay_ms / 1e3
        with self._lock:
            samples = list(self._ttft_ms)
        if len(samples) >= 8:
            return max(self.cfg.hedge_min_delay_ms,
                       float(np.percentile(samples, 99))) / 1e3
        return self.cfg.hedge_min_delay_ms / 1e3

    # -- control lines to replicas / from clients ---------------------------

    def _control_roundtrip(self, r: Replica, doc: dict,
                           timeout: Optional[float] = None) -> dict:
        with socket.create_connection(
                r.address, timeout=timeout or self.cfg.connect_timeout_s
        ) as s:
            s.settimeout(timeout or self.cfg.connect_timeout_s)
            s.sendall((json.dumps(doc) + "\n").encode())
            line = s.makefile("rb").readline(MAX_LINE_BYTES)
        if not line:
            raise OSError("empty control reply")
        return json.loads(line)

    def _maybe_control(self, line: bytes) -> Optional[dict]:
        try:
            doc = json.loads(line.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            return None
        if isinstance(doc, dict) and "stats" in doc and "prompt" not in doc:
            return {"ok": True, "fleet": self.rollup()}
        return None

    # -- the proxy path (handler threads) -----------------------------------

    def _admit(self, raw: dict, parsed: dict):
        """Mint the fleet rid, fire dispatch-sequence chaos, apply the
        acceptor-level brownout.  Returns (flight, shed_terminal)."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._seq += 1
            seq = self._seq
        if self.chaos is not None:
            down = self.chaos.maybe_replica_down(seq)
            if down is not None and down < len(self.replicas):
                self._kill_replica(self.replicas[down], reason="chaos_kill")
            wedge = self.chaos.maybe_replica_wedge(seq)
            if wedge is not None and wedge[0] < len(self.replicas):
                self._wedge_replica(self.replicas[wedge[0]], wedge[1])
            flake = self.chaos.maybe_conn_flake(seq)
            if flake is not None and flake < len(self.replicas):
                self._flake_replica(self.replicas[flake])
        client_rid = raw.get("rid")
        out_rid = client_rid if client_rid is not None else rid
        fl = {"rid": rid, "out_rid": out_rid,
              "trace_id": parsed["trace_id"],
              "priority": parsed.get("priority", 0),
              "t_accept": time.monotonic(), "t_first": None,
              "t_done": None, "status": None, "n_tokens": 0,
              "failovers": 0, "hedged": False}
        if not self._up_replicas():
            return fl, {"rid": out_rid, "status": "shed_fleet_no_replicas",
                        "reason": "no live replicas",
                        "trace_id": fl["trace_id"]}
        if (self._fleet_degraded()
                and fl["priority"] <= self.cfg.shed_priority_max):
            return fl, {"rid": out_rid, "status": "shed_fleet_brownout",
                        "reason": "all replicas degraded",
                        "trace_id": fl["trace_id"]}
        return fl, None

    def _proxy(self, send, raw: dict, parsed: dict) -> bool:
        """Serve one client request end-to-end: route, stream, fail
        over, hedge.  Returns False when the client connection should
        close."""
        cfg = self.cfg
        fl, shed = self._admit(raw, parsed)
        if shed is not None:
            with self._lock:
                self._totals["shed_acceptor"] += 1
                fl["status"] = shed["status"]
                fl["t_done"] = time.monotonic()
                self._flights.append(fl)
            tel.counter("fleet/shed_acceptor_total").inc()
            try:
                send(shed)
            except OSError:
                return False
            return True
        with self._lock:
            self._totals["accepted"] += 1
            self._inflight_count += 1
            self._flights.append(fl)
        tel.counter("fleet/accepted_total").inc()
        try:
            return self._stream(send, raw, fl)
        finally:
            with self._lock:
                self._inflight_count -= 1
                if fl["t_done"] is None:
                    fl["t_done"] = time.monotonic()

    def _wire_doc(self, raw: dict, fl: dict, *, resubmit: bool) -> bytes:
        doc = {k: v for k, v in raw.items()
               if k not in ("rid", "resubmit", "trace_id")}
        doc["rid"] = fl["rid"]
        doc["trace_id"] = fl["trace_id"]
        if resubmit:
            doc["resubmit"] = True
        return (json.dumps(doc) + "\n").encode("utf-8")

    def _open_leg(self, r: Replica, payload: bytes) -> socket.socket:
        last: Optional[OSError] = None
        for attempt in range(self.cfg.connect_retries + 1):
            try:
                s = socket.create_connection(
                    r.address, timeout=self.cfg.connect_timeout_s)
                s.settimeout(self.cfg.stream_timeout_s)
                s.sendall(payload)
                return s
            except OSError as exc:
                last = exc
                tel.counter("fleet/conn_retries_total").inc()
                time.sleep(self.cfg.connect_backoff_s * (2 ** attempt))
        # connect budget exhausted: the replica is unreachable — a
        # SIGKILLed process refuses connections long before its beats
        # read stale
        self._mark_down(r, "unreachable")
        raise _LegError(str(last))

    def _stream(self, send, raw: dict, fl: dict) -> bool:
        cfg = self.cfg
        out_q: "queue.Queue" = queue.Queue()
        legs: Dict[int, dict] = {}
        leg_ids = itertools.count()
        tried: set = set()
        forwarded: List[int] = []
        winner: Optional[int] = None
        prefix_sig = self._prefix_sig(raw)

        def reader(leg_id: int, sock: socket.socket) -> None:
            try:
                for line in sock.makefile("rb"):
                    try:
                        out_q.put((leg_id, json.loads(line)))
                    except ValueError:
                        break              # garbled stream = failed leg
            except OSError:
                pass
            out_q.put((leg_id, None))

        def launch(r: Replica, *, resubmit: bool, skip: int,
                   hedge: bool = False) -> None:
            sock = self._open_leg(r, self._wire_doc(raw, fl,
                                                    resubmit=resubmit))
            leg_id = next(leg_ids)
            leg = {"replica": r, "sock": sock, "skip": skip,
                   "skipped": 0, "hedge": hedge}
            legs[leg_id] = leg
            tried.add(r.index)
            with self._lock:
                r.leg_socks.add(sock)
                r.inflight += 1
                r.dispatched += 1
                if prefix_sig:
                    r.note_prefix(prefix_sig, self.cfg.affinity_hints)
            threading.Thread(target=reader, args=(leg_id, sock),
                             daemon=True).start()

        def close_leg(leg_id: int) -> None:
            leg = legs.pop(leg_id, None)
            if leg is None:
                return
            r = leg["replica"]
            with self._lock:
                r.leg_socks.discard(leg["sock"])
                r.inflight = max(0, r.inflight - 1)
            try:
                leg["sock"].close()
            except OSError:
                pass

        def cancel_leg(leg_id: int) -> None:
            leg = legs.get(leg_id)
            if leg is None:
                return
            r = leg["replica"]
            close_leg(leg_id)
            # the loser's handler is mid-stream, so the cancel rides a
            # fresh control connection; the engine's cancel path frees
            # the loser's KV blocks that iteration
            try:
                self._control_roundtrip(r, {"cancel": fl["rid"]})
            except OSError:
                pass

        def fail_over(from_leg: Optional[int]) -> bool:
            nonlocal winner
            if from_leg is not None:
                legs[from_leg]["replica"].failed_legs += 1
                close_leg(from_leg)
            winner = None
            while fl["failovers"] < cfg.max_failovers:
                fl["failovers"] += 1
                with self._lock:
                    self._totals["failovers"] += 1
                tel.counter("fleet/failovers_total").inc()
                tel.instant("event/fleet_failover", rid=fl["rid"],
                            attempt=fl["failovers"])
                nxt = self._route(exclude=tried, prefix_sig=prefix_sig)
                if nxt is None:
                    return False
                try:
                    launch(nxt, resubmit=True, skip=len(forwarded))
                except _LegError:
                    continue
                with self._lock:
                    self._totals["replayed"] += 1
                tel.counter("fleet/replayed_total").inc()
                return True
            return False

        def finish(status: str, doc: Optional[dict] = None) -> bool:
            fl["status"] = status
            fl["t_done"] = time.monotonic()
            fl["n_tokens"] = len(forwarded)
            for leg_id in list(legs):
                cancel_leg(leg_id)
            if status == "completed":
                with self._lock:
                    self._totals["completed"] += 1
                    if fl["t_first"] is not None:
                        self._ttft_ms.append(
                            (fl["t_first"] - fl["t_accept"]) * 1e3)
                tel.counter("fleet/completed_total").inc()
            elif status.startswith("shed_") or status.startswith("rejected"):
                with self._lock:
                    self._totals["shed_replica"] += 1
                tel.counter("fleet/shed_replica_total").inc()
            out = doc or {"rid": fl["out_rid"], "status": status,
                          "n_tokens": len(forwarded),
                          "trace_id": fl["trace_id"]}
            try:
                send(out)
            except OSError:
                return False
            return True

        primary = self._route(prefix_sig=prefix_sig)
        if primary is None:
            return finish("shed_fleet_no_replicas")
        try:
            launch(primary, resubmit=bool(raw.get("resubmit")), skip=0)
        except _LegError:
            if not fail_over(None):
                return finish("failed_failover_exhausted")
        hedge_at: Optional[float] = None
        if (fl["priority"] >= cfg.hedge_priority
                and len(self._up_replicas()) > 1):
            hedge_at = time.monotonic() + self._hedge_delay_s()

        while True:
            tmo = 0.25
            if hedge_at is not None:
                tmo = min(tmo, max(0.002, hedge_at - time.monotonic()))
            try:
                leg_id, ev = out_q.get(timeout=tmo)
            except queue.Empty:
                if (hedge_at is not None and winner is None
                        and not forwarded
                        and time.monotonic() >= hedge_at):
                    hedge_at = None
                    nxt = self._route(exclude=tried,
                                      prefix_sig=prefix_sig)
                    if nxt is not None:
                        try:
                            launch(nxt, resubmit=False, skip=0, hedge=True)
                            fl["hedged"] = True
                            with self._lock:
                                self._totals["hedged"] += 1
                            tel.counter("fleet/hedged_total").inc()
                        except _LegError:
                            pass
                if not legs:
                    # every leg is gone and nothing replaced them
                    if not fail_over(None):
                        return finish("failed_failover_exhausted")
                continue
            if leg_id not in legs:
                continue                   # cancelled loser's straggler
            if ev is None or "error" in ev:
                # leg died: conn severed / stream timeout / replica error
                with self._lock:
                    self._totals["lost_legs"] += 1
                if winner is None or winner == leg_id:
                    if not fail_over(leg_id):
                        return finish("failed_failover_exhausted")
                else:
                    close_leg(leg_id)
                continue
            if "status" in ev and ev["status"] in ("drained",
                                                   "server_shutdown"):
                # graceful exit under us: replay on a survivor
                if winner is None or winner == leg_id:
                    if not fail_over(leg_id):
                        return finish("failed_failover_exhausted")
                else:
                    close_leg(leg_id)
                continue
            if winner is None:
                winner = leg_id
                if legs[leg_id]["hedge"]:
                    with self._lock:
                        self._totals["hedge_wins"] += 1
                    tel.counter("fleet/hedge_wins_total").inc()
                for other in [k for k in legs if k != winner]:
                    with self._lock:
                        self._totals["hedge_cancelled"] += 1
                    tel.counter("fleet/hedge_cancelled_total").inc()
                    cancel_leg(other)
            if leg_id != winner:
                continue
            if "token" in ev:
                leg = legs[leg_id]
                if leg["skipped"] < leg["skip"]:
                    # replayed prefix: MUST match what the client already
                    # has — token identity across the failover is the
                    # contract, and a mismatch is a correctness bug to
                    # fail loudly, not paper over
                    if ev["token"] != forwarded[leg["skipped"]]:
                        tel.counter("fleet/replay_mismatch_total").inc()
                        log.error(
                            "[fleet] replay divergence rid=%d pos=%d: "
                            "%r != %r", fl["rid"], leg["skipped"],
                            ev["token"], forwarded[leg["skipped"]])
                        return finish("failed_replay_mismatch")
                    leg["skipped"] += 1
                    continue
                if fl["t_first"] is None:
                    fl["t_first"] = time.monotonic()
                forwarded.append(ev["token"])
                try:
                    send({"rid": fl["out_rid"], "token": ev["token"],
                          "done": bool(ev.get("done"))})
                except OSError:
                    # client went away: cancel every leg so no replica
                    # pins KV for a vanished reader
                    for lid in list(legs):
                        cancel_leg(lid)
                    fl["status"] = "client_gone"
                    return False
                continue
            if "status" in ev:
                st = ev["status"]
                close_leg(leg_id)
                return finish(st, {"rid": fl["out_rid"], "status": st,
                                   "n_tokens": len(forwarded),
                                   "trace_id": fl["trace_id"]})

    # -- rollup / summary ---------------------------------------------------

    def totals(self) -> dict:
        with self._lock:
            return dict(self._totals)

    def arm_chaos(self, plan) -> None:
        """Arm (or swap) a fault plan mid-run, restarting the dispatch
        sequence the ``@S`` step keys count — so a bench can warm the
        fleet first and still write specs against MEASURED dispatches."""
        with self._lock:
            self.chaos = plan
            self._seq = 0

    def rollup(self) -> dict:
        """The ``/fleetz`` payload: one consistent cut of per-replica
        state + acceptor totals (everything under the acceptor lock)."""
        now = time.monotonic()
        with self._lock:
            replicas = {
                str(r.index): {
                    "state": r.state,
                    "down_reason": r.down_reason,
                    "address": list(r.address),
                    "local": r.local,
                    "inflight": r.inflight,
                    "dispatched": r.dispatched,
                    "failed_legs": r.failed_legs,
                    "beat_count": r.beat_count,
                    "beat_age_s": round(now - r.beat_changed, 3),
                    "prefix_hints": len(r.prefix_hints),
                    "stats": r.stats,
                } for r in self.replicas}
            totals = dict(self._totals)
        return {"fleet": "serving", "replicas": replicas,
                "up": len(self._up_replicas()),
                "size": len(self.replicas),
                "totals": totals, "written_unix": time.time()}

    def summary(self, slo_ttft_ms: Optional[float] = None) -> dict:
        """Acceptor-side serving summary — same gate keys the engine's
        summary feeds (``goodput_qps`` / ``ttft_ms_p99`` / ...), measured
        where the client sees them: at the fleet's front door."""
        with self._lock:
            flights = [dict(f) for f in self._flights]
            totals = dict(self._totals)
        done = [f for f in flights if f["t_done"] is not None]
        completed = [f for f in done if f["status"] == "completed"]
        ttfts = [(f["t_first"] - f["t_accept"]) * 1e3 for f in completed
                 if f["t_first"] is not None]
        out = {
            "mode": "fleet",
            "replicas": len(self.replicas),
            "replicas_up": len(self._up_replicas()),
            "accepted": totals["accepted"],
            "completed": len(completed),
            "shed": totals["shed_acceptor"] + totals["shed_replica"],
            "shed_acceptor": totals["shed_acceptor"],
            "shed_replica": totals["shed_replica"],
            "failed": sum(1 for f in done
                          if (f["status"] or "").startswith("failed")),
            "failovers": totals["failovers"],
            "replays": totals["replayed"],
            "hedged": totals["hedged"],
            "hedge_wins": totals["hedge_wins"],
            "hedge_cancelled": totals["hedge_cancelled"],
            "tokens_out": sum(f["n_tokens"] for f in completed),
        }
        if ttfts:
            out["ttft_ms_p50"] = float(np.percentile(ttfts, 50))
            out["ttft_ms_p99"] = float(np.percentile(ttfts, 99))
        if done:
            span = (max(f["t_done"] for f in done)
                    - min(f["t_accept"] for f in done))
            out["makespan_s"] = round(max(span, 1e-9), 6)
            out["completed_qps"] = round(len(completed) / max(span, 1e-9),
                                         4)
        if slo_ttft_ms is not None:
            good = [f for f in completed
                    if f["t_first"] is not None
                    and (f["t_first"] - f["t_accept"]) * 1e3 <= slo_ttft_ms]
            out["slo_ttft_ms"] = slo_ttft_ms
            out["slo_attainment"] = (round(len(good) / len(completed), 4)
                                     if completed else None)
            if done:
                out["goodput_qps"] = round(
                    len(good) / max(max(f["t_done"] for f in done)
                                    - min(f["t_accept"] for f in done),
                                    1e-9), 4)
        return out

    def write_telemetry(self, logdir: str,
                        slo_ttft_ms: Optional[float] = None,
                        extra: Optional[dict] = None) -> str:
        os.makedirs(logdir, exist_ok=True)
        serving = self.summary(slo_ttft_ms)
        serving["fleet"] = self.rollup()
        if extra:
            serving.update(extra)
        return tel.write_telemetry_json(logdir, extra={"serving": serving})


# -- local fleet construction ----------------------------------------------

def build_local_fleet(model, params, n_replicas: int, *,
                      seed: int = 0, host: str = "127.0.0.1", port: int = 0,
                      config: Optional[FleetConfig] = None,
                      chaos=None, logdir: Optional[str] = None,
                      health_dir: Optional[str] = None,
                      conn_timeout_s: float = 30.0,
                      brownout: bool = False,
                      slo_ttft_ms: float = 500.0,
                      degrade_max_new: int = 8,
                      engine_kwargs: Optional[dict] = None) -> FleetAcceptor:
    """N in-process replicas (one engine + TCP frontend each, ALL on the
    same seed — the token-identity precondition) behind one acceptor.
    The caller must :meth:`FleetAcceptor.start` it."""
    from dtf_tpu.serve import WallClock
    from dtf_tpu.serve.engine import ServingEngine
    from dtf_tpu.serve.frontend import TCPFrontend
    from dtf_tpu.telemetry.slo import BurnRateMonitor

    kw = dict(engine_kwargs or {})
    replicas: List[Replica] = []
    for k in range(n_replicas):
        beats = None
        if health_dir:
            from dtf_tpu.resilience.health import FileHeartbeatTransport
            transport = FileHeartbeatTransport(health_dir, k)
            beats = transport.beat
        # brownout controller + SLO burn monitor are PER-REPLICA state
        # (hysteresis and burn windows must not be shared)
        bo = None
        if brownout:
            from dtf_tpu.serve import BrownoutController
            bo = BrownoutController(slo_ttft_ms,
                                    degrade_max_new=degrade_max_new)
        engine = ServingEngine(model, params, seed=seed, clock=WallClock(),
                               brownout=bo,
                               slo=BurnRateMonitor.for_serving(slo_ttft_ms),
                               **kw)
        replica = Replica(k, ("127.0.0.1", 0))
        inner = beats

        def heartbeat(count, _r=replica, _inner=inner):
            _r.note_beat(count)
            if _inner is not None:
                _inner(count)

        engine.heartbeat = heartbeat
        frontend = TCPFrontend(engine, "127.0.0.1", 0,
                               conn_timeout_s=conn_timeout_s)
        replica.frontend = frontend
        replica.engine = engine
        replica.address = tuple(frontend.address)
        replicas.append(replica)
    return FleetAcceptor(replicas, host=host, port=port, config=config,
                         chaos=chaos, logdir=logdir, health_dir=health_dir,
                         seed=seed)


def connect_remote_fleet(addresses: Sequence[Tuple[str, int]], *,
                         host: str = "127.0.0.1", port: int = 0,
                         config: Optional[FleetConfig] = None,
                         chaos=None, logdir: Optional[str] = None,
                         health_dir: Optional[str] = None,
                         seed: int = 0) -> FleetAcceptor:
    """Acceptor over already-running ``python -m dtf_tpu.serve --listen
    --replica_index k`` processes.  The acceptor carries no model; all
    replicas must share one ``--seed`` (token identity) and, for
    missed-beat detection, one ``--health_dir``."""
    replicas = [Replica(k, addr) for k, addr in enumerate(addresses)]
    return FleetAcceptor(replicas, host=host, port=port, config=config,
                         chaos=chaos, logdir=logdir, health_dir=health_dir,
                         seed=seed)


# -- trace-driving client (bench / scenario / CI lane) ----------------------

def drive_trace(address: Tuple[str, int], trace, *,
                request_timeout_s: float = 120.0,
                time_scale: float = 1.0) -> Dict[int, dict]:
    """Replay a ``poisson_trace``-shaped trace against a fleet (or
    single-replica) front door over real sockets, one connection per
    request, pacing arrivals on the wall clock.  Returns per-trace-index
    records with the client-side latency marks — the fleet summary's
    ground truth is measured HERE, where the user sits."""
    results: Dict[int, dict] = {}
    threads: List[threading.Thread] = []

    def one(i: int, kw: dict) -> None:
        rec: dict = {"status": None, "tokens": [], "t_send": None,
                     "t_first": None, "t_done": None, "trace_id": None}
        results[i] = rec
        doc = {"prompt": [int(x) for x in kw["prompt"]],
               "max_new_tokens": int(kw["max_new_tokens"]),
               "temperature": float(kw.get("temperature", 0.0)),
               "trace_id": kw.get("trace_id") or f"drv-{i:05d}"}
        if kw.get("deadline_ms") is not None:
            doc["deadline_ms"] = float(kw["deadline_ms"])
        if kw.get("priority") is not None:
            doc["priority"] = int(kw.get("priority", 0))
        rec["trace_id"] = doc["trace_id"]
        try:
            with socket.create_connection(address, timeout=10.0) as s:
                s.settimeout(request_timeout_s)
                rec["t_send"] = time.monotonic()
                s.sendall((json.dumps(doc) + "\n").encode())
                for line in s.makefile("rb"):
                    ev = json.loads(line)
                    if "error" in ev:
                        rec["status"] = f"error:{ev['error']}"
                        break
                    if "token" in ev:
                        if rec["t_first"] is None:
                            rec["t_first"] = time.monotonic()
                        rec["tokens"].append(int(ev["token"]))
                    if "status" in ev:
                        rec["status"] = ev["status"]
                        rec["t_done"] = time.monotonic()
                        break
        except (OSError, ValueError) as exc:
            if rec["status"] is None:
                rec["status"] = f"conn_error:{type(exc).__name__}"

    t0 = time.monotonic()
    for i, (t_arr, kw) in enumerate(trace):
        delay = t0 + t_arr * time_scale - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=one, args=(i, kw), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=request_timeout_s + 15.0)
    return results


def client_summary(results: Dict[int, dict], *,
                   slo_ttft_ms: float) -> dict:
    """Client-side serving summary over :func:`drive_trace` records —
    the A/B's measurement arm (both arms measured identically)."""
    done = [r for r in results.values() if r["t_done"] is not None]
    completed = [r for r in done if r["status"] == "completed"]
    lost = [i for i, r in results.items() if r["t_done"] is None]
    ttfts = [(r["t_first"] - r["t_send"]) * 1e3 for r in completed
             if r["t_first"] is not None and r["t_send"] is not None]
    out = {"offered": len(results), "completed": len(completed),
           "lost": len(lost), "lost_indices": lost[:8],
           "statuses": {}, "slo_ttft_ms": slo_ttft_ms,
           "tokens_out": sum(len(r["tokens"]) for r in completed)}
    for r in results.values():
        st = r["status"] or "no_terminal"
        out["statuses"][st] = out["statuses"].get(st, 0) + 1
    if ttfts:
        out["ttft_ms_p50"] = float(np.percentile(ttfts, 50))
        out["ttft_ms_p99"] = float(np.percentile(ttfts, 99))
    if done:
        sends = [r["t_send"] for r in done if r["t_send"] is not None]
        span = max(r["t_done"] for r in done) - min(sends)
        out["makespan_s"] = round(max(span, 1e-9), 6)
        good = sum(1 for r in completed
                   if r["t_first"] is not None and r["t_send"] is not None
                   and (r["t_first"] - r["t_send"]) * 1e3 <= slo_ttft_ms)
        out["goodput_qps"] = round(good / max(span, 1e-9), 4)
        out["completed_qps"] = round(len(completed) / max(span, 1e-9), 4)
    else:
        out["goodput_qps"] = 0.0
    return out
