"""Brownout overload controller: degrade progressively, never fall over.

The serving engine's only overload responses used to be queue rejection
(loud but binary) and growing latency (silent SLO death).  This
controller watches a smoothed p99 TTFT against the SLO budget and walks
a small state machine of progressively cheaper service levels::

    level 0  normal      full service
    level 1  degrade     max_new_tokens clamped to ``degrade_max_new``
                         (shorter answers, same admission)
    level 2  reject_low  level 1 + low-priority submissions
                         (priority <= ``low_priority_max``) are shed
    level 3  reject_all  no new admissions at all; in-flight requests
                         and the already-admitted queue still finish

Escalation/de-escalation is hysteretic: the controller escalates one
level when the signal exceeds ``enter_ratio * slo`` and de-escalates one
level when it falls under ``exit_ratio * slo``, and either transition
must be ``dwell_iters`` engine iterations after the previous one — so a
single outlier cannot flap the service level.

The signal is ``max(EWMA of windowed p99 TTFT, current head-of-queue
wait)``.  The second term is the early-warning half: under a hard spike
nothing completes, so TTFT observations stop arriving exactly when the
controller most needs to act — but the oldest queued request's wait
keeps rising and bounds every future TTFT from below.

Deliberately jax-free and clock-agnostic (the engine feeds it instants
from its own wall/virtual clock), so controller behavior is exactly
reproducible under the seeded VirtualClock — the chaos lane asserts the
controller-on vs controller-off goodput A/B against it.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

LEVELS = ("normal", "degrade", "reject_low", "reject_all")


class BrownoutController:
    """See module docstring.  The engine calls :meth:`observe_ttft` as
    first tokens land, :meth:`update` once per iteration, and consults
    :meth:`max_new_cap` / :meth:`submit_verdict` at admission time."""

    def __init__(self, slo_ttft_ms: float, *,
                 enter_ratio: float = 1.0,
                 exit_ratio: float = 0.5,
                 dwell_iters: int = 8,
                 window: int = 32,
                 ewma_alpha: float = 0.3,
                 degrade_max_new: int = 8,
                 low_priority_max: int = 0,
                 idle_decay: float = 0.93):
        if slo_ttft_ms <= 0:
            raise ValueError(f"slo_ttft_ms must be > 0, got {slo_ttft_ms}")
        if not 0 < exit_ratio < enter_ratio:
            raise ValueError(
                f"hysteresis needs 0 < exit_ratio < enter_ratio, got "
                f"exit={exit_ratio} enter={enter_ratio}")
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.enter_ratio = enter_ratio
        self.exit_ratio = exit_ratio
        self.dwell_iters = dwell_iters
        self.window = window
        self.ewma_alpha = ewma_alpha
        self.degrade_max_new = degrade_max_new
        self.low_priority_max = low_priority_max
        self.idle_decay = idle_decay

        self.level = 0
        self._ttfts: Deque[float] = deque(maxlen=window)
        self._p99_ewma_ms = 0.0
        self._fresh_obs = False
        self._last_transition_iter: Optional[int] = None
        self.transitions: list = []       # (iteration, old, new) history

    # -- signal -------------------------------------------------------------

    def observe_ttft(self, ttft_ms: float) -> None:
        self._ttfts.append(float(ttft_ms))
        self._fresh_obs = True
        xs = sorted(self._ttfts)
        p99 = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
        a = self.ewma_alpha
        self._p99_ewma_ms = (p99 if self._p99_ewma_ms == 0.0
                             else a * p99 + (1 - a) * self._p99_ewma_ms)

    def signal_ms(self, queue_head_wait_s: float = 0.0) -> float:
        """The controller input: smoothed p99 TTFT, floored by the
        current head-of-queue wait (that wait IS a lower bound on the
        head request's eventual TTFT)."""
        return max(self._p99_ewma_ms, queue_head_wait_s * 1e3)

    # -- state machine ------------------------------------------------------

    def update(self, iteration: int,
               queue_head_wait_s: float = 0.0) -> int:
        """One hysteretic transition decision; returns the (possibly
        new) level.  Call once per engine iteration."""
        if not self._fresh_obs and queue_head_wait_s <= 0.0:
            # No completion landed and nothing is waiting: the smoothed
            # p99 is STALE — at reject_all this is exactly the moment
            # observations stop arriving, and a frozen signal would
            # latch the brownout forever.  Decay toward "recovered" so
            # the controller probes its way back down.
            self._p99_ewma_ms *= self.idle_decay
        self._fresh_obs = False
        sig = self.signal_ms(queue_head_wait_s)
        dwelled = (self._last_transition_iter is None
                   or iteration - self._last_transition_iter
                   >= self.dwell_iters)
        new = self.level
        if sig > self.enter_ratio * self.slo_ttft_ms:
            if dwelled and self.level < len(LEVELS) - 1:
                new = self.level + 1
        elif sig < self.exit_ratio * self.slo_ttft_ms:
            if dwelled and self.level > 0:
                new = self.level - 1
        if new != self.level:
            self.transitions.append((iteration, self.level, new))
            self.level = new
            self._last_transition_iter = iteration
        return self.level

    # -- admission-time queries ---------------------------------------------

    def max_new_cap(self) -> Optional[int]:
        """The brownout output-length ceiling (None = no clamp)."""
        return self.degrade_max_new if self.level >= 1 else None

    def submit_verdict(self, priority: int) -> Optional[str]:
        """Shed reason for a new submission at the current level, or
        None to let it through to the scheduler's own checks."""
        if self.level >= 3:
            return "brownout_admissions"
        if self.level >= 2 and priority <= self.low_priority_max:
            return "brownout_low_priority"
        return None

    def first_transition_to(self, level: int) -> Optional[int]:
        """Iteration of the first transition INTO ``level`` (None if
        never reached) — the alert-leads-control gate compares the SLO
        monitor's first fast-burn alert against the first ``reject_all``
        (level 3) transition."""
        for iteration, _old, new in self.transitions:
            if new == level:
                return iteration
        return None

    def state(self) -> dict:
        return {"level": self.level, "level_name": LEVELS[self.level],
                "p99_ttft_ewma_ms": round(self._p99_ewma_ms, 3),
                "slo_ttft_ms": self.slo_ttft_ms,
                "transitions": len(self.transitions),
                "max_level_reached": max(
                    [new for _, _, new in self.transitions] or [0]),
                "reject_all_iteration": self.first_transition_to(
                    len(LEVELS) - 1)}
