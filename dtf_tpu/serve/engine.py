"""The serving engine: request-driven continuous-batching decode.

Turns the repo's decode machinery into a system that accepts *requests*:

* one :class:`~dtf_tpu.serve.scheduler.Scheduler` (admission control,
  continuous or static batching, prefill/decode phase separation);
* one shared :class:`~dtf_tpu.serve.paged_kv.KVPool` of fixed-size KV
  blocks with per-request block tables;
* ONE compiled decode step per (slots, window) geometry — batch
  composition changes never recompile — plus one compiled prefill per
  prompt-length bucket;
* streaming output per request (``on_token`` fires as every token is
  emitted) and per-request TTFT/TPOT wired into the telemetry spine
  (``serve/*`` instruments, goodput books, ``telemetry.report``'s
  Serving section).

The engine is single-host and synchronous by design: one iteration =
(admit + prefill the admissions) + (one decode step for every occupied
slot).  Wall-clock honesty comes from the injected clock —
:class:`~dtf_tpu.serve.scheduler.WallClock` for real serving,
:class:`~dtf_tpu.serve.scheduler.VirtualClock` for deterministic
scheduling A/Bs (the load bench's CI mode).

Overload & failure model (DESIGN.md §7.4):

* **shed** — a request dropped BEFORE prefill, by the scheduler's
  deadline feasibility check or the :class:`~dtf_tpu.serve.brownout.
  BrownoutController`'s service level; booked under ``serve/shed_total``
  with a per-reason breakdown (``serve/shed_*``) and surfaced in
  :meth:`ServingEngine.summary`.
* **evict** — an in-flight request torn out mid-decode: client
  disconnect (:meth:`ServingEngine.cancel`) or detected KV corruption
  (the decode step's per-slot finite-logits flag).  Its blocks free
  immediately — the pool never bleeds.
* **drain** — :meth:`ServingEngine.drain`: admissions freeze, in-flight
  decodes finish inside the timeout, everything accepted-but-unfinished
  is checkpointed as replay docs; a supervisor replay completes them
  token-identically (per-request rng streams are (seed, rid)-keyed, so
  replay does not depend on batch composition).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dtf_tpu import telemetry as tel
from dtf_tpu.serve import decode as dec
from dtf_tpu.serve.paged_kv import (BlockAllocator, KVPool, blocks_for,
                                    chunk_digests)
from dtf_tpu.serve.scheduler import Request, Scheduler, WallClock
from dtf_tpu.telemetry.reqtrace import RequestTracer, mint_trace_id


def _request_seed(engine_seed: int, rid: int) -> int:
    """Deterministic per-request rng seed (uint32 range): independent of
    batch composition, stable across engine restarts — a replayed
    request redraws the same tokens."""
    return (int(engine_seed) * 2654435761 + int(rid) * 40503) % (1 << 32)


#: Speculative drafting backoff: a request's draft credit caps here and
#: a credit-exhausted request retries one draft round every this many
#: verify iterations (loops form late in greedy streams — never
#: retrying would miss them; retrying every round would let one
#: undraftable stream tax the whole batch's p99 TPOT).
SPEC_CREDIT_MAX = 8
SPEC_RETRY_EVERY = 8


def _pow2_bucket(n: int, cap: int) -> int:
    """Smallest power of two >= n, clamped to cap (>= 1).  The geometry
    bucketing that bounds compile count: narrowed decode table widths,
    hot pool prefixes, and batched-prefill row counts all quantize
    through this, so a serving process warms O(log) executables per
    shape family instead of one per live-context length."""
    b = 1
    while b < n:
        b <<= 1
    return max(1, min(b, cap))


class ServingEngine:
    """See module docstring.  ``model`` is a :class:`dtf_tpu.models.gpt.
    GPT` (params may be sharded under a mesh — GSPMD inserts the
    collectives, same tokens as single-device; tested)."""

    def __init__(self, model, params, *, num_slots: int = 4,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 blocks_per_slot: Optional[int] = None,
                 mode: str = "continuous", top_k: int = 0,
                 top_p: float = 1.0, eos_id: Optional[int] = None,
                 seed: int = 0, clock=None, max_queue: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 static_batch_wait_s: float = 0.05,
                 aging_s: float = 2.0,
                 on_token: Optional[Callable] = None,
                 heartbeat: Optional[Callable[[int], None]] = None,
                 brownout=None, chaos=None, slo=None,
                 trace_ring_capacity: int = 64,
                 coalesce_prefill: bool = True,
                 narrow_decode: bool = True,
                 spec_k: int = 0,
                 decode_kernel: Optional[bool] = None,
                 pool: Optional[KVPool] = None,
                 prefix_cache: bool = False):
        t_init = time.perf_counter()
        # Close any open supervisor down-window into the restart bucket
        # (run_supervised marks down at the crash; construction of the
        # next attempt's engine is "up" — same contract as Trainer).
        tel.get_tracker().mark_up()
        self.model = model
        self.params = params
        cfg = model.cfg
        if cfg.flash_enabled() and block_size % 8:
            raise ValueError(
                f"block_size must be a multiple of 8 when the flash "
                f"prefill kernel is active (sublane tiling), got "
                f"{block_size}")
        self.block_size = block_size
        self.blocks_per_slot = (blocks_per_slot
                                or blocks_for(cfg.max_len, block_size))
        if num_blocks is None:
            # no-sharing default: every slot can hold a full window;
            # size it down to see paging's pool-sharing win
            num_blocks = 1 + num_slots * self.blocks_per_slot
        if pool is not None:
            # externally-owned pool (the decode ladder reuses ONE pool
            # across its timed engine constructions so the per-call
            # zeros/concat churn stays out of the marginal fit); stale
            # finite rows are harmless — prefill rewrites every block
            # before an unmasked read
            if (pool.num_blocks != num_blocks
                    or pool.block_size != block_size):
                raise ValueError(
                    f"external pool geometry ({pool.num_blocks} blocks "
                    f"x {pool.block_size}) != engine "
                    f"({num_blocks} x {block_size})")
            self.pool = pool
        else:
            self.pool = KVPool.create(cfg, num_blocks, block_size)
        self.clock = clock or WallClock()
        self.scheduler = Scheduler(
            num_slots=num_slots, allocator=BlockAllocator(num_blocks),
            block_size=block_size, blocks_per_slot=self.blocks_per_slot,
            mode=mode, max_queue=max_queue,
            prefill_token_budget=prefill_token_budget,
            static_batch_wait_s=static_batch_wait_s, max_len=cfg.max_len,
            aging_s=aging_s)
        self.scheduler.on_shed = self._book_shed
        #: Brownout overload controller (serve/brownout.py); None = no
        #: controller — the engine degrades only via queue rejection.
        self.brownout = brownout
        #: Serving chaos plan (resilience/chaos.py slow_decode /
        #: client_drop / kv_poison, keyed on the engine iteration).
        self.chaos = chaos
        #: SLO burn-rate monitor (telemetry/slo.py BurnRateMonitor);
        #: None = not armed.  Passive: it observes completions and
        #: raises alerts, it never touches admission.
        self.slo = slo
        #: Self-tuning control plane (dtf_tpu/control): attached by
        #: control.arm_controller AFTER construction (its knob wiring
        #: captures the constructed scheduler/brownout); None = pinned
        #: knobs.  The step tail drives its decide() on the engine
        #: clock, so the loop is deterministic under VirtualClock.
        self.controller = None
        #: Per-request distributed tracing (telemetry/reqtrace.py):
        #: lifecycle events into the span file + the /tracez flight
        #: recorder.  Always on — events are cheap and the ring is
        #: bounded.
        self.reqtrace = RequestTracer(trace_ring_capacity)
        #: Incident plane (telemetry/anomaly.py): the process-wide
        #: changepoint monitor, armed eagerly so even a zero-anomaly run
        #: leaves 'armed, zero' books.  Fed from _finish (TTFT/TPOT) and
        #: the step tail (queue depth) — values only, clock-agnostic.
        from dtf_tpu.telemetry import anomaly as _anomaly
        from dtf_tpu.telemetry import diagnose as _diagnose
        self.anomaly = _anomaly.get_monitor().arm()
        _diagnose.install()
        #: Compile-stall exclusion for the latency feeds, WALL clock
        #: only: a request whose service window overlaps a fresh XLA
        #: compile measures the compile, not serving health — feeding
        #: it would make every new-geometry compile a false anomaly
        #: (the trainer applies the same rule to compile-bearing
        #: steps).  A VirtualClock charges compiles zero virtual time,
        #: so its latencies are never polluted and nothing is excluded.
        self._compile_feed_guard = isinstance(self.clock, WallClock)
        self._compiles_seen: Optional[int] = None
        self._last_compile_clock_s: Optional[float] = None
        #: Brownout level as of the previous step tail — the edge the
        #: event/brownout_transition evidence instant fires on.
        self._prev_brownout_level = 0 if brownout is not None else None
        self.mode = mode
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.seed = seed
        self.on_token = on_token
        self.heartbeat = heartbeat

        self.num_slots = num_slots
        self._table = np.full((num_slots, self.blocks_per_slot), -1,
                              np.int32)
        self._tok = np.zeros((num_slots,), np.int32)
        self._pos = np.zeros((num_slots,), np.int32)
        self._temps = np.zeros((num_slots,), np.float32)
        self._seeds = np.zeros((num_slots,), np.uint32)
        self._counts = np.zeros((num_slots,), np.int32)

        #: Coalesce same-bucket admissions into one batched prefill call
        #: (serve/decode.py build_prefill_batched_fn).  Off = the solo
        #: per-request path — the determinism A/B's baseline arm.
        self.coalesce_prefill = bool(coalesce_prefill)
        #: Narrowed decode data path: table width bucketed to the live
        #: context's block extent and the pool's hot prefix bucketed to
        #: the allocator high-water mark, so per-token cost scales with
        #: context used, not pool size.  Off = full-window whole-pool
        #: geometry — the ladder's baseline arm.
        self.narrow_decode = bool(narrow_decode)
        #: Prefix/prompt KV sharing (serve/paged_kv.py content index):
        #: submits match their prompt's block-chain digests against
        #: blocks earlier requests registered, pin the hits, and prefill
        #: only the uncached suffix — bitwise the cold tokens (pinned),
        #: cheaper TTFT (the --prefix_ab bench gates the ratio).  Off =
        #: the engine never registers or matches content, and every
        #: allocator path degenerates to the plain free list.
        self.prefix_cache = bool(prefix_cache)
        self.prefix_lookups = 0
        self.prefix_hit_blocks = 0
        self.prefix_probed_blocks = 0
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        #: Speculative decoding: the n-gram self-drafter (serve/spec.py)
        #: proposes up to spec_k tokens per slot per iteration and the
        #: verify step emits the model's own choices, so the greedy
        #: token stream is bitwise the sequential one (tested).
        self.spec_k = int(spec_k)
        #: Pallas paged-attention kernel for the decode gather (TPU
        #: builds; None = auto: TPU backend AND Mosaic-legal geometry —
        #: 8-aligned block rows, 128-aligned head lanes; explicit True
        #: forces it, e.g. interpret-mode parity tests).  The XLA
        #: gather remains the CPU-sim path and the parity oracle.
        import jax as _jax
        kvh = cfg.num_kv_heads or cfg.num_heads
        lanes_ok = (block_size % 8 == 0
                    and (kvh * (cfg.dim // cfg.num_heads)) % 128 == 0
                    and cfg.dim % 128 == 0)
        self.decode_kernel = (bool(decode_kernel)
                              if decode_kernel is not None
                              else _jax.default_backend() == "tpu"
                              and lanes_ok)
        self._compiled: set = set()
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.prefill_calls = 0
        if not self.narrow_decode:
            # baseline geometry: whole pool stays hot for process life
            self.pool.ensure_hot(self.pool.num_blocks)

        self._next_rid = 0
        self.results: Dict[int, Request] = {}
        self.iterations = 0
        self.batch_log: List[Tuple] = []    # scheduling trace (tests pin)
        self._blocks_peak = 0
        self._pool_frac_peak = 0.0
        self.shed_reasons: Dict[str, int] = {}
        self._drain_requested = False       # set (signal-safely) by SIGTERM
        self.drained = False
        self.drain_docs: List[dict] = []    # replay docs of a drain

        tel.gauge("serve/slots").set(num_slots)
        tel.gauge("serve/kv_blocks_total").set(num_blocks - 1)
        tel.get_tracker().add("init", time.perf_counter() - t_init)

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               arrival_s: Optional[float] = None,
               deadline_ms: Optional[float] = None, priority: int = 0,
               rid: Optional[int] = None,
               trace_id: Optional[str] = None,
               resubmit: bool = False) -> Request:
        """Admission-controlled submit.  Returns the Request; check
        ``.status`` — ``rejected`` means the queue pushed back (the
        closed-loop client's backpressure signal), ``shed`` means
        overload control dropped it (``shed_reason`` says why),
        ``queued`` means it will stream tokens via ``on_token`` and
        land in ``results``."""
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid,
                      prompt=np.asarray(prompt, np.int32).reshape(-1),
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      eos_id=self.eos_id if eos_id is None else eos_id,
                      deadline_ms=deadline_ms, priority=int(priority),
                      trace_id=trace_id, resubmit=bool(resubmit))
        now = self.clock.now() if arrival_s is None else arrival_s
        self.submit_request(req, now)
        return req

    def _book_shed(self, req: Request, reason: str) -> None:
        """ONE booking path for every shed — scheduler deadline sheds
        (submit-time and admit-time) and brownout sheds alike.  The
        total + per-reason pair updates under the registry lock so a
        concurrent /statz scrape never reads a torn pair."""
        with tel.get_registry().locked():
            tel.counter("serve/shed_total").inc()
            tel.counter(f"serve/shed_{reason}").inc()
        self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
        self.results[req.rid] = req
        self.reqtrace.event(req, "shed", self.clock.now(), reason=reason)

    def submit_request(self, req: Request, now: float) -> str:
        tel.counter("serve/submissions_total").inc()
        # ONE live request per rid: a fleet acceptor's failover/hedge
        # replay may resubmit a rid whose earlier copy is still live on
        # this engine (the leg's cancel raced the resubmit through the
        # mailbox).  The stale copy is torn out FIRST — otherwise its
        # mid-stream tokens would cross-wire into the new submission's
        # per-rid stream and the acceptor's replay-prefix verification
        # would (correctly) fail the request.
        for old in list(self.scheduler.queue) + self.scheduler.active():
            if old.rid == req.rid and old is not req:
                self._evict(old, "cancelled", "serve/cancelled_total")
                self._emit(old, -1, True)
                break
        if req.trace_id is None:
            req.trace_id = mint_trace_id()
        # the trace's opening event; a supervisor/drain replay re-opens
        # the SAME trace id with resubmit=True, linking both segments
        # (the flag is explicit replay provenance — a fresh TCP request
        # also arrives with a front-door-minted trace id)
        self.reqtrace.event(req, "submit", now,
                            prompt_len=req.prompt_len,
                            max_new=int(req.max_new_tokens),
                            priority=int(req.priority),
                            **({"resubmit": True} if req.resubmit else {}))
        if self.brownout is not None:
            # Brownout first: at reject_low/reject_all the submission is
            # shed before it costs a queue entry; at degrade the output
            # ceiling is clamped BEFORE the scheduler sizes the block
            # reservation, so degraded requests also reserve less.
            verdict = self.brownout.submit_verdict(req.priority)
            if verdict is not None:
                req.arrival_s = now
                req.status = "shed"
                req.shed_reason = verdict
                self._book_shed(req, verdict)
                return f"shed_{verdict}"
            cap = self.brownout.max_new_cap()
            if cap is not None and req.max_new_tokens > cap:
                req.max_new_tokens = cap
                req.degraded = True
                tel.counter("serve/degraded_total").inc()
        verdict = self.scheduler.submit(req, now)
        if verdict.startswith("rejected"):
            tel.counter("serve/requests_rejected").inc()
            self.results[req.rid] = req
            self.reqtrace.event(req, "rejected", now, verdict=verdict)
        elif verdict.startswith("shed"):
            pass                    # booked via the on_shed hook already
        elif self.prefix_cache:
            # match + PIN shared blocks NOW, after the "queued" verdict:
            # an acquired block cannot be reclaimed by allocation
            # pressure, so the admission walk's fresh-blocks discount
            # (scheduler._fresh_blocks_needed) stays valid from match to
            # _assign by construction
            self._prefix_match(req, now)
        return verdict

    def _prefix_match(self, req: Request, now: float) -> None:
        """Walk the content index for this prompt's digest chain and pin
        every matched full block.  The match cap is ``(prompt_len - 1)
        // block_size`` blocks — the final real prompt token is never
        served from cache because its logits are the first output
        token's source, so at least one suffix token always runs
        through the prefill forward."""
        bs = self.block_size
        alloc = self.scheduler.allocator
        cap = (req.prompt_len - 1) // bs
        req.prefix_digests = chunk_digests(req.prompt, bs,
                                           req.prompt_len // bs)
        matched = alloc.match_chain(req.prefix_digests[:cap]) if cap else []
        self.prefix_lookups += 1
        self.prefix_probed_blocks += cap
        if matched:
            alloc.acquire(matched)
            req.prefix_blocks = list(matched)
            req.cached_prefix_blocks = len(matched)
            self.prefix_hit_blocks += len(matched)
        # the pair updates under the registry lock: a concurrent /statz
        # scrape must never read hit blocks without the lookup that
        # produced them
        with tel.get_registry().locked():
            tel.counter("serve/prefix_lookup_total").inc()
            if matched:
                tel.counter("serve/prefix_hit_blocks_total").inc(
                    len(matched))
        self.reqtrace.event(req, "prefix_match", now,
                            hit_blocks=len(matched), probed_blocks=cap)

    # -- the iteration ------------------------------------------------------

    def _book(self, bucket, seconds: float) -> None:
        """First call per compiled bucket is dominated by the backend
        compile — book it there so serving goodput stays honest."""
        if bucket in self._compiled:
            tel.get_tracker().add("productive", seconds)
        else:
            self._compiled.add(bucket)
            tel.get_tracker().add("compile", seconds)

    def _emit(self, req: Request, token: int, done: bool) -> None:
        if self.on_token is not None:
            self.on_token(req, int(token), done)

    def _clear_slot(self, slot: int) -> None:
        self._table[slot] = -1
        self._tok[slot] = 0
        self._pos[slot] = 0
        self._temps[slot] = 0.0
        self._seeds[slot] = 0
        self._counts[slot] = 0

    def _finish(self, req: Request, now: float) -> None:
        req.status = "completed"
        req.done_s = now
        slot = req.slot
        self.scheduler.release(req)
        self._clear_slot(slot)
        self.results[req.rid] = req
        ttft = req.ttft_s()
        tpot = req.tpot_s()
        # counter + latency histograms update as ONE group: a /statz
        # scrape mid-completion must not see the count without its
        # observation (or vice versa)
        with tel.get_registry().locked():
            tel.counter("serve/requests_completed").inc()
            if ttft is not None:
                tel.histogram("serve/ttft_ms").observe(ttft * 1e3)
            if tpot is not None:
                tel.histogram("serve/tpot_ms").observe(tpot * 1e3)
        if ttft is not None and self.brownout is not None:
            self.brownout.observe_ttft(ttft * 1e3)
        # incident plane feeds: per-completion latency observations into
        # the changepoint detectors (values only, no clock reads).  On a
        # wall clock the TPOT feed excludes completions whose decode
        # window [first_token, last_token] contained the most recent
        # XLA compile — their streaming cadence measures the compile
        # stall, not serving health, and every fresh decode-batch
        # geometry would read as a fault.  TTFT feeds UNGUARDED on
        # purpose: its compile pollution is the first-encounter prefill
        # of each prompt bucket, which lands during detector cold-start
        # (min_samples shields it), while a mid-run compile that blocks
        # QUEUED requests is real head-of-line blocking the client
        # waited through — e.g. a failover onto cold geometries — and
        # the correlator, not the feed, is the layer that decides
        # whether chaos or the compile owns that spike.
        clean_tpot = True
        if self._compile_feed_guard:
            from dtf_tpu.telemetry import costobs as _costobs
            c = _costobs.get_observatory().total_compiles()
            if c != self._compiles_seen:
                self._compiles_seen = c
                self._last_compile_clock_s = now
            stamp = self._last_compile_clock_s
            if stamp is not None and req.first_token_s is not None:
                end = (req.last_token_s
                       if req.last_token_s is not None else now)
                clean_tpot = not (req.first_token_s <= stamp <= end)
        if ttft is not None:
            self.anomaly.observe("serve/ttft_ms", ttft * 1e3,
                                 tick=self.iterations)
        if clean_tpot and tpot is not None:
            self.anomaly.observe("serve/tpot_ms", tpot * 1e3,
                                 tick=self.iterations)
        if self.slo is not None:
            if ttft is not None and self.slo.slo_ttft_ms is not None:
                self.slo.record("ttft", ttft * 1e3 > self.slo.slo_ttft_ms,
                                now)
            if (tpot is not None and self.slo.slo_tpot_ms is not None
                    and self.slo.has("tpot")):
                self.slo.record("tpot", tpot * 1e3 > self.slo.slo_tpot_ms,
                                now)
            if req.deadline_ms is not None and self.slo.has("deadline"):
                self.slo.record(
                    "deadline",
                    req.completion_s() > req.deadline_ms / 1e3, now)
        self.reqtrace.event(req, "completed", now,
                            n_tokens=req.n_generated(),
                            ttft_ms=(None if ttft is None
                                     else round(ttft * 1e3, 3)))

    def _scrub_blocks(self, blocks) -> None:
        """Zero a request's pool blocks (corruption eviction): bad rows
        must not outlive their victim into the free list."""
        if not blocks:
            return
        b = np.asarray(blocks, np.int32)
        self.pool.k = self.pool.k.at[:, b].set(0)
        self.pool.v = self.pool.v.at[:, b].set(0)

    def _invalidate_poisoned(self, blocks) -> None:
        """Prefix-cache half of a kv-poison eviction: tear the victim's
        blocks out of the content index (no future submit can match NaN
        rows; a parked victim block drops to the free list) and strip
        queued requests' pins on them — a queued holder just loses its
        discount and cold-prefills when admitted, no tokens were ever
        derived from the bad rows.  Healthy pins released alongside
        (the walk frees the whole chain) are still registered, so they
        park back into the cached tier and stay matchable.  A no-op
        with the cache off — the decode eviction's event order is
        bitwise the pre-cache engine's."""
        if not self.prefix_cache or not blocks:
            return
        alloc = self.scheduler.allocator
        alloc.invalidate_blocks(blocks)
        poisoned = set(blocks)
        for q in self.scheduler.queue:
            if q.prefix_blocks and poisoned.intersection(q.prefix_blocks):
                alloc.free(q.prefix_blocks)
                q.prefix_blocks = None
                q.cached_prefix_blocks = 0

    def _poison_eviction(self, req: Request) -> None:
        """Shared-block poison detected at SUFFIX PREFILL time: unlike
        the decode step — where every active sharer's own finite-logits
        flag trips in the same iteration — this detection runs BEFORE
        the iteration's decode, and scrubbing (zeroing) the shared
        blocks here would hand the other sharers finite-but-wrong rows.
        So the eviction walks the refcount set first: every active
        request sharing any of the victim's blocks goes with it (digest
        chains are ancestor-closed, so one intersection pass finds every
        transitive sharer), THEN each victim's blocks are scrubbed and
        invalidated.  No surviving stream ever emits a NaN-derived
        token (pinned)."""
        victims = [req]
        if self.prefix_cache and req.blocks:
            poisoned = set(req.blocks)
            victims += [r for r in self.scheduler.active()
                        if r is not req and r.blocks
                        and poisoned.intersection(r.blocks)]
        for v in victims:
            self._scrub_blocks(v.blocks)
            self._invalidate_poisoned(v.blocks)
            self._evict(v, "failed", "serve/kv_evictions_total")
            self._emit(v, -1, True)

    def _evict(self, req: Request, status: str, counter: str) -> None:
        """Tear an IN-FLIGHT or queued request out right now: blocks
        free on this iteration (the pool never waits for a dead
        client), slot-side state is scrubbed so the next decode writes
        its row into the trash block."""
        slot = req.slot
        where = self.scheduler.cancel(req, status=status)
        if slot is not None and where == "running":
            self._clear_slot(slot)
        req.done_s = self.clock.now()
        self.results[req.rid] = req
        tel.counter(counter).inc()
        self.reqtrace.event(req, status, req.done_s, where=where,
                            n_tokens=req.n_generated())

    def cancel(self, rid: int, status: str = "cancelled") -> bool:
        """Client disconnect / caller cancel for a request anywhere in
        its lifecycle (queued, mid-prefill reservation, mid-decode).
        Returns True when something was actually torn down.  NOT
        thread-safe — call from the engine-driving thread (the TCP
        front end posts cancels through its mailbox)."""
        req = self.results.get(rid)
        if req is None:
            for r in list(self.scheduler.queue) + self.scheduler.active():
                if r.rid == rid:
                    req = r
                    break
        if req is None or req.status in ("completed", "rejected", "shed",
                                         "cancelled", "failed"):
            return False
        self._evict(req, status, "serve/cancelled_total")
        # terminal notification: streaming consumers (the TCP bridge's
        # per-request stream map, --stream printers) must learn the
        # request ended, or their per-rid state leaks for the process
        # lifetime on a long-lived server
        self._emit(req, -1, True)
        return True

    def _token_out(self, req: Request, token: int, now: float) -> bool:
        """Record one emitted token; returns done."""
        req.tokens.append(int(token))
        if req.first_token_s is None:
            req.first_token_s = now
            # before the done-check: a one-token request's first_token
            # must precede its completed event in the timeline
            self.reqtrace.event(req, "first_token", now,
                                ttft_ms=round((now - req.arrival_s) * 1e3,
                                              3))
        req.last_token_s = now
        done = (len(req.tokens) >= req.max_new_tokens
                or (req.eos_id is not None and int(token) == req.eos_id))
        if done:
            # finish BEFORE the emit so a streaming consumer (the TCP
            # front end's terminal line) reads the final status, not
            # "running"
            self._finish(req, now)
        self._emit(req, token, done)
        return done

    def _mark_admitted(self, slot: int, req: Request) -> None:
        self.reqtrace.event(req, "admitted", self.clock.now(), slot=slot,
                            iteration=self.iterations,
                            queue_wait_ms=round(
                                (self.clock.now() - req.arrival_s) * 1e3,
                                3))

    def _post_prefill(self, slot: int, req: Request, first: int,
                      seed: int, p_pad: int, c0: float,
                      tokens: Optional[int] = None) -> None:
        """Per-request bookkeeping shared by the solo, batched, and
        suffix prefill paths: the batch-log entry (mode-independent —
        the coalescing determinism pin compares it across paths),
        slot-side state, and the first token's emission.  Clock charges
        and the rate-estimator feed happen at CALL level before this
        runs.  ``tokens`` is the count actually forwarded (the suffix
        path passes only its uncached tokens; default = the whole
        padded prompt)."""
        tokens = p_pad if tokens is None else tokens
        tel.counter("serve/prefill_tokens_total").inc(tokens)
        self.batch_log.append(("prefill", req.rid))
        self.reqtrace.event(req, "prefill", self.clock.now(),
                            tokens=tokens,
                            dur_ms=round((self.clock.now() - c0) * 1e3, 3))
        req.pos = req.prompt_len
        self._table[slot] = -1
        self._table[slot, :len(req.blocks)] = req.blocks
        self._tok[slot] = first
        self._pos[slot] = req.prompt_len
        self._temps[slot] = req.temperature
        self._seeds[slot] = seed
        self._counts[slot] = 1
        if self.prefix_cache and req.prefix_digests:
            # publish this request's full-content blocks into the
            # sharing index — BEFORE the first token's emission, so a
            # one-token request's blocks are registered by the time
            # _finish releases them (they park in the cached tier
            # instead of hitting the free list unregistered)
            n_full = req.prompt_len // self.block_size
            if n_full:
                self.scheduler.allocator.register_chain(
                    req.prefix_digests[:n_full], req.blocks[:n_full])
        self._token_out(req, first, self.clock.now())

    def _prefill(self, slot: int, req: Request) -> None:
        import jax.numpy as jnp

        p_len = req.prompt_len
        p_pad = req.padded_prompt_len(self.block_size)
        nb_prompt = p_pad // self.block_size
        fn = dec.build_prefill_fn(self.model, padded_len=p_pad,
                                  num_blocks_req=nb_prompt,
                                  top_k=self.top_k, top_p=self.top_p)
        prompt = np.zeros((1, p_pad), np.int32)
        prompt[0, :p_len] = req.prompt
        seed = _request_seed(self.seed, req.rid)
        c0 = self.clock.now()
        t0 = time.perf_counter()
        with tel.span("serve/prefill", tokens=int(p_pad), rid=int(req.rid),
                      t=round(c0, 6)):
            first, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(prompt), jnp.int32(p_len),
                jnp.asarray(np.asarray(req.blocks[:nb_prompt], np.int32)),
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([seed], jnp.uint32))
            first = int(first)
        self._book(("prefill", p_pad, self.pool.hot_blocks),
                   time.perf_counter() - t0)
        self.prefill_calls += 1
        tel.histogram("serve/prefill_batch_size").observe(1)
        self.clock.charge("prefill", tokens=p_pad)
        # Feed the deadline estimator from the SAME clock latencies a
        # client experiences (wall or virtual), so feasibility math and
        # measured TTFT cannot disagree about what "slow" means.
        self.scheduler.observe_prefill(p_pad, self.clock.now() - c0)
        self._post_prefill(slot, req, first, seed, p_pad, c0)

    def _prefill_batch(self, group: List[Tuple[int, Request]]) -> None:
        """R same-bucket admissions through ONE batched prefill call
        (rows rounded up to a power of two; padding rows write the
        trash block and their sampled token is discarded)."""
        import jax.numpy as jnp

        p_pad = group[0][1].padded_prompt_len(self.block_size)
        nb_prompt = p_pad // self.block_size
        r = len(group)
        r_pad = _pow2_bucket(r, max(self.num_slots, r))
        fn = dec.build_prefill_batched_fn(
            self.model, padded_len=p_pad, num_blocks_req=nb_prompt,
            n_rows=r_pad, top_k=self.top_k, top_p=self.top_p)
        prompts = np.zeros((r_pad, p_pad), np.int32)
        p_lens = np.ones((r_pad,), np.int32)
        blocks = np.zeros((r_pad, nb_prompt), np.int32)    # pad -> trash
        temps = np.zeros((r_pad,), np.float32)
        seeds = np.zeros((r_pad,), np.uint32)
        for i, (_, req) in enumerate(group):
            prompts[i, :req.prompt_len] = req.prompt
            p_lens[i] = req.prompt_len
            blocks[i] = req.blocks[:nb_prompt]
            temps[i] = req.temperature
            seeds[i] = _request_seed(self.seed, req.rid)
        c0 = self.clock.now()
        t0 = time.perf_counter()
        with tel.span("serve/prefill", tokens=int(p_pad) * r,
                      rids=sorted(int(req.rid) for _, req in group),
                      t=round(c0, 6)):
            firsts, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(prompts), jnp.asarray(p_lens),
                jnp.asarray(blocks), jnp.asarray(temps),
                jnp.asarray(seeds))
            firsts = np.asarray(firsts)
        self._book(("prefill_batch", p_pad, r_pad, self.pool.hot_blocks),
                   time.perf_counter() - t0)
        self.prefill_calls += 1
        tel.histogram("serve/prefill_batch_size").observe(r)
        # one virtual charge per member — the cost-model trajectory (and
        # so every scheduling decision and the batch log) is identical
        # to the solo path's; the batched win is measured on the wall
        # clock and in dispatch/compile counts, not by rigging the
        # policy clock
        for _ in group:
            self.clock.charge("prefill", tokens=p_pad)
        self.scheduler.observe_prefill(p_pad * r, self.clock.now() - c0)
        for i, (slot, req) in enumerate(group):
            self._post_prefill(slot, req, int(firsts[i]),
                               int(seeds[i]), p_pad, c0)

    def _prefill_suffix(self, group: List[Tuple[int, Request]]) -> None:
        """R same-(bucket, cached-length) admissions through ONE
        suffix-only prefill call (decode.build_prefill_suffix_fn): the
        matched shared blocks sit read-only at the front of each table,
        only the uncached suffix tokens run through the forward, and
        only those tokens are charged to the clock and the rate
        estimator — the TTFT win the --prefix_ab bench gates.  Token
        streams are bitwise the cold path's (pinned)."""
        import jax.numpy as jnp

        bs = self.block_size
        p_pad = group[0][1].padded_prompt_len(bs)
        start = group[0][1].cached_prefix_blocks * bs
        nb_pre = start // bs
        nb_sfx = (p_pad - start) // bs
        s_w = p_pad - start
        r = len(group)
        r_pad = _pow2_bucket(r, max(self.num_slots, r))
        fn = dec.build_prefill_suffix_fn(
            self.model, padded_len=p_pad, start_len=start, n_rows=r_pad,
            top_k=self.top_k, top_p=self.top_p)
        toks = np.zeros((r_pad, s_w), np.int32)
        p_lens = np.full((r_pad,), start + 1, np.int32)  # pad rows: row 0
        pre = np.zeros((r_pad, nb_pre), np.int32)        # pad -> trash
        sfx = np.zeros((r_pad, nb_sfx), np.int32)
        temps = np.zeros((r_pad,), np.float32)
        seeds = np.zeros((r_pad,), np.uint32)
        for i, (_, req) in enumerate(group):
            tail = req.prompt[start:]
            toks[i, :len(tail)] = tail
            p_lens[i] = req.prompt_len
            pre[i] = req.blocks[:nb_pre]
            sfx[i] = req.blocks[nb_pre:nb_pre + nb_sfx]
            temps[i] = req.temperature
            seeds[i] = _request_seed(self.seed, req.rid)
        c0 = self.clock.now()
        t0 = time.perf_counter()
        with tel.span("serve/prefill", tokens=int(s_w) * r,
                      cached=int(start) * r,
                      rids=sorted(int(req.rid) for _, req in group),
                      t=round(c0, 6)):
            firsts, oks, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(toks), jnp.asarray(p_lens), jnp.asarray(pre),
                jnp.asarray(sfx), jnp.asarray(temps), jnp.asarray(seeds))
            firsts = np.asarray(firsts)
            oks = np.asarray(oks)
        self._book(("prefill_suffix", p_pad, start, r_pad,
                    self.pool.hot_blocks), time.perf_counter() - t0)
        self.prefill_calls += 1
        tel.histogram("serve/prefill_batch_size").observe(r)
        # only the SUFFIX tokens are real prefill work — the cached rows
        # were paid for by whichever request registered them
        for _ in group:
            self.clock.charge("prefill", tokens=s_w)
        self.scheduler.observe_prefill(s_w * r, self.clock.now() - c0)
        for i, (slot, req) in enumerate(group):
            if not bool(oks[i]):
                # the gathered shared prefix went non-finite between
                # match and forward (kv_poison): never emit a NaN-
                # derived first token — evict every sharer (the walk
                # below; a group-mate sharing the same blocks may
                # already be gone by the time its row comes up)
                if req.status == "running":
                    self._poison_eviction(req)
                continue
            self._post_prefill(slot, req, int(firsts[i]), int(seeds[i]),
                               p_pad, c0, tokens=s_w)

    def _prefill_admitted(self,
                          admitted: List[Tuple[int, Request]]) -> None:
        """Dispatch this iteration's admissions to prefill: coalesce
        same-bucket runs into batched calls (admission order is
        preserved — the scheduler's decisions, the batch log, and every
        request's tokens are identical to the solo path, pinned by the
        determinism A/B), or run each solo when coalescing is off.
        Prefix-cache hits group by (bucket, cached length) and take the
        suffix path — with the cache off every request has cached
        length 0 and the grouping degenerates to the pre-cache one."""
        for slot, req in admitted:
            self._mark_admitted(slot, req)
        i = 0
        while i < len(admitted):
            start = admitted[i][1].cached_prefix_blocks * self.block_size
            if not self.coalesce_prefill:
                if start:
                    self._prefill_suffix([admitted[i]])
                else:
                    self._prefill(*admitted[i])
                i += 1
                continue
            p_pad = admitted[i][1].padded_prompt_len(self.block_size)
            j = i + 1
            while (j < len(admitted)
                   and admitted[j][1].padded_prompt_len(self.block_size)
                   == p_pad
                   and admitted[j][1].cached_prefix_blocks
                   * self.block_size == start):
                j += 1
            group = admitted[i:j]
            if start:
                self._prefill_suffix(group)
            elif len(group) == 1:
                self._prefill(*group[0])
            else:
                self._prefill_batch(group)
            i = j

    # -- narrowed geometry --------------------------------------------------

    def _nb_bucket(self, active: List[Request], extra: int) -> int:
        """Narrowed decode table width: blocks covering the batch's
        deepest live context plus the rows this step will write
        (``extra`` = 1 for plain decode, the window width for verify),
        bucketed to a power of two so compile count stays O(log)."""
        if not self.narrow_decode:
            return self.blocks_per_slot
        need_rows = max(int(self._pos[r.slot]) + extra for r in active)
        nb = blocks_for(need_rows, self.block_size)
        return _pow2_bucket(nb, self.blocks_per_slot)

    def _ensure_hot_prefix(self) -> None:
        """Bucket the pool's hot prefix to the allocator's high-water
        mark — the other half of "cost scales with context used": the
        functional scatter's copy is of the hot arrays only."""
        if not self.narrow_decode:
            return
        h = _pow2_bucket(self.scheduler.allocator.highest_used() + 1,
                         self.pool.num_blocks)
        self.pool.ensure_hot(h)

    def _decode(self, active: List[Request]) -> None:
        import jax.numpy as jnp

        nb = self._nb_bucket(active, 1)
        fn = dec.build_decode_fn(
            self.model, num_slots=self.num_slots, blocks_per_slot=nb,
            block_size=self.block_size, top_k=self.top_k,
            top_p=self.top_p, kernel=self.decode_kernel)
        c0 = self.clock.now()
        t0 = time.perf_counter()
        with tel.span("serve/decode", batch=len(active),
                      rids=sorted(int(r.rid) for r in active),
                      iteration=self.iterations, t=round(c0, 6)):
            nxt, ok, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(self._table[:, :nb]), jnp.asarray(self._tok),
                jnp.asarray(self._pos), jnp.asarray(self._temps),
                jnp.asarray(self._seeds), jnp.asarray(self._counts))
            nxt = np.asarray(nxt)
            ok = np.asarray(ok)
        self._book(("decode", nb, self.pool.hot_blocks),
                   time.perf_counter() - t0)
        self.clock.charge("decode", batch=len(active))
        self.scheduler.observe_decode(self.clock.now() - c0)
        now = self.clock.now()
        tel.counter("serve/decode_iterations_total").inc()
        tel.counter("serve/tokens_generated_total").inc(len(active))
        self.batch_log.append(
            ("decode", tuple(sorted(r.rid for r in active))))
        for req in active:
            slot = req.slot
            if not bool(ok[slot]):
                # Non-finite logits = this slot's KV rows (or weights)
                # went bad.  Evict ONLY the victim — emitting a token
                # sampled from NaN logits would be silent garbage — and
                # keep serving every healthy slot.  Scrub the blocks
                # BEFORE they return to the free list: recycled NaN
                # rows would otherwise poison every later request that
                # reuses them (the additive visibility mask cannot mask
                # NaN), permanently degrading the pool.  Shared blocks:
                # every ACTIVE sharer's gather hit the same NaN rows, so
                # its own flag trips in this very batch — the extra walk
                # here only de-indexes the content and strips queued
                # pins (no-ops with the cache off; event order is the
                # pre-cache engine's).
                self._scrub_blocks(req.blocks)
                self._invalidate_poisoned(req.blocks)
                self._evict(req, "failed", "serve/kv_evictions_total")
                self._emit(req, -1, True)
                continue
            tok = int(nxt[slot])
            req.pos += 1
            self._pos[slot] += 1
            self._counts[slot] += 1
            self._tok[slot] = tok
            self._token_out(req, tok, now)

    # -- speculative decoding -----------------------------------------------

    def _spec_decode(self, active: List[Request]) -> None:
        """One speculative iteration: the n-gram self-drafter proposes
        up to ``spec_k`` tokens per slot, the verify step runs the whole
        window through the paged cache in one pass, and the host emits
        the longest prefix of drafts the model itself would have chosen
        plus the bonus token at the first mismatch — so every emitted
        token is the model's own choice and the greedy stream is
        bitwise the sequential engine's (pinned).  Slots with nothing
        to draft (budget exhausted, no n-gram match) ride the same
        window with a 1-token ``n_in``; if NO slot drafted, the plain
        decode step runs instead (cheaper geometry)."""
        import jax.numpy as jnp

        from dtf_tpu.serve.spec import propose_drafts

        s_w = self.spec_k + 1
        toks = np.zeros((self.num_slots, s_w), np.int32)
        n_in = np.ones((self.num_slots,), np.int32)
        proposed = 0
        for req in active:
            slot = req.slot
            toks[slot, 0] = self._tok[slot]
            budget = req.max_new_tokens - len(req.tokens) - 1
            d = min(self.spec_k, max(budget, 0))
            # adaptive backoff: a request whose drafts keep getting
            # rejected stops paying the verify premium (rides the
            # window with n_in=1) until the periodic retry — p99 TPOT
            # must never be hostage to an undraftable stream.  The
            # retry itself probes with a SINGLE draft (one extra verify
            # lane); a hit restores credit and the next round drafts
            # the full k again.
            if req.spec_credit <= 0:
                req.spec_idle += 1
                if req.spec_idle >= SPEC_RETRY_EVERY:
                    d = min(d, 1)
                else:
                    d = 0
            if d > 0:
                drafts = propose_drafts(
                    np.concatenate([req.prompt,
                                    np.asarray(req.tokens, np.int32)]), d)
                if drafts:
                    toks[slot, 1:1 + len(drafts)] = drafts
                    n_in[slot] = 1 + len(drafts)
                    proposed += len(drafts)
                else:
                    # an attempted-but-empty draft round consumes credit
                    # too: without this, an undraftable (high-entropy)
                    # stream would re-scan its whole context EVERY
                    # iteration forever — the exact per-iteration host
                    # tax the backoff exists to bound
                    req.spec_idle = 0
                    req.spec_credit -= 1
        if proposed == 0:
            return self._decode(active)
        nb = self._nb_bucket(active, s_w)
        fn = dec.build_verify_fn(
            self.model, num_slots=self.num_slots, blocks_per_slot=nb,
            block_size=self.block_size, width=s_w, top_k=self.top_k,
            top_p=self.top_p)
        c0 = self.clock.now()
        t0 = time.perf_counter()
        with tel.span("serve/decode", batch=len(active),
                      rids=sorted(int(r.rid) for r in active),
                      iteration=self.iterations, spec=int(proposed),
                      t=round(c0, 6)):
            out_toks, ok, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(self._table[:, :nb]), jnp.asarray(toks),
                jnp.asarray(self._pos), jnp.asarray(n_in),
                jnp.asarray(self._temps), jnp.asarray(self._seeds),
                jnp.asarray(self._counts))
            out_toks = np.asarray(out_toks)
            ok = np.asarray(ok)
        self._book(("verify", nb, s_w, self.pool.hot_blocks),
                   time.perf_counter() - t0)
        self.clock.charge("verify", batch=len(active),
                          tokens=int(proposed))
        now = self.clock.now()
        emitted = 0
        accepted = 0
        self.batch_log.append(
            ("decode", tuple(sorted(r.rid for r in active))))
        for req in active:
            slot = req.slot
            if not bool(ok[slot]):
                self._scrub_blocks(req.blocks)
                self._invalidate_poisoned(req.blocks)
                self._evict(req, "failed", "serve/kv_evictions_total")
                self._emit(req, -1, True)
                continue
            # accept drafts while they equal the model's own choice
            a = 0
            while (a + 1 < int(n_in[slot])
                   and toks[slot, a + 1] == out_toks[slot, a]):
                a += 1
            row_emitted = 0
            for i in range(a + 1):
                tok = int(out_toks[slot, i])
                req.pos += 1
                self._pos[slot] += 1
                self._counts[slot] += 1
                self._tok[slot] = tok
                row_emitted += 1
                if self._token_out(req, tok, now):
                    break
            emitted += row_emitted
            # drafts that became emitted tokens (EOS can cut the tail)
            accepted += row_emitted - 1
            if int(n_in[slot]) > 1:
                req.spec_idle = 0
                if a > 0:
                    req.spec_credit = min(
                        max(req.spec_credit, 0) + a, SPEC_CREDIT_MAX)
                else:
                    req.spec_credit -= 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        with tel.get_registry().locked():
            tel.counter("serve/spec_proposed_total").inc(proposed)
            tel.counter("serve/spec_accepted_total").inc(accepted)
        tel.counter("serve/decode_iterations_total").inc()
        tel.counter("serve/tokens_generated_total").inc(emitted)
        # the EWMA learns seconds per EMITTED token per slot, so the
        # deadline feasibility math tracks the speculative rate.  A
        # zero-emission iteration (every slot evicted for non-finite
        # logits) is NOT a rate observation — dividing by an epsilon
        # token count would inflate the EWMA ~1e9x and shed every
        # queued request as infeasible for the next ~70 iterations.
        if emitted > 0:
            self.scheduler.observe_decode(
                self.clock.now() - c0,
                tokens_per_slot=emitted / len(active))

    def _oldest_active(self) -> Optional[Request]:
        act = self.scheduler.active()
        return min(act, key=lambda r: r.rid) if act else None

    def _serve_chaos(self) -> None:
        """Iteration-keyed serving faults (resilience/chaos.py):
        slow_decode advances the engine clock (virtual) or sleeps
        (wall) — the injected latency is indistinguishable from a slow
        decode to everything downstream (TTFT stamps, rate estimator,
        brownout signal); client_drop cancels the oldest active request
        the way a vanished TCP peer would; kv_poison NaN-scribbles the
        oldest active request's pool blocks so the decode step's
        finite-logits flag must catch it."""
        it = self.iterations
        delay = self.chaos.maybe_slow_decode(it)
        if delay > 0:
            self.clock.advance_to(self.clock.now() + delay)
        if self.chaos.maybe_client_drop(it):
            victim = self._oldest_active()
            if victim is not None:
                self.cancel(victim.rid)
        if self.chaos.maybe_kv_poison(it):
            victim = self._oldest_active()
            if victim is not None and victim.blocks:
                import jax.numpy as jnp
                blocks = np.asarray(victim.blocks, np.int32)
                self.pool.k = self.pool.k.at[:, blocks].set(jnp.nan)
                self.pool.v = self.pool.v.at[:, blocks].set(jnp.nan)

    def step(self) -> bool:
        """One engine iteration: admit + prefill, then one decode step
        for every occupied slot.  Continuous mode refills freed slots on
        the SAME iteration a request finishes (the eviction happened in
        ``_finish`` before this admit runs).  Returns whether any work
        ran — False means the scheduler is batch-forming (static mode's
        fill-or-timeout wait) and the caller should advance the clock to
        the next actionable instant instead of spinning."""
        it0 = time.perf_counter()
        prod0 = tel.get_tracker().buckets["productive"]
        comp0 = tel.get_tracker().buckets["compile"]
        if self.chaos is not None:
            self._serve_chaos()
        admitted = self.scheduler.admit(self.clock.now())
        if admitted:
            self._ensure_hot_prefix()
            self._prefill_admitted(admitted)
        active = self.scheduler.active()
        if active:
            self._ensure_hot_prefix()
            if self.spec_k > 0:
                self._spec_decode(active)
            else:
                self._decode(active)
        if self.brownout is not None:
            level = self.brownout.update(
                self.iterations,
                self.scheduler.oldest_queued_wait_s(self.clock.now()))
            tel.gauge("serve/brownout_level").set(level)
            if level != self._prev_brownout_level:
                # evidence instant for the incident correlator: the
                # brownout plane changed state (brownout.py itself
                # stays telemetry-free; the engine owns the edge)
                tel.instant("event/brownout_transition",
                            old=self._prev_brownout_level, new=level,
                            iteration=self.iterations)
                self._prev_brownout_level = level
        if self.slo is not None:
            self.slo.update(self.clock.now(), self.iterations)
        if self.controller is not None:
            # after brownout/slo updates: the controller's consistent
            # cut reads THIS iteration's burn gauges and service level
            self.controller.decide(self.clock.now(), self.iterations)
        self.iterations += 1
        if self.heartbeat is not None:
            self.heartbeat(self.iterations)
        # KV-pool observability (serve/paged_kv.py pool_observation):
        # pool pressure is visible BEFORE admission starts rejecting —
        # in-use/frac/hot-prefix plus the HBM bytes the live blocks pin.
        # Pure host arithmetic (no device sync); the group updates under
        # the registry lock so a /statz or /memz scrape never reads the
        # in-use count without its matching fraction.
        from dtf_tpu.serve.paged_kv import pool_observation
        obs = pool_observation(self.scheduler.allocator, self.pool)
        used = obs["blocks_in_use"]
        self._blocks_peak = max(self._blocks_peak, used)
        self._pool_frac_peak = max(self._pool_frac_peak, obs["pool_frac"])
        with tel.get_registry().locked():
            tel.gauge("serve/kv_blocks_peak").set(self._blocks_peak)
            # (renamed from serve/kv_blocks_used — ISSUE 15's KV
            # observability family is the canonical spelling)
            tel.gauge("serve/kv_blocks_in_use").set(used)
            tel.gauge("serve/kv_pool_frac").set(obs["pool_frac"])
            tel.gauge("serve/kv_hot_prefix_blocks").set(
                obs["hot_prefix_blocks"])
            tel.gauge("serve/kv_cached_blocks").set(
                self.scheduler.allocator.cached_blocks)
            tel.gauge("hbm/kv_pool_bytes").set(obs["bytes_in_use"])
        tel.gauge("serve/queue_depth").set(len(self.scheduler.queue))
        tel.gauge("serve/active_requests").set(self.scheduler.num_active())
        self.anomaly.observe("serve/queue_depth",
                             len(self.scheduler.queue),
                             tick=self.iterations)
        tracker = tel.get_tracker()
        booked = ((tracker.buckets["productive"] - prod0)
                  + (tracker.buckets["compile"] - comp0))
        tracker.add("other",
                    max(0.0, time.perf_counter() - it0 - booked))
        return bool(admitted or active)

    # -- graceful drain -----------------------------------------------------

    def request_drain(self) -> None:
        """Signal-handler-safe drain request (sets one flag; the engine
        loop performs the actual drain at the next iteration boundary —
        same discipline as utils/preemption.py)."""
        self._drain_requested = True

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Graceful shutdown: freeze admissions, keep decoding until the
        in-flight batch finishes (or the wall-clock timeout — the
        preemption grace window — runs out), then checkpoint every
        accepted-but-unfinished request as a replay doc.  Replay in a
        fresh engine is token-identical: per-request rng streams are
        (seed, rid)-keyed, so an interrupted request redraws the exact
        same tokens from scratch (tested).  Queued requests and
        timeout-stranded in-flight requests both land in
        ``drain_docs``; zero accepted work is lost."""
        t0 = time.monotonic()
        self.scheduler.draining = True
        tel.instant("event/serve_drain", iteration=self.iterations,
                    active=self.scheduler.num_active(),
                    queued=len(self.scheduler.queue))
        while (self.scheduler.num_active()
               and time.monotonic() - t0 < timeout_s):
            self.step()
        timed_out = self.scheduler.num_active() > 0
        unfinished: List[dict] = []
        for req in self.scheduler.active() + list(self.scheduler.queue):
            unfinished.append(req.replay_doc())
            self._evict(req, "drained", "serve/drained_total")
            self._emit(req, -1, True)
        self.drain_docs = sorted(unfinished, key=lambda d: d["rid"])
        self.drained = True
        return {"unfinished": self.drain_docs,
                "drain_s": time.monotonic() - t0,
                "timed_out": timed_out}

    # -- closed-loop driving ------------------------------------------------

    def run(self, trace=None, max_iterations: int = 1_000_000,
            drain_timeout_s: float = 30.0) -> Dict:
        """Drive the engine until idle.  ``trace`` is an optional sorted
        ``[(arrival_s, request_kwargs), ...]`` — requests are submitted
        as the clock passes their arrival instants (closed loop: the
        server's own pace decides when it looks at the queue).  Returns
        ``self.results``."""
        trace = list(trace or [])
        i = 0
        it = 0
        while i < len(trace) or self.scheduler.has_work():
            if self._drain_requested and not self.drained:
                # Preemption (SIGTERM): drain instead of dying mid-batch.
                # Trace entries not yet submitted were never ACCEPTED —
                # a real client would retry them against the next
                # process; accepted-but-unfinished work is checkpointed.
                self.drain(drain_timeout_s)
                break
            if it >= max_iterations:
                raise RuntimeError(
                    f"engine did not drain within {max_iterations} "
                    f"iterations — wedged scheduler?")
            now = self.clock.now()
            while i < len(trace) and trace[i][0] <= now:
                t_arr, kw = trace[i]
                self.submit(arrival_s=t_arr, **kw)
                i += 1
            if not self.scheduler.has_work():
                if i >= len(trace):
                    break       # tail of the trace was shed at submit
                t0 = time.perf_counter()
                self.clock.advance_to(trace[i][0])
                tel.get_tracker().add(
                    "stall", time.perf_counter() - t0)
                continue
            progress = self.step()
            it += 1
            if not progress:
                # batch-forming (static fill-or-timeout): jump to the
                # earliest instant something can happen — the next
                # arrival or the oldest queued request aging past the
                # batch wait — instead of spinning the iteration loop.
                horizon = []
                if i < len(trace):
                    horizon.append(trace[i][0])
                if self.scheduler.queue:
                    horizon.append(self.scheduler.queue[0].arrival_s
                                   + self.scheduler.static_batch_wait_s)
                if horizon:
                    t0 = time.perf_counter()
                    self.clock.advance_to(min(horizon))
                    tel.get_tracker().add(
                        "stall", time.perf_counter() - t0)
        return self.results

    # -- reporting ----------------------------------------------------------

    def summary(self, slo_ttft_ms: Optional[float] = None) -> dict:
        """Latency/goodput aggregate for the report CLI and the load
        bench: TTFT/TPOT percentiles over completed requests, completed
        QPS over the measured makespan, and — given an SLO budget —
        **goodput QPS**: completed requests whose TTFT met the budget,
        per second of makespan (the MLPerf-style gate: latency under
        load, not a ladder slope)."""
        done = [r for r in self.results.values()
                if r.status == "completed"]
        by_status = {}
        for r in self.results.values():
            by_status[r.status] = by_status.get(r.status, 0) + 1
        out = {"mode": self.mode, "completed": len(done),
               "rejected": by_status.get("rejected", 0),
               "shed": by_status.get("shed", 0),
               "shed_reasons": dict(sorted(self.shed_reasons.items())),
               "cancelled": by_status.get("cancelled", 0),
               "failed": by_status.get("failed", 0),
               "drained_unfinished": by_status.get("drained", 0),
               "degraded": sum(1 for r in self.results.values()
                               if r.degraded),
               "slots": self.num_slots,
               "kv_blocks_total": self.pool.num_blocks - 1,
               "kv_blocks_peak": self._blocks_peak,
               "kv_blocks_in_use": self.scheduler.allocator.used_blocks,
               "kv_pool_frac_peak": round(self._pool_frac_peak, 6),
               "kv_hot_prefix_blocks": self.pool.hot_blocks,
               "kv_block_size": self.block_size,
               "prefill_calls": self.prefill_calls,
               "decode_iterations": sum(
                   1 for e in self.batch_log if e[0] == "decode")}
        if self.prefix_cache:
            probed = self.prefix_probed_blocks
            out["prefix_cache"] = True
            out["prefix_lookups"] = self.prefix_lookups
            out["prefix_hit_blocks"] = self.prefix_hit_blocks
            out["prefix_probed_blocks"] = probed
            out["prefix_hit_rate"] = (
                self.prefix_hit_blocks / probed if probed else 0.0)
            out["kv_cached_blocks"] = (
                self.scheduler.allocator.cached_blocks)
        if self.spec_k > 0:
            out["spec_k"] = self.spec_k
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
            out["spec_acceptance"] = (
                self.spec_accepted / self.spec_proposed
                if self.spec_proposed else None)
        if self.brownout is not None:
            out["brownout"] = self.brownout.state()
        if self.slo is not None:
            out["slo"] = self.slo.state()
        if self.controller is not None:
            out["control"] = self.controller.summary()
        # Deadline accounting over ADMITTED-and-completed requests: a
        # violation is a completion later than (deadline + the SLO TTFT
        # budget) — the grace the SLO already tolerates at the front
        # door.  Sheds are NOT violations; shedding before prefill is
        # the contract working.
        with_dl = [r for r in done if r.deadline_ms is not None]
        if with_dl:
            grace_s = (slo_ttft_ms or 0.0) / 1e3
            viol = sum(1 for r in with_dl
                       if r.completion_s()
                       > r.deadline_ms / 1e3 + grace_s)
            out["deadline_requests_completed"] = len(with_dl)
            out["deadline_violations"] = viol
        if not done:
            return out
        ttft = np.array([r.ttft_s() for r in done]) * 1e3
        tpots = [r.tpot_s() for r in done if r.tpot_s() is not None]
        t0 = min(r.arrival_s for r in done)
        t1 = max(r.done_s for r in done)
        makespan = max(t1 - t0, 1e-9)
        pct = lambda a, q: float(np.percentile(np.asarray(a), q))
        out.update({
            "ttft_ms_p50": pct(ttft, 50), "ttft_ms_p99": pct(ttft, 99),
            "makespan_s": makespan,
            "completed_qps": len(done) / makespan,
            "tokens_out": int(sum(r.n_generated() for r in done)),
        })
        if tpots:
            tpot = np.array(tpots) * 1e3
            out["tpot_ms_p50"] = pct(tpot, 50)
            out["tpot_ms_p99"] = pct(tpot, 99)
        if slo_ttft_ms is not None:
            good = int(np.sum(ttft <= slo_ttft_ms))
            out["slo_ttft_ms"] = float(slo_ttft_ms)
            out["goodput_qps"] = good / makespan
            out["slo_attainment"] = good / len(done)
        return out

    def write_telemetry(self, logdir: str,
                        slo_ttft_ms: Optional[float] = None,
                        extra: Optional[dict] = None) -> str:
        doc = {"serving": {**self.summary(slo_ttft_ms), **(extra or {})}}
        return tel.write_telemetry_json(logdir, extra=doc)
