"""Line-oriented JSON-over-TCP front end for the serving engine.

The engine was in-process only; this is the minimal NETWORK edge that
makes it a server a load balancer can point at, built on stdlib
``socketserver`` (no new dependencies).  Framing: one JSON document per
``\\n``-terminated line, both directions.

Request line (client -> server)::

    {"prompt": [3, 17, 91], "max_new_tokens": 16,
     "temperature": 0.0, "deadline_ms": 1500, "priority": 1}

Response lines (server -> client), streamed as tokens are emitted::

    {"rid": 7, "token": 42, "done": false}
    ...
    {"rid": 7, "status": "completed", "n_tokens": 16}    # terminal line

A request that never starts streaming gets just the terminal line
(``status`` = ``rejected_*`` / ``shed_*`` with the reason, or
``drained`` when a graceful shutdown checkpointed it for replay).
Malformed input (bad JSON, missing/invalid fields, oversized lines)
earns ``{"error": ...}`` and the connection is closed — a front door
must never crash on garbage.

Failure handling, the part that makes this the PR's robustness edge:

* **per-connection timeouts** — a socket idle past ``conn_timeout_s``
  is closed (slowloris protection); a response stream stuck past
  ``request_timeout_s`` errors out rather than wedging its handler
  thread forever;
* **client disconnect** — a failed write cancels the request through
  :meth:`~dtf_tpu.serve.engine.ServingEngine.cancel`, which frees its
  KV blocks THAT iteration: a vanished reader cannot pin pool memory;
* **graceful drain** — SIGTERM (wired in ``__main__``) freezes the
  front door, finishes in-flight decodes, and every connection waiting
  on an unfinished request is told ``status: drained``.

Threading model: socket handler threads never touch the engine — they
post submissions/cancels into the :class:`FrontendBridge` mailbox and
block on a per-request event queue.  ONE thread (the caller of
:meth:`TCPFrontend.run_loop`) drives the engine, draining the mailbox
at each iteration boundary; the engine itself stays single-threaded and
lock-free.
"""

from __future__ import annotations

import json
import queue
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from dtf_tpu import telemetry as tel

#: Cap on one request line; a malformed client streaming an unbounded
#: "line" must not balloon server memory.
MAX_LINE_BYTES = 1 << 20


def parse_listen(spec: str) -> Tuple[str, int]:
    """``":8100"`` / ``"0.0.0.0:8100"`` -> (host, port)."""
    host, sep, port = spec.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"bad --listen {spec!r}; expected HOST:PORT or "
                         f":PORT")
    return host or "127.0.0.1", int(port)


def parse_request_line(line: bytes) -> dict:
    """Validate one request line into submit() kwargs.  Raises
    ``ValueError`` with a client-safe message on any malformation."""
    try:
        doc = json.loads(line.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise ValueError(f"malformed JSON: {exc}") from exc
    if not isinstance(doc, dict):
        raise ValueError("request must be a JSON object")
    prompt = doc.get("prompt")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and t >= 0 for t in prompt)):
        raise ValueError("'prompt' must be a non-empty list of token ids")
    max_new = doc.get("max_new_tokens", 16)
    if not isinstance(max_new, int) or max_new < 1:
        raise ValueError("'max_new_tokens' must be a positive int")
    deadline = doc.get("deadline_ms")
    if deadline is not None and (not isinstance(deadline, (int, float))
                                 or deadline <= 0):
        raise ValueError("'deadline_ms' must be a positive number")
    priority = doc.get("priority", 0)
    if not isinstance(priority, int):
        raise ValueError("'priority' must be an int")
    temperature = doc.get("temperature", 0.0)
    if not isinstance(temperature, (int, float)) or temperature < 0:
        raise ValueError("'temperature' must be a non-negative number")
    # Distributed tracing: the trace id is minted HERE, at the network
    # edge (a client may also carry its own through a retry), so the
    # request's timeline starts where the operator's responsibility
    # does.  Echoed on the terminal response line for correlation.
    trace_id = doc.get("trace_id")
    if trace_id is not None and (not isinstance(trace_id, str)
                                 or not (1 <= len(trace_id) <= 64)):
        raise ValueError("'trace_id' must be a short string")
    from dtf_tpu.telemetry.reqtrace import mint_trace_id
    out = {"prompt": np.asarray(prompt, np.int32),
           "max_new_tokens": max_new,
           "temperature": float(temperature),
           "deadline_ms": deadline, "priority": priority,
           "trace_id": trace_id or mint_trace_id()}
    # Fleet wire: the acceptor (serve/fleet.py) mints fleet-unique rids
    # and carries them to the replica so a failover replay on a survivor
    # reuses the SAME (seed, rid)-keyed rng stream — token identity
    # across the failure domain.  ``resubmit`` marks the replay segment
    # in the request's reqtrace chain.  Plain clients send neither.
    rid = doc.get("rid")
    if rid is not None:
        if not isinstance(rid, int) or isinstance(rid, bool) or rid < 0:
            raise ValueError("'rid' must be a non-negative int")
        out["rid"] = rid
    resubmit = doc.get("resubmit", False)
    if not isinstance(resubmit, bool):
        raise ValueError("'resubmit' must be a bool")
    if resubmit:
        out["resubmit"] = True
    return out


class FrontendBridge:
    """Thread-safe mailbox between socket handler threads and the one
    engine-driving thread.  Handlers post work; the engine loop drains
    it at iteration boundaries; token events flow back through
    per-request queues."""

    def __init__(self):
        self.submissions: "queue.Queue" = queue.Queue()
        self.cancels: "queue.Queue" = queue.Queue()
        self.work_ready = threading.Event()
        self._streams: Dict[int, "queue.Queue"] = {}
        self._lock = threading.Lock()

    # handler side ----------------------------------------------------------

    def submit(self, kwargs: dict) -> "queue.Queue":
        """Post a submission; returns the event queue its response
        stream will arrive on."""
        events: "queue.Queue" = queue.Queue()
        self.submissions.put((kwargs, events))
        self.work_ready.set()
        return events

    def cancel(self, rid: int) -> None:
        self.cancels.put(rid)
        self.work_ready.set()

    # engine side -----------------------------------------------------------

    def register(self, rid: int, events: "queue.Queue") -> None:
        with self._lock:
            self._streams[rid] = events

    def route(self, rid: int, event: dict) -> None:
        with self._lock:
            q = self._streams.get(rid)
        if q is not None:
            q.put(event)
            if event.get("terminal"):
                with self._lock:
                    self._streams.pop(rid, None)

    def abort_all(self, status: str) -> None:
        """Terminal-line every stream still waiting (server shutdown)."""
        with self._lock:
            streams, self._streams = dict(self._streams), {}
        for rid, q in streams.items():
            q.put({"rid": rid, "status": status, "terminal": True})


class TCPFrontend:
    """Owns the ``socketserver`` + bridge + engine loop.  Construct,
    then call :meth:`run_loop` from the thread that owns the engine
    (blocks until :meth:`shutdown` or an engine drain)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0, *,
                 conn_timeout_s: float = 30.0,
                 request_timeout_s: float = 120.0):
        self.engine = engine
        self.bridge = FrontendBridge()
        self.conn_timeout_s = conn_timeout_s
        self.request_timeout_s = request_timeout_s
        self._shutdown = False
        self._drain_status: Optional[dict] = None
        # Fleet control surface: a wedge deadline (chaos replica_wedge —
        # the engine loop stops draining the mailbox and stepping until
        # it passes, so beats go stale exactly like a GC-paused process)
        # and a routing-stats snapshot the engine thread refreshes and
        # handler threads serve on {"stats": true} without ever touching
        # the engine (atomic reference swap, same mailbox discipline).
        self.wedge_until: float = 0.0
        self.stats: dict = {"queue_depth": 0, "active": 0,
                            "iterations": 0, "brownout_level": 0,
                            "kv_pool_frac": 0.0, "slo_fast_firing": 0,
                            "draining": False, "completed": 0}
        self._stats_at = 0.0
        self._conns: set = set()
        self._conns_lock = threading.Lock()

        # Engine streaming -> bridge routing.  Chain any pre-existing
        # on_token (e.g. --stream printing) rather than replacing it.
        prev = engine.on_token

        def on_token(req, token, done):
            if prev is not None:
                prev(req, token, done)
            if token >= 0:
                self.bridge.route(req.rid, {"rid": req.rid, "token": token,
                                            "done": done})
            if done:
                self.bridge.route(req.rid, {
                    "rid": req.rid, "status": req.status,
                    "n_tokens": req.n_generated(),
                    "trace_id": req.trace_id, "terminal": True})

        engine.on_token = on_token

        frontend = self

        class Handler(socketserver.StreamRequestHandler):
            timeout = conn_timeout_s

            def handle(self):
                tel.counter("serve/conn_total").inc()
                self.connection.settimeout(conn_timeout_s)
                frontend._track_conn(self.connection, True)
                try:
                    while not frontend._shutdown:
                        line = self.rfile.readline(MAX_LINE_BYTES + 1)
                        if not line:
                            return                    # client closed
                        if not line.strip():
                            continue
                        if len(line) > MAX_LINE_BYTES:
                            self._error("request line too large")
                            return
                        ctl = frontend._maybe_control(line.strip())
                        if ctl is not None:
                            self._send(ctl)
                            continue
                        try:
                            kwargs = parse_request_line(line.strip())
                        except ValueError as exc:
                            self._error(str(exc))
                            return
                        if not self._stream_one(kwargs):
                            return
                except (TimeoutError, OSError):
                    # idle/read timeout or transport error: just close
                    # (any in-flight request was already handled by
                    # _stream_one's own error path)
                    tel.counter("serve/conn_errors_total").inc()
                finally:
                    frontend._track_conn(self.connection, False)

            def _send(self, doc: dict) -> None:
                self.wfile.write((json.dumps(doc, sort_keys=True) + "\n")
                                 .encode("utf-8"))
                self.wfile.flush()

            def _error(self, message: str) -> None:
                tel.counter("serve/conn_errors_total").inc()
                try:
                    self._send({"error": message})
                except OSError:
                    pass

            def _stream_one(self, kwargs: dict) -> bool:
                """Submit + stream one request; returns False when the
                connection should close."""
                events = frontend.bridge.submit(kwargs)
                rid = None
                while True:
                    try:
                        ev = events.get(timeout=frontend.request_timeout_s)
                    except queue.Empty:
                        self._error("response stream timed out")
                        if rid is not None:
                            frontend.bridge.cancel(rid)
                        return False
                    rid = ev["rid"]
                    out = {k: v for k, v in ev.items() if k != "terminal"}
                    try:
                        self._send(out)
                    except OSError:
                        # client went away mid-stream: free its KV
                        # blocks immediately
                        tel.counter("serve/conn_errors_total").inc()
                        frontend.bridge.cancel(rid)
                        return False
                    if ev.get("terminal"):
                        return True

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.address = self.server.server_address
        self._server_thread = threading.Thread(
            target=self.server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="dtf-serve-acceptor")

    # -- control messages (handler threads; never touch the engine) ---------

    def _track_conn(self, conn, add: bool) -> None:
        with self._conns_lock:
            (self._conns.add if add else self._conns.discard)(conn)

    def _maybe_control(self, line: bytes) -> Optional[dict]:
        """A control line — ``{"cancel": rid}`` / ``{"stats": true}`` /
        ``{"wedge_ms": D}`` — gets a one-line reply; returns None for
        anything else (falls through to request parsing)."""
        try:
            doc = json.loads(line.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            return None
        if not isinstance(doc, dict):
            return None
        if "cancel" in doc:
            rid = doc["cancel"]
            if not isinstance(rid, int) or isinstance(rid, bool) or rid < 0:
                return {"error": "'cancel' must be a non-negative rid"}
            self.bridge.cancel(rid)
            return {"ok": True, "cancel": rid}
        if "stats" in doc:
            return {"ok": True, "stats": self.stats}
        if "wedge_ms" in doc:
            dur = doc["wedge_ms"]
            if not isinstance(dur, (int, float)) or dur <= 0:
                return {"error": "'wedge_ms' must be a positive number"}
            self.wedge_until = time.monotonic() + float(dur) / 1e3
            return {"ok": True, "wedge_ms": float(dur)}
        return None

    # -- engine loop --------------------------------------------------------

    def _build_stats(self) -> dict:
        """The routing snapshot (engine thread only): what the fleet
        acceptor's admission control loop weighs — queue depth, brownout
        state, KV-pool pressure, SLO fast-burn — read from the engine at
        an iteration boundary, never from a handler."""
        eng = self.engine
        alloc = eng.scheduler.allocator
        usable = max(alloc.num_blocks - 1, 1)
        fast_firing = 0
        if eng.slo is not None:
            try:
                objs = eng.slo.state().get("objectives", {})
                fast_firing = sum(1 for o in objs.values()
                                  if o.get("firing_fast"))
            except Exception:
                pass
        return {"queue_depth": len(eng.scheduler.queue),
                "active": len(eng.scheduler.active()),
                "iterations": eng.iterations,
                "brownout_level": (eng.brownout.level if eng.brownout
                                   else 0),
                "kv_pool_frac": round(alloc.used_blocks / usable, 4),
                "slo_fast_firing": fast_firing,
                "draining": bool(eng._drain_requested or eng.drained),
                "completed": sum(1 for r in eng.results.values()
                                 if r.status == "completed")}

    def run_once(self) -> bool:
        """One engine-loop slice: honor a wedge, drain the mailbox,
        refresh the routing snapshot, step if there is work.  Returns
        True when the engine made progress (False = idle or wedged).
        The single-frontend :meth:`run_loop` and the fleet's one-thread
        round-robin driver (serve/fleet.py) both build on this — the
        fleet driver MUST interleave replicas from one thread, or their
        concurrently-booked goodput categories overcount wall-clock and
        the books gate fails on an honest run."""
        now = time.monotonic()
        if now < self.wedge_until:
            return False       # wedged: mailbox backs up, beats stop
        self._drain_mailbox()
        if now - self._stats_at > 0.02:
            self.stats = self._build_stats()
            self._stats_at = now
        if self.engine.scheduler.has_work():
            self.engine.step()
            return True
        return False

    def kill(self) -> None:
        """Abrupt death for fleet chaos (``replica_down``): sever every
        open connection and stop accepting — no drain, no
        ``abort_all`` goodbyes.  A SIGKILLed process sends nothing; its
        peers must notice from the severed sockets and stale beats."""
        import socket as _socket
        self._shutdown = True
        self.server.shutdown()
        self.server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _drain_mailbox(self) -> None:
        while True:
            try:
                rid = self.bridge.cancels.get_nowait()
            except queue.Empty:
                break
            self.engine.cancel(rid)
        while True:
            try:
                kwargs, events = self.bridge.submissions.get_nowait()
            except queue.Empty:
                break
            req = self.engine.submit(**kwargs)
            self.bridge.register(req.rid, events)
            if req.status not in ("queued", "running"):
                # rejected/shed at the front door: terminal line now
                self.bridge.route(req.rid, {
                    "rid": req.rid, "status": (
                        f"shed_{req.shed_reason}" if req.status == "shed"
                        else req.status),
                    "reason": req.shed_reason,
                    "trace_id": req.trace_id, "terminal": True})

    def run_loop(self, drain_timeout_s: float = 30.0,
                 idle_wait_s: float = 0.02) -> Optional[dict]:
        """Drive the engine until :meth:`shutdown` or a requested drain.
        Returns the drain result (None for a plain shutdown)."""
        self._server_thread.start()
        try:
            while not self._shutdown:
                if self.engine._drain_requested and not self.engine.drained:
                    self._drain_mailbox()      # last-chance submissions
                    self._drain_status = self.engine.drain(drain_timeout_s)
                    break
                if not self.run_once():
                    # book the idle wait as stall, same as engine.run's
                    # between-arrivals waits — otherwise a mostly-idle
                    # server's goodput books don't sum to wall-clock
                    # and report --check fails on an honest run
                    t0 = time.perf_counter()
                    self.bridge.work_ready.wait(idle_wait_s)
                    self.bridge.work_ready.clear()
                    tel.get_tracker().add("stall",
                                          time.perf_counter() - t0)
        finally:
            self.shutdown()
        return self._drain_status

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        self.bridge.abort_all(
            "drained" if self.engine.drained else "server_shutdown")
        self.server.shutdown()
        self.server.server_close()
