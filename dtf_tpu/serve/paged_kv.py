"""Paged (blocked) KV cache: fixed-size HBM blocks + a free-list allocator.

The serving memory problem the contiguous cache cannot solve: a decode
batch's requests have *different* lengths, and a per-slot contiguous
cache must pad every slot to the model window — a 32-slot GPT-2-small
server at max_len 1024 reserves ~0.6 GB of KV rows it mostly never
writes.  Here the cache is ONE shared pool of fixed-size blocks
(``block_size`` token rows each); a request owns only the blocks its
actual prompt+generation needs, recorded in a per-request **block
table** that maps logical position -> physical block.  Finished
requests return their blocks to the free list, so short and long
streams share the same HBM pool (the vLLM paged-attention memory
model, applied to this repo's decode path).

Split of responsibilities:

* :class:`BlockAllocator` — pure-Python, deterministic free-list
  (lowest-id-first so identical schedules produce identical physical
  layouts; tests pin this).
* :class:`KVPool` — the device arrays: ``k``/``v`` of shape
  ``(L, num_blocks, block_size, KVH·Dh)`` plus scatter helpers.  Block
  0 is the **trash block**: never allocated, the write target for
  inactive decode slots (a static-shape decode step writes a row for
  every slot; pointing dead slots at block 0 keeps their garbage out
  of live blocks, and gathered trash rows are masked before softmax).
* Per-request block tables live host-side in the scheduler; the decode
  step receives them as a dense ``(slots, blocks_per_slot)`` int32
  array where ``-1`` means "no block" (gathers clamp to the trash
  block; masking makes the value irrelevant).

CPU-sim honesty note: the decode step *gathers* each slot's blocks
into logical order before attention (``pool[table]``), which
materializes a transient contiguous view — correct everywhere, and
exactly what the parity test leans on (the gather of a permuted table
is bit-identical to the contiguous layout).  On real TPU hardware the
gather is instead a block-indexed DMA inside the paged decode kernel
(``ops.decode_kernel.paged_attention``); the *pool residency* — the
HBM claim — is what paging buys at either maturity level.

Cost model note (the narrowed data path): the jitted step consumes the
pool FUNCTIONALLY — on backends without donation (the CPU sim) every
step's scatter copies the whole pool, so per-token cost scales with
POOL SIZE, not with context used.  The allocator hands out lowest ids
first, so live blocks concentrate in a low prefix; :meth:`KVPool.
ensure_hot` keeps exactly that prefix (bucketed) as the working "hot"
arrays the step touches, parking the tail in cold storage that only
moves on bucket transitions.  Per-token cost then scales with the
pool's *high-water mark*, and the decode ladder's oversized-pool
invariance gate pins it.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

#: Physical block id reserved as the write sink for inactive slots /
#: unassigned table entries.  Never handed out by the allocator.
TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Allocation failed — the admission path treats this as "stay
    queued", never as a crash."""


def chunk_digests(tokens: Sequence[int], block_size: int,
                  n_blocks: int) -> List[bytes]:
    """Hash-chain digests over the first ``n_blocks`` block-sized token
    chunks of ``tokens``: ``digest[i] = blake2b(digest[i-1] || chunk_i)``.
    Chunk ``i``'s digest therefore commits to the WHOLE token prefix
    through block ``i`` — exactly what a KV block's rows depend on (row
    ``t`` attends positions ``0..t``), so equal digests mean bitwise-
    reusable block content (the cold prefill executables are padding-
    length invariant; pinned by tests).  The radix-style index keys on
    these digests: a walk that stops at the first miss can never match
    a block whose prefix context diverged."""
    out: List[bytes] = []
    prev = b""
    toks = np.asarray(tokens, np.int32)
    for i in range(n_blocks):
        chunk = toks[i * block_size:(i + 1) * block_size]
        if len(chunk) < block_size:
            break
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(chunk.tobytes())
        prev = h.digest()
        out.append(prev)
    return out


class BlockAllocator:
    """Deterministic free-list over physical block ids ``1..num_blocks-1``
    (block 0 is the trash block).

    Lowest-id-first allocation: the same admission schedule always
    produces the same physical layout, which the scheduler-determinism
    tests pin (and which makes paged-vs-contiguous parity failures
    reproducible instead of heisenbugs).

    **Prefix sharing** (the sharing-aware pool): every live block carries
    a refcount — fresh allocations start at 1, :meth:`acquire` pins a
    matched shared block for one more owner, :meth:`free` decrements.  A
    content-registered block (:meth:`register_chain`) whose refcount
    drops to 0 does NOT return to the free list: it PARKS in the cached
    tier (LRU order) and stays matchable through the digest index until
    allocation pressure reclaims it lazily (:meth:`allocate` drains the
    free list first, then the cached tier oldest-first).  Cache capacity
    is therefore exactly the pool's idle headroom: ``free_blocks`` counts
    free + cached (both are allocatable on demand), so the scheduler's
    worst-case reservation math — and the leak assertions — see parked
    blocks as available and mid-flight exhaustion stays impossible by
    construction.  An engine that never registers content never parks a
    block, and every path below degenerates bit-for-bit to the plain
    free-list behavior (the cache-off arm's determinism pin).
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block {TRASH_BLOCK} is the reserved "
                f"trash block), got {num_blocks}")
        self.num_blocks = num_blocks
        # sorted free list; pop from the front = lowest id first
        self._free: List[int] = list(range(1, num_blocks))
        # allocated ids, maintained incrementally: highest_used() must
        # be O(live blocks), never O(pool) — an O(pool) scan per engine
        # iteration would reintroduce exactly the pool-size cost term
        # the narrowed data path exists to remove (measured)
        self._used: set = set()
        # live refcounts (>= 1 for every block in _used; a block is in
        # exactly one of: _free, _cached, _used)
        self._ref: Dict[int, int] = {}
        # parked refcount-0 registered blocks, insertion order = LRU
        # reclaim order (oldest-parked first)
        self._cached: "OrderedDict[int, bytes]" = OrderedDict()
        # content index: chain digest -> physical block (live or parked)
        self._index: Dict[bytes, int] = {}
        self._block_key: Dict[int, bytes] = {}

    @property
    def free_blocks(self) -> int:
        # parked cached blocks are allocatable on demand (lazy reclaim),
        # so they count as free — the reservation math and the leak
        # assertions both want "blocks nobody is holding"
        return len(self._free) + len(self._cached)

    #: alias used by the leak assertions: the number of free blocks must
    #: return to its initial value after any churn of allocate/free —
    #: including client disconnects and mid-prefill cancels (tested).
    free_count = free_blocks

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free) - len(self._cached)

    @property
    def cached_blocks(self) -> int:
        """Parked (refcount-0, content-registered) blocks — the
        ``serve/kv_cached_blocks`` gauge."""
        return len(self._cached)

    def can_allocate(self, n: int) -> bool:
        return n <= self.free_blocks

    def allocate(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > self.free_blocks:
            raise PoolExhausted(
                f"asked for {n} KV blocks, {self.free_blocks} free "
                f"(pool {self.num_blocks - 1} usable)")
        take = min(n, len(self._free))
        out, self._free = self._free[:take], self._free[take:]
        # allocation pressure: reclaim parked cache blocks lazily,
        # oldest-parked first (LRU) — deterministic, like the free list
        while len(out) < n:
            b, _ = self._cached.popitem(last=False)
            self._unregister(b)
            out.append(b)
        self._used.update(out)
        for b in out:
            self._ref[b] = 1
        return out

    def free(self, blocks: List[int]) -> None:
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"double free within one release: {blocks}")
        for b in blocks:
            if not (0 < b < self.num_blocks):
                raise ValueError(f"freeing block {b} outside the pool")
            if b not in self._used:
                raise ValueError(f"double free of block {b}")
        release = []
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] > 0:
                continue                  # another sharer still holds it
            del self._ref[b]
            self._used.discard(b)
            key = self._block_key.get(b)
            if key is not None:
                # registered content: park instead of freeing — stays
                # matchable until allocation pressure reclaims it
                self._cached[b] = key
            else:
                release.append(b)
        if release:
            # keep the free list sorted so allocation order stays
            # canonical
            self._free = sorted(self._free + release)

    def acquire(self, blocks: List[int]) -> None:
        """Pin matched shared blocks for one more owner: live blocks get
        a refcount bump; parked blocks un-park back into the live set.
        Must only be handed blocks returned by :meth:`match_chain` (a
        free-list block here is a bookkeeping bug and raises)."""
        for b in blocks:
            if b in self._used:
                self._ref[b] += 1
            elif b in self._cached:
                del self._cached[b]
                self._used.add(b)
                self._ref[b] = 1
            else:
                raise ValueError(
                    f"acquiring block {b} that is neither live nor "
                    f"cached")

    def ref_count(self, block: int) -> int:
        """Live owners of ``block`` (0 when parked or free)."""
        return self._ref.get(block, 0)

    def match_chain(self, digests: Sequence[bytes]) -> List[int]:
        """Walk the digest chain through the index; returns the matched
        physical blocks for the longest indexed prefix (stops at the
        first miss — descendants of a missing link are unreachable by
        construction, the radix property).  Read-only: callers pin the
        result with :meth:`acquire` before relying on it."""
        out: List[int] = []
        for d in digests:
            b = self._index.get(d)
            if b is None:
                break
            out.append(b)
        return out

    def register_chain(self, digests: Sequence[bytes],
                       blocks: Sequence[int]) -> int:
        """Publish freshly-prefilled full-content blocks into the index
        (``digests[i]`` describes ``blocks[i]``'s content chain).  A
        digest already indexed keeps its existing physical block (first
        writer wins — the racing copy simply stays unregistered and
        frees normally); a block already registered under another key
        is skipped.  Returns the number of new registrations."""
        n = 0
        for d, b in zip(digests, blocks):
            if d in self._index or b in self._block_key:
                continue
            if b not in self._used:
                raise ValueError(
                    f"registering block {b} that is not live")
            self._index[d] = b
            self._block_key[b] = d
            n += 1
        return n

    def invalidate_blocks(self, blocks) -> None:
        """Corruption path (kv_poison): tear the given blocks out of the
        content index so no future request can match poisoned rows.  A
        parked victim additionally moves to the free list (its content
        is the only thing that kept it parked); live victims stay owned
        — their sharers' release walk frees them normally (and, being
        unregistered now, they fall to the free list, never back into
        the cached tier)."""
        release = []
        for b in blocks:
            self._unregister(b)
            if b in self._cached:
                del self._cached[b]
                release.append(b)
        if release:
            self._free = sorted(self._free + release)

    def _unregister(self, b: int) -> None:
        key = self._block_key.pop(b, None)
        if key is not None and self._index.get(key) == b:
            del self._index[key]

    def highest_used(self) -> int:
        """Largest physical block id currently allocated OR parked in
        the cached tier (0 = none; the trash block is always id 0).
        Lowest-id-first allocation keeps live blocks in a low prefix,
        so ``highest_used() + 1`` is the pool prefix the decode step
        actually needs resident — the narrowed data path's hot-prefix
        bound.  Parked blocks count: their rows are live content a
        future match maps straight into a request's table, so
        ``KVPool.ensure_hot`` must keep them resident (migrating one to
        cold storage would hand a matched request a stale gather —
        pinned by the churn/cache-hits composition test).  O(live +
        cached blocks), never O(pool) (called every engine
        iteration)."""
        live = max(self._used, default=0)
        if self._cached:
            return max(live, max(self._cached))
        return live


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` KV rows (ceil division)."""
    return -(-max(tokens, 0) // block_size)


@dataclasses.dataclass
class KVPool:
    """The device-resident block pool for one model.

    ``k``/``v``: ``(num_layers, hot_blocks, block_size, KVH·Dh)`` in the
    model dtype — the HOT prefix of the pool, the only arrays the jitted
    steps touch.  ``cold_k``/``cold_v`` hold the tail blocks
    (``num_blocks - hot_blocks``) that no live request reaches; they
    move between hot and cold only at :meth:`ensure_hot` bucket
    transitions, never per step.  A pool created with
    ``ensure_hot(num_blocks)`` (the default) is the classic whole-pool
    layout — the ladder's baseline arm.

    Functional updates (jax arrays are immutable): the scatter helpers
    return NEW pool arrays; the engine threads them through its jitted
    step exactly like the contiguous cache threads through ``lax.scan``
    in ``GPT.generate``.
    """

    k: "object"            # jax array (hot prefix)
    v: "object"
    block_size: int
    cold_k: "object" = None    # jax array (tail), zero-width when all hot
    cold_v: "object" = None

    @classmethod
    def create(cls, cfg, num_blocks: int, block_size: int,
               dtype=None) -> "KVPool":
        import jax.numpy as jnp

        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        kvh = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.dim // cfg.num_heads
        shape = (cfg.num_layers, num_blocks, block_size, kvh * hd)
        cold = (cfg.num_layers, 0, block_size, kvh * hd)
        dt = dtype or cfg.dtype
        return cls(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                   block_size=block_size,
                   cold_k=jnp.zeros(cold, dt), cold_v=jnp.zeros(cold, dt))

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1] + self.cold_k.shape[1]

    @property
    def hot_blocks(self) -> int:
        return self.k.shape[1]

    def ensure_hot(self, h: int) -> None:
        """Resize the hot prefix to exactly ``h`` blocks (ids ``0..h-1``).

        O(pool) concatenates, but only on bucket transitions — the
        engine buckets ``h`` to powers of two of the allocator's
        high-water mark, so steady-state iterations never move a byte.
        Shrinking parks stale-but-finite freed blocks in cold storage;
        they are rewritten by prefill before any unmasked read when
        reallocated (trash block 0 is always hot)."""
        import jax.numpy as jnp

        if not (1 <= h <= self.num_blocks):
            raise ValueError(
                f"hot prefix {h} outside [1, {self.num_blocks}]")
        cur = self.hot_blocks
        if h == cur:
            return
        if h > cur:
            take = h - cur
            self.k = jnp.concatenate([self.k, self.cold_k[:, :take]],
                                     axis=1)
            self.v = jnp.concatenate([self.v, self.cold_v[:, :take]],
                                     axis=1)
            self.cold_k = self.cold_k[:, take:]
            self.cold_v = self.cold_v[:, take:]
        else:
            self.cold_k = jnp.concatenate([self.k[:, h:], self.cold_k],
                                          axis=1)
            self.cold_v = jnp.concatenate([self.v[:, h:], self.cold_v],
                                          axis=1)
            self.k = self.k[:, :h]
            self.v = self.v[:, :h]

    def bytes_per_block(self) -> int:
        """HBM bytes one block pins across both pool arrays."""
        per = self.k.dtype.itemsize
        l, _, bs, w = self.k.shape
        return 2 * l * bs * w * per


def pool_observation(allocator: BlockAllocator, pool: "KVPool") -> dict:
    """One consistent read of the pool's pressure for the KV
    observability gauges (``serve/kv_blocks_in_use`` /
    ``serve/kv_pool_frac`` / ``serve/kv_hot_prefix_blocks`` and the
    ``hbm/kv_pool_bytes`` claim): blocks in use, the fraction of the
    usable pool they pin, the hot-prefix width the jitted steps touch,
    and the HBM bytes the live blocks claim — pure host arithmetic off
    the allocator and the pool shapes, no device sync."""
    used = allocator.used_blocks
    usable = max(allocator.num_blocks - 1, 1)   # block 0 is the trash block
    return {"blocks_in_use": used,
            "pool_frac": used / usable,
            "hot_prefix_blocks": pool.hot_blocks,
            "bytes_in_use": used * pool.bytes_per_block()}


def dense_table(block_tables: List[Optional[List[int]]],
                blocks_per_slot: int) -> np.ndarray:
    """Host block tables (``None`` = empty slot) -> the dense
    ``(slots, blocks_per_slot)`` int32 array the decode step consumes.
    Unassigned entries are ``-1`` (the gather clamps them to the trash
    block; the visibility mask makes the gathered value irrelevant)."""
    out = np.full((len(block_tables), blocks_per_slot), -1, np.int32)
    for i, tbl in enumerate(block_tables):
        if tbl:
            if len(tbl) > blocks_per_slot:
                raise ValueError(
                    f"slot {i} holds {len(tbl)} blocks > window "
                    f"{blocks_per_slot}")
            out[i, :len(tbl)] = tbl
    return out


def contiguous_table(num_slots: int, blocks_per_slot: int) -> np.ndarray:
    """The identity block table: slot ``i`` owns blocks
    ``[1 + i·nbs, 1 + (i+1)·nbs)`` of a pool sized
    ``1 + num_slots·blocks_per_slot`` (block 0 stays the trash block).
    This IS the contiguous per-slot cache — same decode code path, no
    indirection benefit — and the baseline the paged parity test
    compares against: paged gather(permuted table) must emit the same
    tokens as gather(identity table)."""
    base = 1 + np.arange(num_slots, dtype=np.int32)[:, None] * blocks_per_slot
    return base + np.arange(blocks_per_slot, dtype=np.int32)[None, :]
