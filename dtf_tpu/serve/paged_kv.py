"""Paged (blocked) KV cache: fixed-size HBM blocks + a free-list allocator.

The serving memory problem the contiguous cache cannot solve: a decode
batch's requests have *different* lengths, and a per-slot contiguous
cache must pad every slot to the model window — a 32-slot GPT-2-small
server at max_len 1024 reserves ~0.6 GB of KV rows it mostly never
writes.  Here the cache is ONE shared pool of fixed-size blocks
(``block_size`` token rows each); a request owns only the blocks its
actual prompt+generation needs, recorded in a per-request **block
table** that maps logical position -> physical block.  Finished
requests return their blocks to the free list, so short and long
streams share the same HBM pool (the vLLM paged-attention memory
model, applied to this repo's decode path).

Split of responsibilities:

* :class:`BlockAllocator` — pure-Python, deterministic free-list
  (lowest-id-first so identical schedules produce identical physical
  layouts; tests pin this).
* :class:`KVPool` — the device arrays: ``k``/``v`` of shape
  ``(L, num_blocks, block_size, KVH·Dh)`` plus scatter helpers.  Block
  0 is the **trash block**: never allocated, the write target for
  inactive decode slots (a static-shape decode step writes a row for
  every slot; pointing dead slots at block 0 keeps their garbage out
  of live blocks, and gathered trash rows are masked before softmax).
* Per-request block tables live host-side in the scheduler; the decode
  step receives them as a dense ``(slots, blocks_per_slot)`` int32
  array where ``-1`` means "no block" (gathers clamp to the trash
  block; masking makes the value irrelevant).

CPU-sim honesty note: the decode step *gathers* each slot's blocks
into logical order before attention (``pool[table]``), which
materializes a transient contiguous view — correct everywhere, and
exactly what the parity test leans on (the gather of a permuted table
is bit-identical to the contiguous layout).  On real TPU hardware the
gather is instead a block-indexed DMA inside the paged decode kernel
(``ops.decode_kernel.paged_attention``); the *pool residency* — the
HBM claim — is what paging buys at either maturity level.

Cost model note (the narrowed data path): the jitted step consumes the
pool FUNCTIONALLY — on backends without donation (the CPU sim) every
step's scatter copies the whole pool, so per-token cost scales with
POOL SIZE, not with context used.  The allocator hands out lowest ids
first, so live blocks concentrate in a low prefix; :meth:`KVPool.
ensure_hot` keeps exactly that prefix (bucketed) as the working "hot"
arrays the step touches, parking the tail in cold storage that only
moves on bucket transitions.  Per-token cost then scales with the
pool's *high-water mark*, and the decode ladder's oversized-pool
invariance gate pins it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

#: Physical block id reserved as the write sink for inactive slots /
#: unassigned table entries.  Never handed out by the allocator.
TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Allocation failed — the admission path treats this as "stay
    queued", never as a crash."""


class BlockAllocator:
    """Deterministic free-list over physical block ids ``1..num_blocks-1``
    (block 0 is the trash block).

    Lowest-id-first allocation: the same admission schedule always
    produces the same physical layout, which the scheduler-determinism
    tests pin (and which makes paged-vs-contiguous parity failures
    reproducible instead of heisenbugs).
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block {TRASH_BLOCK} is the reserved "
                f"trash block), got {num_blocks}")
        self.num_blocks = num_blocks
        # sorted free list; pop from the front = lowest id first
        self._free: List[int] = list(range(1, num_blocks))
        # allocated ids, maintained incrementally: highest_used() must
        # be O(live blocks), never O(pool) — an O(pool) scan per engine
        # iteration would reintroduce exactly the pool-size cost term
        # the narrowed data path exists to remove (measured)
        self._used: set = set()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    #: alias used by the leak assertions: the number of free blocks must
    #: return to its initial value after any churn of allocate/free —
    #: including client disconnects and mid-prefill cancels (tested).
    free_count = free_blocks

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise PoolExhausted(
                f"asked for {n} KV blocks, {len(self._free)} free "
                f"(pool {self.num_blocks - 1} usable)")
        out, self._free = self._free[:n], self._free[n:]
        self._used.update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"double free within one release: {blocks}")
        for b in blocks:
            if not (0 < b < self.num_blocks):
                raise ValueError(f"freeing block {b} outside the pool")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
        # keep the free list sorted so allocation order stays canonical
        self._free = sorted(self._free + list(blocks))
        self._used.difference_update(blocks)

    def highest_used(self) -> int:
        """Largest physical block id currently allocated (0 = none; the
        trash block is always id 0).  Lowest-id-first allocation keeps
        live blocks in a low prefix, so ``highest_used() + 1`` is the
        pool prefix the decode step actually needs resident — the
        narrowed data path's hot-prefix bound.  O(live blocks) by
        construction (called every engine iteration)."""
        return max(self._used, default=0)


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` KV rows (ceil division)."""
    return -(-max(tokens, 0) // block_size)


@dataclasses.dataclass
class KVPool:
    """The device-resident block pool for one model.

    ``k``/``v``: ``(num_layers, hot_blocks, block_size, KVH·Dh)`` in the
    model dtype — the HOT prefix of the pool, the only arrays the jitted
    steps touch.  ``cold_k``/``cold_v`` hold the tail blocks
    (``num_blocks - hot_blocks``) that no live request reaches; they
    move between hot and cold only at :meth:`ensure_hot` bucket
    transitions, never per step.  A pool created with
    ``ensure_hot(num_blocks)`` (the default) is the classic whole-pool
    layout — the ladder's baseline arm.

    Functional updates (jax arrays are immutable): the scatter helpers
    return NEW pool arrays; the engine threads them through its jitted
    step exactly like the contiguous cache threads through ``lax.scan``
    in ``GPT.generate``.
    """

    k: "object"            # jax array (hot prefix)
    v: "object"
    block_size: int
    cold_k: "object" = None    # jax array (tail), zero-width when all hot
    cold_v: "object" = None

    @classmethod
    def create(cls, cfg, num_blocks: int, block_size: int,
               dtype=None) -> "KVPool":
        import jax.numpy as jnp

        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        kvh = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.dim // cfg.num_heads
        shape = (cfg.num_layers, num_blocks, block_size, kvh * hd)
        cold = (cfg.num_layers, 0, block_size, kvh * hd)
        dt = dtype or cfg.dtype
        return cls(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                   block_size=block_size,
                   cold_k=jnp.zeros(cold, dt), cold_v=jnp.zeros(cold, dt))

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1] + self.cold_k.shape[1]

    @property
    def hot_blocks(self) -> int:
        return self.k.shape[1]

    def ensure_hot(self, h: int) -> None:
        """Resize the hot prefix to exactly ``h`` blocks (ids ``0..h-1``).

        O(pool) concatenates, but only on bucket transitions — the
        engine buckets ``h`` to powers of two of the allocator's
        high-water mark, so steady-state iterations never move a byte.
        Shrinking parks stale-but-finite freed blocks in cold storage;
        they are rewritten by prefill before any unmasked read when
        reallocated (trash block 0 is always hot)."""
        import jax.numpy as jnp

        if not (1 <= h <= self.num_blocks):
            raise ValueError(
                f"hot prefix {h} outside [1, {self.num_blocks}]")
        cur = self.hot_blocks
        if h == cur:
            return
        if h > cur:
            take = h - cur
            self.k = jnp.concatenate([self.k, self.cold_k[:, :take]],
                                     axis=1)
            self.v = jnp.concatenate([self.v, self.cold_v[:, :take]],
                                     axis=1)
            self.cold_k = self.cold_k[:, take:]
            self.cold_v = self.cold_v[:, take:]
        else:
            self.cold_k = jnp.concatenate([self.k[:, h:], self.cold_k],
                                          axis=1)
            self.cold_v = jnp.concatenate([self.v[:, h:], self.cold_v],
                                          axis=1)
            self.k = self.k[:, :h]
            self.v = self.v[:, :h]

    def bytes_per_block(self) -> int:
        """HBM bytes one block pins across both pool arrays."""
        per = self.k.dtype.itemsize
        l, _, bs, w = self.k.shape
        return 2 * l * bs * w * per


def pool_observation(allocator: BlockAllocator, pool: "KVPool") -> dict:
    """One consistent read of the pool's pressure for the KV
    observability gauges (``serve/kv_blocks_in_use`` /
    ``serve/kv_pool_frac`` / ``serve/kv_hot_prefix_blocks`` and the
    ``hbm/kv_pool_bytes`` claim): blocks in use, the fraction of the
    usable pool they pin, the hot-prefix width the jitted steps touch,
    and the HBM bytes the live blocks claim — pure host arithmetic off
    the allocator and the pool shapes, no device sync."""
    used = allocator.used_blocks
    usable = max(allocator.num_blocks - 1, 1)   # block 0 is the trash block
    return {"blocks_in_use": used,
            "pool_frac": used / usable,
            "hot_prefix_blocks": pool.hot_blocks,
            "bytes_in_use": used * pool.bytes_per_block()}


def dense_table(block_tables: List[Optional[List[int]]],
                blocks_per_slot: int) -> np.ndarray:
    """Host block tables (``None`` = empty slot) -> the dense
    ``(slots, blocks_per_slot)`` int32 array the decode step consumes.
    Unassigned entries are ``-1`` (the gather clamps them to the trash
    block; the visibility mask makes the gathered value irrelevant)."""
    out = np.full((len(block_tables), blocks_per_slot), -1, np.int32)
    for i, tbl in enumerate(block_tables):
        if tbl:
            if len(tbl) > blocks_per_slot:
                raise ValueError(
                    f"slot {i} holds {len(tbl)} blocks > window "
                    f"{blocks_per_slot}")
            out[i, :len(tbl)] = tbl
    return out


def contiguous_table(num_slots: int, blocks_per_slot: int) -> np.ndarray:
    """The identity block table: slot ``i`` owns blocks
    ``[1 + i·nbs, 1 + (i+1)·nbs)`` of a pool sized
    ``1 + num_slots·blocks_per_slot`` (block 0 stays the trash block).
    This IS the contiguous per-slot cache — same decode code path, no
    indirection benefit — and the baseline the paged parity test
    compares against: paged gather(permuted table) must emit the same
    tokens as gather(identity table)."""
    base = 1 + np.arange(num_slots, dtype=np.int32)[:, None] * blocks_per_slot
    return base + np.arange(blocks_per_slot, dtype=np.int32)[None, :]
