"""Paged (blocked) KV cache: fixed-size HBM blocks + a free-list allocator.

The serving memory problem the contiguous cache cannot solve: a decode
batch's requests have *different* lengths, and a per-slot contiguous
cache must pad every slot to the model window — a 32-slot GPT-2-small
server at max_len 1024 reserves ~0.6 GB of KV rows it mostly never
writes.  Here the cache is ONE shared pool of fixed-size blocks
(``block_size`` token rows each); a request owns only the blocks its
actual prompt+generation needs, recorded in a per-request **block
table** that maps logical position -> physical block.  Finished
requests return their blocks to the free list, so short and long
streams share the same HBM pool (the vLLM paged-attention memory
model, applied to this repo's decode path).

Split of responsibilities:

* :class:`BlockAllocator` — pure-Python, deterministic free-list
  (lowest-id-first so identical schedules produce identical physical
  layouts; tests pin this).
* :class:`KVPool` — the device arrays: ``k``/``v`` of shape
  ``(L, num_blocks, block_size, KVH·Dh)`` plus scatter helpers.  Block
  0 is the **trash block**: never allocated, the write target for
  inactive decode slots (a static-shape decode step writes a row for
  every slot; pointing dead slots at block 0 keeps their garbage out
  of live blocks, and gathered trash rows are masked before softmax).
* Per-request block tables live host-side in the scheduler; the decode
  step receives them as a dense ``(slots, blocks_per_slot)`` int32
  array where ``-1`` means "no block" (gathers clamp to the trash
  block; masking makes the value irrelevant).

CPU-sim honesty note: the decode step *gathers* each slot's blocks
into logical order before attention (``pool[table]``), which
materializes a transient contiguous view — correct everywhere, and
exactly what the parity test leans on (the gather of a permuted table
is bit-identical to the contiguous layout).  On real TPU hardware the
gather would instead be a block-indexed DMA inside a paged decode
kernel (a future ops/ kernel); the *pool residency* — the HBM claim —
is what paging buys at either maturity level.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

#: Physical block id reserved as the write sink for inactive slots /
#: unassigned table entries.  Never handed out by the allocator.
TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Allocation failed — the admission path treats this as "stay
    queued", never as a crash."""


class BlockAllocator:
    """Deterministic free-list over physical block ids ``1..num_blocks-1``
    (block 0 is the trash block).

    Lowest-id-first allocation: the same admission schedule always
    produces the same physical layout, which the scheduler-determinism
    tests pin (and which makes paged-vs-contiguous parity failures
    reproducible instead of heisenbugs).
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block {TRASH_BLOCK} is the reserved "
                f"trash block), got {num_blocks}")
        self.num_blocks = num_blocks
        # sorted free list; pop from the front = lowest id first
        self._free: List[int] = list(range(1, num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    #: alias used by the leak assertions: the number of free blocks must
    #: return to its initial value after any churn of allocate/free —
    #: including client disconnects and mid-prefill cancels (tested).
    free_count = free_blocks

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise PoolExhausted(
                f"asked for {n} KV blocks, {len(self._free)} free "
                f"(pool {self.num_blocks - 1} usable)")
        out, self._free = self._free[:n], self._free[n:]
        return out

    def free(self, blocks: List[int]) -> None:
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"double free within one release: {blocks}")
        for b in blocks:
            if not (0 < b < self.num_blocks):
                raise ValueError(f"freeing block {b} outside the pool")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
        # keep the free list sorted so allocation order stays canonical
        self._free = sorted(self._free + list(blocks))


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` KV rows (ceil division)."""
    return -(-max(tokens, 0) // block_size)


@dataclasses.dataclass
class KVPool:
    """The device-resident block pool for one model.

    ``k``/``v``: ``(num_layers, num_blocks, block_size, KVH·Dh)`` in the
    model dtype.  Functional updates (jax arrays are immutable): the
    scatter helpers return NEW pool arrays; the engine threads them
    through its jitted step exactly like the contiguous cache threads
    through ``lax.scan`` in ``GPT.generate``.
    """

    k: "object"            # jax array
    v: "object"
    block_size: int

    @classmethod
    def create(cls, cfg, num_blocks: int, block_size: int,
               dtype=None) -> "KVPool":
        import jax.numpy as jnp

        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        kvh = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.dim // cfg.num_heads
        shape = (cfg.num_layers, num_blocks, block_size, kvh * hd)
        dt = dtype or cfg.dtype
        return cls(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                   block_size=block_size)

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    def bytes_per_block(self) -> int:
        """HBM bytes one block pins across both pool arrays."""
        per = self.k.dtype.itemsize
        l, _, bs, w = self.k.shape
        return 2 * l * bs * w * per


def dense_table(block_tables: List[Optional[List[int]]],
                blocks_per_slot: int) -> np.ndarray:
    """Host block tables (``None`` = empty slot) -> the dense
    ``(slots, blocks_per_slot)`` int32 array the decode step consumes.
    Unassigned entries are ``-1`` (the gather clamps them to the trash
    block; the visibility mask makes the gathered value irrelevant)."""
    out = np.full((len(block_tables), blocks_per_slot), -1, np.int32)
    for i, tbl in enumerate(block_tables):
        if tbl:
            if len(tbl) > blocks_per_slot:
                raise ValueError(
                    f"slot {i} holds {len(tbl)} blocks > window "
                    f"{blocks_per_slot}")
            out[i, :len(tbl)] = tbl
    return out


def contiguous_table(num_slots: int, blocks_per_slot: int) -> np.ndarray:
    """The identity block table: slot ``i`` owns blocks
    ``[1 + i·nbs, 1 + (i+1)·nbs)`` of a pool sized
    ``1 + num_slots·blocks_per_slot`` (block 0 stays the trash block).
    This IS the contiguous per-slot cache — same decode code path, no
    indirection benefit — and the baseline the paged parity test
    compares against: paged gather(permuted table) must emit the same
    tokens as gather(identity table)."""
    base = 1 + np.arange(num_slots, dtype=np.int32)[:, None] * blocks_per_slot
    return base + np.arange(blocks_per_slot, dtype=np.int32)[None, :]
