"""Jitted paged prefill / decode steps for the serving engine.

Mirrors the op-per-op decode math of ``GPTBlock.decode_step`` /
``GPT._decode_logits`` EXACTLY (same einsum contractions, same fp32
softmax statistics, same cache-dtype discipline) with two serving
generalizations the training-side entry points don't have:

* **per-slot positions** — a continuous batch's requests sit at
  different sequence lengths, so ``pos`` is a ``(slots,)`` vector and
  the attention visibility mask is per-slot (``arange(T) <= pos[b]``),
  where the contiguous path's is a scalar broadcast;
* **block-table indirection** — the KV cache rows come from the shared
  block pool (serve/paged_kv.py): each layer gathers ``pool[table]``
  into logical order, folds the current token's k/v in at its slot
  position, and the new rows are scattered back to
  ``(table[b, pos//bs], pos % bs)`` after the layer stack.

Because the gathered view of an identity block table is bit-identical
to a contiguous per-slot cache, "paged decode == contiguous decode" is
a pure statement about this indirection — the parity tests pin it
token-for-token (greedy and sampled, single-device and TP mesh).

Functions are built once per (model, static shape) and cached, so every
engine over the same model/geometry shares one compiled step.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

NEG_BIG = -1e30


def _donate_pools():
    """Donate the pool buffers so the functional update is in-place on
    backends that implement donation; CPU does not (and logs a warning
    per compile), so the sim path keeps plain arguments."""
    return (1, 2) if jax.default_backend() != "cpu" else ()

def _cached(model, tag, statics, build):
    """Per-(model, static geometry) compiled-step cache, stored ON the
    model object so its lifetime is exactly the model's — no global
    registry pinning dead models (and their executables) for the
    process lifetime, no id-recycling hazards."""
    cache: Dict[tuple, object] = model.__dict__.setdefault(
        "_serve_fn_cache", {})
    key = (tag, statics)
    if key not in cache:
        cache[key] = build()
    return cache[key]


def _apply_rope_at(x, pos):
    """RoPE for one decode token per slot: x (B, 1, H, Dh), pos (B,).
    Same split-half convention as nn.rope.apply_rope (which it calls
    with per-slot positions)."""
    from dtf_tpu.nn.rope import apply_rope
    return apply_rope(x, pos[:, None])


def _sample_keys(seeds, counts):
    """Per-slot sampling keys: fold the request seed and its token
    counter so a request's rng stream is independent of batch
    composition — the same request draws the same tokens whether it
    rode a continuous batch or a static one (tested)."""
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.key(s), c))(seeds, counts)


def _block_decode_paged(block, lp, x_t, pk, pv, table, pos, visible_bias):
    """One decoder block, one token per slot, against gathered pool
    blocks.  The attention body is a line-for-line mirror of
    ``GPTBlock.decode_step`` (grouped cache, fp32 softmax stats, cache
    dtype end-to-end); only the cache materialization differs."""
    cfg = block.cfg
    p = lp["attn"]
    b = x_t.shape[0]
    h = block.ln1.apply(lp["ln1"], x_t)
    q, k_t, v_t = block.attn.qkv(p, h)          # (B,1,H,Dh) / (B,1,KVH,Dh)
    if cfg.rope:
        q = _apply_rope_at(q, pos)
        k_t = _apply_rope_at(k_t, pos)

    nbs = table.shape[1]
    bs = pk.shape[1]
    t_cache = nbs * bs
    kvh = k_t.shape[2]
    hd = k_t.shape[3]
    safe = jnp.maximum(table, 0)                # -1 -> trash block
    ck = pk[safe].reshape(b, t_cache, kvh, hd)  # logical-order gather
    cv = pv[safe].reshape(b, t_cache, kvh, hd)
    rows = jnp.arange(b)
    ck = ck.at[rows, pos].set(k_t[:, 0].astype(ck.dtype))
    cv = cv.at[rows, pos].set(v_t[:, 0].astype(cv.dtype))

    h_all = q.shape[2]
    g = h_all // kvh
    qg = q.reshape(b, kvh, g, hd).astype(ck.dtype)
    scale = hd ** -0.5
    s = jnp.einsum("bkgd,btkd->bkgt", qg, ck,
                   preferred_element_type=jnp.float32) * scale
    s = s + visible_bias                        # (B, KVH, G, T)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,btkd->bkgd", w.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    out = out.reshape(b, 1, h_all, hd).astype(x_t.dtype)
    x_t = x_t + block.attn.out_proj(p, out)
    y = block._mlp_residual(lp, x_t)
    return y, k_t[:, 0].reshape(b, -1), v_t[:, 0].reshape(b, -1)


def _paged_logits(model, params, pool_k, pool_v, table, tok, pos):
    """tok/pos (B,) -> (logits (B, V), new pools).  The layer walk is the
    same unrolled scan as ``GPT._decode_logits`` (decode is latency-
    bound; unrolling lets XLA overlap weight streaming across layers)."""
    bs = pool_k.shape[2]
    nbs = table.shape[1]
    b = tok.shape[0]
    x = model._embed(params, tok[:, None], pos[:, None])     # (B, 1, D)
    # per-slot visibility, hoisted out of the layer loop like the
    # contiguous path's visible_bias
    t_cache = nbs * bs
    visible_bias = jnp.where(
        jnp.arange(t_cache)[None, None, None, :]
        <= pos[:, None, None, None], 0.0, NEG_BIG)

    def layer_scan(carry_x, inputs):
        lp, pk, pv = inputs
        y, k_row, v_row = _block_decode_paged(
            model.block, lp, carry_x, pk, pv, table, pos, visible_bias)
        return y, (k_row, v_row)

    x, (k_new, v_new) = lax.scan(
        layer_scan, x, (params["layers"], pool_k, pool_v), unroll=True)
    x = model.ln_f.apply(params["ln_f"], x)
    logits = model.tok.attend(params["tok"], x)[:, 0, :]

    # scatter the new rows: physical (block, offset) per slot; dead
    # slots' table entries are -1 -> trash block 0 (paged_kv.TRASH_BLOCK)
    blk = jnp.take_along_axis(table, (pos // bs)[:, None], axis=1)[:, 0]
    blk = jnp.maximum(blk, 0)
    off = pos % bs
    pool_k = pool_k.at[:, blk, off].set(k_new.astype(pool_k.dtype))
    pool_v = pool_v.at[:, blk, off].set(v_new.astype(pool_v.dtype))
    return logits, pool_k, pool_v


def build_decode_fn(model, *, num_slots: int, blocks_per_slot: int,
                    block_size: int, top_k: int = 0, top_p: float = 1.0):
    """The engine's one compiled decode iteration.

    ``fn(params, pool_k, pool_v, table (B,nbs) i32, tok (B,) i32,
    pos (B,) i32, temps (B,) f32, seeds (B,) u32, counts (B,) i32)
    -> (next_tok (B,) i32, ok (B,) bool, pool_k, pool_v)``

    ``ok[b]`` is the per-slot health flag: False when slot b's logits
    went non-finite — corrupted KV rows (the ``kv_poison`` chaos kind
    models HBM bit-rot), a NaN'd weight, any numeric breakage.  The
    engine evicts ONLY that slot's request and keeps serving the rest;
    without the flag a poisoned slot silently streams garbage tokens
    (sampling over NaN logits still returns an index).  Dead slots
    gather the zeroed trash block, so their logits stay finite and the
    flag never false-positives on them.

    Static shape per (slots, window): ONE compile covers every batch
    composition — that is what makes continuous batching free of
    recompiles.  Pools are donated (the update is in-place where the
    backend allows).
    """
    from dtf_tpu.nn.sampling import sample_token_batched

    statics = (num_slots, blocks_per_slot, block_size, top_k, float(top_p))

    def build():
        def step(params, pool_k, pool_v, table, tok, pos, temps, seeds,
                 counts):
            logits, pool_k, pool_v = _paged_logits(
                model, params, pool_k, pool_v, table, tok, pos)
            ok = jnp.all(jnp.isfinite(logits), axis=-1)
            keys = _sample_keys(seeds, counts)
            nxt = sample_token_batched(keys, logits, temperature=temps,
                                       top_k=top_k, top_p=top_p)
            return nxt, ok, pool_k, pool_v

        return jax.jit(step, donate_argnums=_donate_pools())

    return _cached(model, "decode", statics, build)


def build_prefill_fn(model, *, padded_len: int, num_blocks_req: int,
                     top_k: int = 0, top_p: float = 1.0):
    """One request's prefill: the whole prompt in ONE batched forward
    (MXU matmuls, not P sequential decode steps), k/v scattered into the
    request's pool blocks, first token sampled from the last-prompt
    logits.

    ``fn(params, pool_k, pool_v, prompt (1, P_pad) i32, p_len () i32,
    blocks (nb,) i32, temp (1,) f32, seed (1,) u32)
    -> (first_tok () i32, pool_k, pool_v)``

    Compiled per padded prompt length (= per block count — prompts pad
    to whole blocks), so a serving process warms one executable per
    length bucket.
    """
    from dtf_tpu.nn.sampling import sample_token_batched

    statics = (padded_len, num_blocks_req, top_k, float(top_p))

    def build():
        def prefill(params, pool_k, pool_v, prompt, p_len, blocks, temp,
                    seed):
            x = model._embed(params, prompt, jnp.arange(padded_len))

            def prefill_layer(cx, lp):
                y, k, v = model.block.prefill(lp, cx)
                return y, (k, v)

            x, (ks, vs) = lax.scan(prefill_layer, x, params["layers"])
            # logits at the LAST REAL prompt position (padding rows are
            # causal-invisible to it)
            x_last = lax.dynamic_slice_in_dim(x, p_len - 1, 1, axis=1)
            x_last = model.ln_f.apply(params["ln_f"], x_last)
            logits = model.tok.attend(params["tok"], x_last)[:, 0, :]

            # (L, 1, P_pad, KVH, Dh) -> (L, nb, bs, KVH*Dh) -> pool blocks
            l = ks.shape[0]
            bs = pool_k.shape[2]
            chunk = lambda a: a.reshape(l, num_blocks_req, bs, -1)
            pool_k = pool_k.at[:, blocks].set(
                chunk(ks).astype(pool_k.dtype))
            pool_v = pool_v.at[:, blocks].set(
                chunk(vs).astype(pool_v.dtype))

            keys = _sample_keys(seed, jnp.zeros((1,), jnp.int32))
            first = sample_token_batched(keys, logits, temperature=temp,
                                         top_k=top_k, top_p=top_p)
            return first[0], pool_k, pool_v

        return jax.jit(prefill, donate_argnums=_donate_pools())

    return _cached(model, "prefill", statics, build)
