"""Jitted paged prefill / decode steps for the serving engine.

Mirrors the op-per-op decode math of ``GPTBlock.decode_step`` /
``GPT._decode_logits`` EXACTLY (same einsum contractions, same fp32
softmax statistics, same cache-dtype discipline) with two serving
generalizations the training-side entry points don't have:

* **per-slot positions** — a continuous batch's requests sit at
  different sequence lengths, so ``pos`` is a ``(slots,)`` vector and
  the attention visibility mask is per-slot (``arange(T) <= pos[b]``),
  where the contiguous path's is a scalar broadcast;
* **block-table indirection** — the KV cache rows come from the shared
  block pool (serve/paged_kv.py): each layer gathers ``pool[table]``
  into logical order, folds the current token's k/v in at its slot
  position, and the new rows are scattered back to
  ``(table[b, pos//bs], pos % bs)`` after the layer stack.

Because the gathered view of an identity block table is bit-identical
to a contiguous per-slot cache, "paged decode == contiguous decode" is
a pure statement about this indirection — the parity tests pin it
token-for-token (greedy and sampled, single-device and TP mesh).

Functions are built once per (model, static shape) and cached, so every
engine over the same model/geometry shares one compiled step.

The FAST data path (ISSUE 14) adds three step shapes on top:

* **narrowed decode** — the engine passes a table sliced to the live
  context's block extent (``blocks_per_slot`` here is the TABLE WIDTH,
  not the admission window) and a pool whose hot prefix covers only the
  allocator's high-water mark, so the per-step gather/scatter cost
  scales with context actually used, not pool size;
* **batched prefill** (:func:`build_prefill_batched_fn`) — R same-bucket
  admissions run as ONE forward with per-row lengths, one compile per
  (rows, prompt bucket) geometry;
* **multi-token verify** (:func:`build_verify_fn`) — the speculative
  decoder's target step: S = k+1 tokens per slot through the paged
  cache in one pass (batched-prefill math at decode time), emitting the
  model's own next-token choice at every window position so the host
  can accept the longest matching draft prefix.

On TPU builds the per-layer gather is a block-indexed DMA inside
``ops.decode_kernel.paged_attention`` (lane-segment attention, online
softmax across block grid steps); the XLA gather stays the CPU-sim
path and the parity oracle.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.telemetry import costobs

NEG_BIG = -1e30


def _donate_pools():
    """Donate the pool buffers so the functional update is in-place on
    backends that implement donation; CPU does not (and logs a warning
    per compile), so the sim path keeps plain arguments."""
    return (1, 2) if jax.default_backend() != "cpu" else ()

def _cached(model, tag, statics, build):
    """Per-(model, static geometry) compiled-step cache, stored ON the
    model object so its lifetime is exactly the model's — no global
    registry pinning dead models (and their executables) for the
    process lifetime, no id-recycling hazards.

    Every entry is wrapped in the cost observatory's AOT-capturing
    shim (telemetry/costobs.py): the first call per input signature
    pays ``lower().compile()`` — exactly the compile jit would have
    paid — and its ``cost_analysis()``/``memory_analysis()`` lands as a
    CostCard keyed by the SAME (tag, statics) geometry this cache keys
    executables by.  One card per compiled geometry, captured at
    compile time, zero hot-path cost."""
    cache: Dict[tuple, object] = model.__dict__.setdefault(
        "_serve_fn_cache", {})
    key = (tag, statics)
    if key not in cache:
        cache[key] = costobs.instrument(build(), f"serve/{tag}", statics)
    return cache[key]


def _apply_rope_at(x, pos):
    """RoPE for one decode token per slot: x (B, 1, H, Dh), pos (B,).
    Same split-half convention as nn.rope.apply_rope (which it calls
    with per-slot positions)."""
    from dtf_tpu.nn.rope import apply_rope
    return apply_rope(x, pos[:, None])


def _sample_keys(seeds, counts):
    """Per-slot sampling keys: fold the request seed and its token
    counter so a request's rng stream is independent of batch
    composition — the same request draws the same tokens whether it
    rode a continuous batch or a static one (tested)."""
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.key(s), c))(seeds, counts)


def _block_decode_paged(block, lp, x_t, pk, pv, table, pos, visible_bias,
                        kernel=False):
    """One decoder block, one token per slot, against gathered pool
    blocks.  The attention body is a line-for-line mirror of
    ``GPTBlock.decode_step`` (grouped cache, fp32 softmax stats, cache
    dtype end-to-end); only the cache materialization differs.

    ``kernel=True`` swaps the XLA gather+softmax for the block-indexed
    Pallas paged-attention kernel (ops/decode_kernel.py): the same math
    with the gather as a per-block DMA — the TPU-build path, run in
    interpret mode by the CPU parity tests."""
    cfg = block.cfg
    p = lp["attn"]
    b = x_t.shape[0]
    h = block.ln1.apply(lp["ln1"], x_t)
    q, k_t, v_t = block.attn.qkv(p, h)          # (B,1,H,Dh) / (B,1,KVH,Dh)
    if cfg.rope:
        q = _apply_rope_at(q, pos)
        k_t = _apply_rope_at(k_t, pos)

    nbs = table.shape[1]
    bs = pk.shape[1]
    t_cache = nbs * bs
    kvh = k_t.shape[2]
    hd = k_t.shape[3]
    h_all = q.shape[2]
    safe = jnp.maximum(table, 0)                # -1 -> trash block
    if kernel:
        from dtf_tpu.ops.decode_kernel import paged_attention
        out = paged_attention(
            q.reshape(b, h_all * hd), k_t.reshape(b, kvh * hd),
            v_t.reshape(b, kvh * hd), pk, pv, safe, pos,
            num_heads=h_all, kv_heads=kvh)
        out = out.reshape(b, 1, h_all, hd).astype(x_t.dtype)
        x_t = x_t + block.attn.out_proj(p, out)
        y = block._mlp_residual(lp, x_t)
        return y, k_t[:, 0].reshape(b, -1), v_t[:, 0].reshape(b, -1)
    ck = pk[safe].reshape(b, t_cache, kvh, hd)  # logical-order gather
    cv = pv[safe].reshape(b, t_cache, kvh, hd)
    rows = jnp.arange(b)
    ck = ck.at[rows, pos].set(k_t[:, 0].astype(ck.dtype))
    cv = cv.at[rows, pos].set(v_t[:, 0].astype(cv.dtype))

    g = h_all // kvh
    qg = q.reshape(b, kvh, g, hd).astype(ck.dtype)
    scale = hd ** -0.5
    s = jnp.einsum("bkgd,btkd->bkgt", qg, ck,
                   preferred_element_type=jnp.float32) * scale
    s = s + visible_bias                        # (B, KVH, G, T)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,btkd->bkgd", w.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    out = out.reshape(b, 1, h_all, hd).astype(x_t.dtype)
    x_t = x_t + block.attn.out_proj(p, out)
    y = block._mlp_residual(lp, x_t)
    return y, k_t[:, 0].reshape(b, -1), v_t[:, 0].reshape(b, -1)


def _paged_logits(model, params, pool_k, pool_v, table, tok, pos,
                  kernel=False):
    """tok/pos (B,) -> (logits (B, V), new pools).  The layer walk is the
    same unrolled scan as ``GPT._decode_logits`` (decode is latency-
    bound; unrolling lets XLA overlap weight streaming across layers)."""
    bs = pool_k.shape[2]
    nbs = table.shape[1]
    b = tok.shape[0]
    x = model._embed(params, tok[:, None], pos[:, None])     # (B, 1, D)
    # per-slot visibility, hoisted out of the layer loop like the
    # contiguous path's visible_bias
    t_cache = nbs * bs
    visible_bias = jnp.where(
        jnp.arange(t_cache)[None, None, None, :]
        <= pos[:, None, None, None], 0.0, NEG_BIG)

    def layer_scan(carry_x, inputs):
        lp, pk, pv = inputs
        y, k_row, v_row = _block_decode_paged(
            model.block, lp, carry_x, pk, pv, table, pos, visible_bias,
            kernel=kernel)
        return y, (k_row, v_row)

    x, (k_new, v_new) = lax.scan(
        layer_scan, x, (params["layers"], pool_k, pool_v), unroll=True)
    x = model.ln_f.apply(params["ln_f"], x)
    logits = model.tok.attend(params["tok"], x)[:, 0, :]

    # scatter the new rows: physical (block, offset) per slot; dead
    # slots' table entries are -1 -> trash block 0 (paged_kv.TRASH_BLOCK)
    blk = jnp.take_along_axis(table, (pos // bs)[:, None], axis=1)[:, 0]
    blk = jnp.maximum(blk, 0)
    off = pos % bs
    pool_k = pool_k.at[:, blk, off].set(k_new.astype(pool_k.dtype))
    pool_v = pool_v.at[:, blk, off].set(v_new.astype(pool_v.dtype))
    return logits, pool_k, pool_v


def build_decode_fn(model, *, num_slots: int, blocks_per_slot: int,
                    block_size: int, top_k: int = 0, top_p: float = 1.0,
                    kernel: bool = False):
    """The engine's one compiled decode iteration.

    ``fn(params, pool_k, pool_v, table (B,nbs) i32, tok (B,) i32,
    pos (B,) i32, temps (B,) f32, seeds (B,) u32, counts (B,) i32)
    -> (next_tok (B,) i32, ok (B,) bool, pool_k, pool_v)``

    ``ok[b]`` is the per-slot health flag: False when slot b's logits
    went non-finite — corrupted KV rows (the ``kv_poison`` chaos kind
    models HBM bit-rot), a NaN'd weight, any numeric breakage.  The
    engine evicts ONLY that slot's request and keeps serving the rest;
    without the flag a poisoned slot silently streams garbage tokens
    (sampling over NaN logits still returns an index).  Dead slots
    gather the zeroed trash block, so their logits stay finite and the
    flag never false-positives on them.

    Static shape per (slots, window): ONE compile covers every batch
    composition — that is what makes continuous batching free of
    recompiles.  ``blocks_per_slot`` is the TABLE WIDTH of this step —
    the narrowed engine passes the live-context bucket here, the
    baseline passes the full admission window.  ``kernel=True`` routes
    attention through the Pallas paged-attention kernel.  Pools are
    donated (the update is in-place where the backend allows).
    """
    from dtf_tpu.nn.sampling import sample_token_batched

    statics = (num_slots, blocks_per_slot, block_size, top_k, float(top_p),
               bool(kernel))

    def build():
        def step(params, pool_k, pool_v, table, tok, pos, temps, seeds,
                 counts):
            logits, pool_k, pool_v = _paged_logits(
                model, params, pool_k, pool_v, table, tok, pos,
                kernel=kernel)
            ok = jnp.all(jnp.isfinite(logits), axis=-1)
            keys = _sample_keys(seeds, counts)
            nxt = sample_token_batched(keys, logits, temperature=temps,
                                       top_k=top_k, top_p=top_p)
            return nxt, ok, pool_k, pool_v

        return jax.jit(step, donate_argnums=_donate_pools())

    return _cached(model, "decode", statics, build)


def build_prefill_fn(model, *, padded_len: int, num_blocks_req: int,
                     top_k: int = 0, top_p: float = 1.0):
    """One request's prefill: the whole prompt in ONE batched forward
    (MXU matmuls, not P sequential decode steps), k/v scattered into the
    request's pool blocks, first token sampled from the last-prompt
    logits.

    ``fn(params, pool_k, pool_v, prompt (1, P_pad) i32, p_len () i32,
    blocks (nb,) i32, temp (1,) f32, seed (1,) u32)
    -> (first_tok () i32, pool_k, pool_v)``

    Compiled per padded prompt length (= per block count — prompts pad
    to whole blocks), so a serving process warms one executable per
    length bucket.
    """
    from dtf_tpu.nn.sampling import sample_token_batched

    statics = (padded_len, num_blocks_req, top_k, float(top_p))

    def build():
        def prefill(params, pool_k, pool_v, prompt, p_len, blocks, temp,
                    seed):
            x = model._embed(params, prompt, jnp.arange(padded_len))

            def prefill_layer(cx, lp):
                y, k, v = model.block.prefill(lp, cx)
                return y, (k, v)

            x, (ks, vs) = lax.scan(prefill_layer, x, params["layers"])
            # logits at the LAST REAL prompt position (padding rows are
            # causal-invisible to it)
            x_last = lax.dynamic_slice_in_dim(x, p_len - 1, 1, axis=1)
            x_last = model.ln_f.apply(params["ln_f"], x_last)
            logits = model.tok.attend(params["tok"], x_last)[:, 0, :]

            # (L, 1, P_pad, KVH, Dh) -> (L, nb, bs, KVH*Dh) -> pool blocks
            l = ks.shape[0]
            bs = pool_k.shape[2]
            chunk = lambda a: a.reshape(l, num_blocks_req, bs, -1)
            pool_k = pool_k.at[:, blocks].set(
                chunk(ks).astype(pool_k.dtype))
            pool_v = pool_v.at[:, blocks].set(
                chunk(vs).astype(pool_v.dtype))

            keys = _sample_keys(seed, jnp.zeros((1,), jnp.int32))
            first = sample_token_batched(keys, logits, temperature=temp,
                                         top_k=top_k, top_p=top_p)
            return first[0], pool_k, pool_v

        return jax.jit(prefill, donate_argnums=_donate_pools())

    return _cached(model, "prefill", statics, build)


def build_prefill_batched_fn(model, *, padded_len: int,
                             num_blocks_req: int, n_rows: int,
                             top_k: int = 0, top_p: float = 1.0):
    """R same-bucket prefills as ONE batched forward — the multi-request
    generalization of :func:`build_prefill_fn` (whose per-row math it
    mirrors exactly: rows are independent through the whole network, so
    a request's first token is bitwise the same whether it prefilled
    solo or coalesced — pinned by tests).

    ``fn(params, pool_k, pool_v, prompts (R, P_pad) i32, p_lens (R,)
    i32, blocks (R, nb) i32, temps (R,) f32, seeds (R,) u32)
    -> (first_toks (R,) i32, pool_k, pool_v)``

    Compiled per (rows bucket, padded prompt length).  Padding rows
    (the engine rounds R up to a power of two) carry ``blocks`` rows of
    all-zeros — their k/v lands in the trash block and their sampled
    token is discarded.
    """
    from dtf_tpu.nn.sampling import sample_token_batched

    statics = (padded_len, num_blocks_req, n_rows, top_k, float(top_p))

    def build():
        def prefill(params, pool_k, pool_v, prompts, p_lens, blocks,
                    temps, seeds):
            x = model._embed(params, prompts, jnp.arange(padded_len))

            def prefill_layer(cx, lp):
                y, k, v = model.block.prefill(lp, cx)
                return y, (k, v)

            x, (ks, vs) = lax.scan(prefill_layer, x, params["layers"])
            # per-row logits at the LAST REAL prompt position
            x_last = jnp.take_along_axis(
                x, (p_lens - 1)[:, None, None], axis=1)
            x_last = model.ln_f.apply(params["ln_f"], x_last)
            logits = model.tok.attend(params["tok"], x_last)[:, 0, :]

            # (L, R, P_pad, KVH, Dh) -> (L, R, nb, bs, KVH*Dh) -> blocks
            l = ks.shape[0]
            bs = pool_k.shape[2]
            chunk = lambda a: a.reshape(l, n_rows, num_blocks_req, bs, -1)
            pool_k = pool_k.at[:, blocks].set(
                chunk(ks).astype(pool_k.dtype))
            pool_v = pool_v.at[:, blocks].set(
                chunk(vs).astype(pool_v.dtype))

            keys = _sample_keys(seeds, jnp.zeros((n_rows,), jnp.int32))
            first = sample_token_batched(keys, logits, temperature=temps,
                                         top_k=top_k, top_p=top_p)
            return first, pool_k, pool_v

        return jax.jit(prefill, donate_argnums=_donate_pools())

    return _cached(model, "prefill_batched", statics, build)


def build_prefill_suffix_fn(model, *, padded_len: int, start_len: int,
                            n_rows: int, top_k: int = 0,
                            top_p: float = 1.0):
    """Suffix-only prefill for prefix-cache hits: the request's first
    ``start_len`` rows (whole blocks) are already resident in the pool
    — matched by content through the sharing index — so only the
    ``padded_len - start_len`` suffix rows go through the forward.
    RoPE/positional rows and the causal mask are offset by the cached
    length (queries sit at global positions ``start_len..padded_len-1``
    against a key axis that is the gathered prefix followed by the
    fresh suffix).

    Bitwise discipline: the layer body is the same ``lax.scan`` over
    ``GPTBlock.prefill``'s op sequence (dense ``dot_product_attention``
    over ``expand_kv``'d heads, the mask a row-slice of the full causal
    mask) that :func:`build_prefill_fn` compiles — the per-row numerics
    of the suffix rows, the scattered suffix K/V, and the sampled first
    token are bitwise identical to the cold prefill's (pinned by
    tests), which is what makes cache-on vs cache-off token identity a
    structural property instead of a tolerance.

    ``fn(params, pool_k, pool_v, toks (R, S) i32 [suffix tokens],
    p_lens (R,) i32 [GLOBAL prompt lengths], pre_blocks (R, nb_pre)
    i32, sfx_blocks (R, nb_sfx) i32, temps (R,) f32, seeds (R,) u32)
    -> (first_toks (R,) i32, ok (R,) bool, pool_k, pool_v)``

    ``ok[r]`` is the per-row health flag the cold prefill doesn't need:
    a cold prefill reads nothing from the pool, but a suffix prefill
    GATHERS shared blocks — if ``kv_poison`` corrupted one between
    match and prefill, the logits go non-finite and the engine must
    evict instead of emitting a NaN-derived first token.  Padding rows
    (R rounded up to a power of two) carry all-zero block rows — their
    gathers hit the trash block, their k/v lands there too, and their
    sampled token is discarded.

    Compiled per (padded prompt bucket, cached-prefix length, rows
    bucket); ``start_len`` must be a positive whole-block multiple
    strictly below ``padded_len`` (the last real prompt token is never
    cached — its logits are the first token's source).
    """
    from dtf_tpu.nn.attention import causal_mask, dot_product_attention
    from dtf_tpu.nn.sampling import sample_token_batched

    statics = (padded_len, start_len, n_rows, top_k, float(top_p))
    cfg = model.cfg
    s_w = padded_len - start_len

    def build():
        def prefill(params, pool_k, pool_v, toks, p_lens, pre_blocks,
                    sfx_blocks, temps, seeds):
            bs = pool_k.shape[2]
            pos = jnp.arange(start_len, padded_len)
            x = model._embed(params, toks, pos)              # (R, S, D)
            # queries are rows start_len.. of the SAME causal mask the
            # cold prefill applies over the full padded length
            mask = causal_mask(padded_len)[:, :, start_len:, :]
            safe_pre = jnp.maximum(pre_blocks, 0)

            def prefill_layer(cx, inp):
                lp, pk, pv = inp
                block = model.block
                p = lp["attn"]
                h = block.ln1.apply(lp["ln1"], cx)
                q, k_s, v_s = block.attn.qkv(p, h)
                if cfg.rope:
                    from dtf_tpu.nn.rope import apply_rope
                    q = apply_rope(q, pos)
                    k_s = apply_rope(k_s, pos)
                kvh = k_s.shape[2]
                hd = k_s.shape[3]
                # gathered shared-prefix rows (read-only — the suffix
                # scatter below never touches pre_blocks)
                cpk = pk[safe_pre].reshape(n_rows, start_len, kvh, hd)
                cpv = pv[safe_pre].reshape(n_rows, start_len, kvh, hd)
                k_full = jnp.concatenate([cpk.astype(k_s.dtype), k_s],
                                         axis=1)
                v_full = jnp.concatenate([cpv.astype(v_s.dtype), v_s],
                                         axis=1)
                out = dot_product_attention(
                    q, block.attn.expand_kv(k_full),
                    block.attn.expand_kv(v_full), mask)
                cx = cx + block.attn.out_proj(p, out)
                return block._mlp_residual(lp, cx), (k_s, v_s)

            x, (ks, vs) = lax.scan(prefill_layer, x,
                                   (params["layers"], pool_k, pool_v))
            # per-row logits at the LAST REAL prompt position, which is
            # always a suffix row (matches cap at (prompt_len-1)//bs
            # full blocks)
            x_last = jnp.take_along_axis(
                x, (p_lens - 1 - start_len)[:, None, None], axis=1)
            x_last = model.ln_f.apply(params["ln_f"], x_last)
            logits = model.tok.attend(params["tok"], x_last)[:, 0, :]
            ok = jnp.all(jnp.isfinite(logits), axis=-1)

            # (L, R, S, KVH, Dh) -> (L, R, nb_sfx, bs, KVH*Dh) -> blocks
            l = ks.shape[0]
            nb_sfx = s_w // bs
            chunk = lambda a: a.reshape(l, n_rows, nb_sfx, bs, -1)
            pool_k = pool_k.at[:, sfx_blocks].set(
                chunk(ks).astype(pool_k.dtype))
            pool_v = pool_v.at[:, sfx_blocks].set(
                chunk(vs).astype(pool_v.dtype))

            keys = _sample_keys(seeds, jnp.zeros((n_rows,), jnp.int32))
            first = sample_token_batched(keys, logits, temperature=temps,
                                         top_k=top_k, top_p=top_p)
            return first, ok, pool_k, pool_v

        return jax.jit(prefill, donate_argnums=_donate_pools())

    return _cached(model, "prefill_suffix", statics, build)


def _paged_window_logits(model, params, pool_k, pool_v, table, toks,
                         pos0):
    """S tokens per slot against the paged cache in ONE forward pass —
    the speculative verify core.  ``toks`` (B, S): the last emitted
    token followed by the drafts; ``pos0`` (B,): its sequence position.
    Returns (logits (B, S, V), new pools' k/v rows (L, B, S, KVH·Dh)).

    The attention math is the window generalization of
    :func:`_paged_logits`'s per-block mirror: the window's own k/v rows
    fold into the gathered view at positions ``pos0 + s`` and the
    visibility mask is per (query position, cache row) — query ``s``
    sees rows ``<= pos0 + s``, so rejected-draft rows left stale in the
    pool by an earlier verify step sit strictly above every later
    query's horizon until overwritten."""
    cfg = model.cfg
    bs = pool_k.shape[2]
    nbs = table.shape[1]
    b, s_w = toks.shape
    t_cache = nbs * bs
    posw = pos0[:, None] + jnp.arange(s_w)[None, :]          # (B, S)
    # clamped only for OOB-safe embedding of invalid (past-n_in) rows;
    # valid rows always sit inside the admission window
    pos_emb = jnp.minimum(posw, cfg.max_len - 1)
    x = model._embed(params, toks, pos_emb)                  # (B, S, D)
    visible_bias = jnp.where(
        jnp.arange(t_cache)[None, None, None, None, :]
        <= posw[:, None, None, :, None], 0.0, NEG_BIG)       # (B,1,1,S,T)
    safe = jnp.maximum(table, 0)
    rows = jnp.arange(b)[:, None]

    def layer_scan(carry_x, inputs):
        lp, pk, pv = inputs
        block = model.block
        p = lp["attn"]
        h = block.ln1.apply(lp["ln1"], carry_x)
        q, k_t, v_t = block.attn.qkv(p, h)     # (B,S,H,Dh)/(B,S,KVH,Dh)
        if cfg.rope:
            from dtf_tpu.nn.rope import apply_rope
            q = apply_rope(q, pos_emb)
            k_t = apply_rope(k_t, pos_emb)
        kvh = k_t.shape[2]
        hd = k_t.shape[3]
        ck = pk[safe].reshape(b, t_cache, kvh, hd)
        cv = pv[safe].reshape(b, t_cache, kvh, hd)
        # fold the whole window in; rows past a slot's n_in are masked
        # out of every valid query by the position horizon above
        ck = ck.at[rows, posw].set(k_t.astype(ck.dtype), mode="drop")
        cv = cv.at[rows, posw].set(v_t.astype(cv.dtype), mode="drop")
        h_all = q.shape[2]
        g = h_all // kvh
        qg = q.reshape(b, s_w, kvh, g, hd).astype(ck.dtype)
        scale = hd ** -0.5
        s = jnp.einsum("bskgd,btkd->bkgst", qg, ck,
                       preferred_element_type=jnp.float32) * scale
        s = s + visible_bias                   # (B, KVH, G, S, T)
        w = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bkgst,btkd->bskgd", w.astype(cv.dtype), cv,
                         preferred_element_type=jnp.float32)
        out = out.reshape(b, s_w, h_all, hd).astype(carry_x.dtype)
        y = carry_x + block.attn.out_proj(p, out)
        y = block._mlp_residual(lp, y)
        return y, (k_t.reshape(b, s_w, -1), v_t.reshape(b, s_w, -1))

    x, (k_new, v_new) = lax.scan(
        layer_scan, x, (params["layers"], pool_k, pool_v), unroll=True)
    x = model.ln_f.apply(params["ln_f"], x)
    logits = model.tok.attend(params["tok"], x)              # (B, S, V)
    return logits, k_new, v_new


def build_verify_fn(model, *, num_slots: int, blocks_per_slot: int,
                    block_size: int, width: int, top_k: int = 0,
                    top_p: float = 1.0):
    """The speculative decoder's target step: S = ``width`` tokens per
    slot (current token + k drafts) verified in one paged pass.

    ``fn(params, pool_k, pool_v, table (B,nb) i32, toks (B,S) i32,
    pos0 (B,) i32, n_in (B,) i32, temps (B,) f32, seeds (B,) u32,
    counts (B,) i32) -> (out_toks (B,S) i32, ok (B,) bool, pool_k,
    pool_v)``

    ``out_toks[b, s]`` is the model's OWN next-token choice after
    window position ``s`` — greedy argmax or the request's (seed, rid,
    count+s)-keyed draw, exactly the token the sequential decode step
    would emit given the same prefix.  The host accepts drafts while
    ``toks[b, s+1] == out_toks[b, s]`` and emits the bonus token at the
    first mismatch, so the emitted stream is bitwise the sequential
    one.  K/V rows are written for positions ``pos0 .. pos0+n_in-1``
    (rows past ``n_in`` scatter to the trash block); rejected-draft
    rows go stale above the next query horizon and are overwritten
    before they can become visible.
    """
    from dtf_tpu.nn.sampling import sample_token_window

    statics = (num_slots, blocks_per_slot, block_size, width, top_k,
               float(top_p))

    def build():
        def verify(params, pool_k, pool_v, table, toks, pos0, n_in,
                   temps, seeds, counts):
            b, s_w = toks.shape
            bs = pool_k.shape[2]
            nbs = table.shape[1]
            logits, k_new, v_new = _paged_window_logits(
                model, params, pool_k, pool_v, table, toks, pos0)
            valid = jnp.arange(s_w)[None, :] < n_in[:, None]  # (B, S)
            ok = jnp.all(jnp.isfinite(logits) | ~valid[:, :, None],
                         axis=(1, 2))
            # per-(row, position) keys: position s draws at stream
            # count counts+s — the count the sequential step would use
            keys = jax.vmap(lambda sd, c: jax.vmap(
                lambda cc: jax.random.fold_in(jax.random.key(sd), cc))(
                    c + jnp.arange(s_w, dtype=jnp.int32)))(seeds, counts)
            out_toks = sample_token_window(
                keys, logits, temperature=temps, top_k=top_k, top_p=top_p)
            # scatter the window's k/v rows: valid rows to their table
            # blocks, the rest to the trash block
            posw = pos0[:, None] + jnp.arange(s_w)[None, :]
            blk_idx = jnp.clip(posw // bs, 0, nbs - 1)
            blk = jnp.take_along_axis(table, blk_idx, axis=1)
            blk = jnp.where(valid, jnp.maximum(blk, 0), 0)
            off = posw % bs
            pool_k = pool_k.at[:, blk, off].set(
                k_new.astype(pool_k.dtype))
            pool_v = pool_v.at[:, blk, off].set(
                v_new.astype(pool_v.dtype))
            return out_toks, ok, pool_k, pool_v

        return jax.jit(verify, donate_argnums=_donate_pools())

    return _cached(model, "verify", statics, build)
