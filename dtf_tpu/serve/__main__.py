"""Serving CLI: run the continuous-batching engine as a process.

    # demo traffic: 32 seeded requests at ~8 QPS through the tiny preset
    python -m dtf_tpu.serve --preset tiny --demo 32 --qps 8 \
        --logdir /tmp/dtf_serve

    # requests from a JSONL file (one {"prompt": [...ids...],
    # "max_new_tokens": N, "temperature": T, "deadline_ms": D,
    # "priority": P} per line), streamed tokens
    python -m dtf_tpu.serve --preset tiny --requests reqs.jsonl --stream

    # the TCP front end: line-oriented JSON over a socket
    # (serve/frontend.py documents the framing)
    python -m dtf_tpu.serve --preset tiny --listen :8100

Resilience spine reuse (DESIGN.md §5, §7.4): ``--max_restarts N`` wraps
the serve session in the bounded-restart supervisor — a crashed or
wedged server restarts and REPLAYS the unfinished requests (completed
results survive the attempt boundary); ``--health_dir`` publishes a
liveness heartbeat per engine iteration through ``resilience.health``'s
file transport.  ``--wedge_at K`` injects a crash at iteration K of the
first attempt — the supervisor-path proof the CI lane drives.

Overload & preemption (PR 10): **SIGTERM drains gracefully** — admissions
freeze, in-flight decodes finish inside ``--drain_timeout_s``, and every
accepted-but-unfinished request is checkpointed to ``<logdir>/
drain.jsonl`` (a ``--requests``-compatible replay file) AND replayed
in-process when the supervisor has restart budget; replay is
token-identical (per-request rng streams are (seed, rid)-keyed).
``--drain_at K`` fires the same drain deterministically at iteration K
(the CI spelling — real signal delivery is timing-racy).  ``--brownout``
arms the hysteretic overload controller against ``--slo_ttft_ms``;
``--deadline_ms`` attaches completion deadlines to demo traffic (the
scheduler sheds hopeless requests before prefill); ``--chaos`` takes the
serving fault kinds (``slow_decode@S:80ms:N``, ``client_drop@S``,
``kv_poison@S``).

Weights are seeded-random (this repo has no trained checkpoints to
ship); the engine, scheduler, cache, and telemetry paths are exactly
the production ones.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np


def build_trace(ns, vocab_size: int,
                max_len: Optional[int] = None) -> List[Tuple[float, dict]]:
    """The request trace: JSONL file or a seeded Poisson demo mix."""
    trace: List[Tuple[float, dict]] = []
    if ns.requests:
        with open(ns.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                trace.append((float(doc.get("arrival_s", 0.0)), {
                    "rid": int(doc.get("rid", i)),
                    "prompt": np.asarray(doc["prompt"], np.int32),
                    "max_new_tokens": int(doc.get("max_new_tokens", 16)),
                    "temperature": float(doc.get("temperature",
                                                 ns.temperature)),
                    "deadline_ms": doc.get("deadline_ms"),
                    "priority": int(doc.get("priority", 0)),
                    # a drain.jsonl replay carries the ORIGINAL trace id
                    # (and an explicit resubmit mark) so the replayed
                    # request links to its pre-SIGTERM timeline
                    # (reqtrace continuity)
                    "trace_id": doc.get("trace_id"),
                    "resubmit": bool(doc.get("resubmit")),
                }))
        trace.sort(key=lambda e: e[0])
        return trace
    if getattr(ns, "prefix_cache", False):
        # shared-prefix chatbot mix: the demo traffic that actually
        # exercises the cache (a pure Poisson mix shares no chunks, so
        # /memz would show an armed-but-idle cache)
        from dtf_tpu.bench.serve_load import shared_prefix_trace
        suffix_lens = [int(x) for x in ns.prompt_lens.split(",")]
        output_lens = [int(x) for x in ns.output_lens.split(",")]
        prefix_len = 5 * ns.block_size
        if max_len is not None:
            # admission rejects prompt+output > max_len, so the demo
            # prefix must leave room for the longest suffix+output mix
            # (block-aligned: only FULL blocks are shareable)
            budget = max_len - max(suffix_lens) - max(output_lens)
            prefix_len = min(prefix_len,
                             (budget // ns.block_size) * ns.block_size)
        if prefix_len < ns.block_size:
            raise SystemExit(
                "--prefix_cache demo: no room for a shareable prefix — "
                f"max_len {max_len} minus worst-case suffix+output "
                f"leaves {prefix_len} < one {ns.block_size}-token block; "
                "lower --prompt_lens/--output_lens or --block_size")
        return shared_prefix_trace(
            seed=ns.seed, n_requests=ns.demo, qps=ns.qps,
            n_prefixes=3, prefix_len=prefix_len,
            suffix_lens=suffix_lens, output_lens=output_lens,
            vocab_size=vocab_size)
    # ONE Poisson trace generator in the repo (the load bench's
    # unit-rate chain, rate-scaling invariant included).
    from dtf_tpu.bench.serve_load import poisson_trace
    return poisson_trace(
        seed=ns.seed, n_requests=ns.demo, qps=ns.qps,
        prompt_lens=[int(x) for x in ns.prompt_lens.split(",")],
        output_lens=[int(x) for x in ns.output_lens.split(",")],
        vocab_size=vocab_size, temperature=ns.temperature,
        deadline_ms=ns.deadline_ms or None,
        priorities=[int(x) for x in ns.priorities.split(",")],
        qps_profile=getattr(ns, "qps_profile", "constant"))


def _write_drain_file(engine, logdir: str,
                      replica_index: Optional[int] = None) -> Optional[str]:
    """Checkpoint a drain's unfinished requests as a --requests-
    compatible JSONL replay file (arrival 0: they are due NOW).  An
    attempt that finished WITHOUT leaving unfinished work removes any
    previous attempt's file instead — after a successful supervisor
    replay, a stale drain.jsonl would tell the operator to re-serve
    requests that already completed.

    Fleet replicas namespace their checkpoint (``drain.r<k>.jsonl``):
    rids are per-engine, so two standalone replicas' drain files can
    collide — the per-replica name keeps the namespaces apart and
    ``serve.fleet.merge_drain_docs`` refuses a colliding merge (an
    acceptor-run fleet never collides: rids are fleet-minted)."""
    if not logdir:
        return None
    name = ("drain.jsonl" if replica_index is None
            else f"drain.r{replica_index}.jsonl")
    path = os.path.join(logdir, name)
    if not engine.drained or not engine.drain_docs:
        if os.path.exists(path):
            os.remove(path)
        return None
    os.makedirs(logdir, exist_ok=True)
    with open(path, "w") as f:
        for doc in engine.drain_docs:
            f.write(json.dumps({**doc, "arrival_s": 0.0},
                               sort_keys=True) + "\n")
    return path


def _make_engine(ns, model, params, clock, printer, heartbeat, chaos):
    from dtf_tpu.serve import BrownoutController, ServingEngine
    from dtf_tpu.telemetry.slo import BurnRateMonitor

    brownout = None
    if ns.brownout:
        brownout = BrownoutController(
            ns.slo_ttft_ms, degrade_max_new=ns.degrade_max_new)
    # SLO burn-rate monitor: always armed (passive — it observes and
    # alerts, never admits or sheds); surfaced on /slo and in summary()
    slo = BurnRateMonitor.for_serving(ns.slo_ttft_ms)
    probe = None
    if ns.admin_port is not None:
        from dtf_tpu.telemetry.live import LivenessProbe
        probe = LivenessProbe()
        inner_hb = heartbeat

        def heartbeat(count, _inner=inner_hb, _probe=probe):
            _probe.beat(count)
            if _inner is not None:
                _inner(count)

    engine = ServingEngine(
        model, params, num_slots=ns.slots, block_size=ns.block_size,
        num_blocks=ns.pool_blocks, mode=ns.mode, top_k=ns.top_k,
        top_p=ns.top_p, eos_id=ns.eos_id, seed=ns.seed, clock=clock,
        max_queue=ns.max_queue, aging_s=ns.aging_s, on_token=printer,
        heartbeat=heartbeat, brownout=brownout, chaos=chaos, slo=slo,
        spec_k=ns.spec_k, coalesce_prefill=not ns.no_prefill_coalesce,
        narrow_decode=not ns.no_narrow,
        prefix_cache=getattr(ns, "prefix_cache", False))
    ctl = None
    if getattr(ns, "controller", False):
        # self-tuning control plane (DESIGN.md §9): registry + standard
        # serving knobs + SLO-driven controller on the engine cadence
        from dtf_tpu.control import arm_controller
        ctl = arm_controller(engine)
    if ns.admin_port is not None:
        # one admin window per process; a supervisor's next attempt
        # rebinds the fresh engine's ring + monitor onto the same server
        from dtf_tpu.telemetry.live import (get_admin, health_file_fn,
                                            start_admin)
        fresh = get_admin() is None
        admin = start_admin(
            ns.admin_port, probe=probe,
            trace_ring=engine.reqtrace.ring, slo=slo,
            health_fn=(health_file_fn(ns.health_dir) if ns.health_dir
                       else None),
            control_fn=(ctl.state if ctl is not None else None),
            logdir=getattr(ns, "logdir", None))
        if fresh:
            print(f"admin endpoint on http://127.0.0.1:{admin.port} "
                  f"(/statz /healthz /tracez /slo /controlz /memz "
                  f"/incidentz; GET / for the full index)",
                  flush=True)
    return engine


def serve_session(ns, model, params, trace,
                  drain_target: Optional[Dict] = None) -> Dict:
    """Run the trace to completion under the supervisor: unfinished
    requests replay on restart (arrival re-stamped to the new attempt's
    clock — an external client would keep its own latency books across
    the gap), completed results survive.  A SIGTERM drain consumes a
    restart (the replay is the supervisor's) when budget exists;
    otherwise the drain file is the hand-off and the exit is clean.

    ``drain_target`` is the SIGTERM mailbox main() installed at process
    start (the handler must exist before the multi-second jax/model
    init, or an early preemption signal just kills the process): the
    session registers each attempt's engine there and honors a signal
    that arrived before any engine existed."""
    from dtf_tpu.resilience.supervisor import run_supervised
    from dtf_tpu.serve import VirtualClock, WallClock

    completed: Dict[int, object] = {}
    #: rid -> trace id seen on any previous attempt: the supervisor's
    #: in-process replay re-submits under the SAME trace id, so the
    #: replayed request's timeline links to its pre-crash/pre-drain
    #: events (reqtrace continuity, mirrored by drain.jsonl for the
    #: cross-process hand-off).
    trace_ids: Dict[int, str] = {}
    #: rids a previous attempt ACCEPTED (anything past the front door:
    #: queued/running at the crash, drained, cancelled, failed).  Only
    #: these replay with resubmit=True — a shed/rejected request's retry
    #: keeps its trace id for continuity but is a fresh submission, not
    #: a replay (Request.resubmit's documented invariant).
    accepted_ids: set = set()
    current: Dict[str, object] = (drain_target if drain_target is not None
                                  else {})
    chaos = None
    if ns.chaos:
        from dtf_tpu.resilience.chaos import FaultPlan
        chaos = FaultPlan.parse(ns.chaos, process_index=0)

    def printer(req, token, done):
        if ns.stream:
            tail = " <end>" if done else ""
            print(f"  [req {req.rid}] +{token}{tail}", flush=True)

    def make_heartbeat():
        if not ns.health_dir:
            return None
        from dtf_tpu.resilience.health import FileHeartbeatTransport
        transport = FileHeartbeatTransport(ns.health_dir, 0)
        return lambda count: transport.beat(count)

    def fit_once(attempt: int):
        clock = (VirtualClock() if ns.clock == "virtual" else WallClock())
        engine = _make_engine(ns, model, params, clock, printer,
                              make_heartbeat(), chaos)
        current["engine"] = engine
        if current.pop("early_sigterm", None):
            # preemption arrived during init: drain immediately — the
            # whole trace becomes the hand-off/replay set
            engine.request_drain()
        if ns.wedge_at is not None and attempt == 0:
            real_step = engine.step

            def wedged_step():
                if engine.iterations == ns.wedge_at:
                    raise RuntimeError(
                        "chaos: serve wedged (injected --wedge_at)")
                return real_step()

            engine.step = wedged_step
        if ns.drain_at is not None and attempt == 0:
            real_step2 = engine.step

            def draining_step():
                if engine.iterations == ns.drain_at:
                    engine.request_drain()
                return real_step2()

            engine.step = draining_step
        pending = []
        for t, kw in trace:
            if kw["rid"] in completed:
                continue
            if attempt:
                # replay: same trace id as the previous attempt; the
                # resubmit mark ONLY when that attempt accepted it
                kw = {**kw,
                      "trace_id": (kw.get("trace_id")
                                   or trace_ids.get(kw["rid"])),
                      "resubmit": kw.get("resubmit", False)
                      or kw["rid"] in accepted_ids}
                t = 0.0
            pending.append((t, kw))
        try:
            engine.run(pending, drain_timeout_s=ns.drain_timeout_s)
        finally:
            completed.update(
                {rid: r for rid, r in engine.results.items()
                 if r.status == "completed"})
            for r in (list(engine.results.values())
                      + list(engine.scheduler.queue)
                      + engine.scheduler.active()):
                if r.trace_id:
                    trace_ids[r.rid] = r.trace_id
                if r.status not in ("shed", "rejected"):
                    accepted_ids.add(r.rid)
            if ns.logdir:
                os.makedirs(ns.logdir, exist_ok=True)
                engine.write_telemetry(ns.logdir,
                                       slo_ttft_ms=ns.slo_ttft_ms)
                _write_drain_file(engine, ns.logdir, ns.replica_index)
        return engine

    def drained_needs_restart(engine) -> bool:
        # A drain that left trace work undone restarts (the supervisor's
        # replay completes checkpointed requests AND serves the trace
        # tail that never arrived before the preemption) when budget
        # exists; with --max_restarts 0 the drain.jsonl file is the
        # hand-off and this process exits clean.
        return (ns.max_restarts > 0 and engine.drained
                and len(completed) < len(trace))

    engine = run_supervised(fit_once, max_restarts=ns.max_restarts,
                            needs_restart=drained_needs_restart)
    return {"engine": engine, "completed": completed}


def serve_listen(ns, model, params,
                 drain_target: Optional[Dict] = None) -> int:
    """The TCP front end: one engine on the wall clock, socket handlers
    feeding it through the frontend bridge, SIGTERM = graceful drain."""
    from dtf_tpu.serve import WallClock
    from dtf_tpu.serve.frontend import TCPFrontend, parse_listen

    chaos = None
    if ns.chaos:
        from dtf_tpu.resilience.chaos import FaultPlan
        chaos = FaultPlan.parse(ns.chaos, process_index=0)
    heartbeat = None
    if ns.health_dir:
        # A fleet replica beats under ITS index so the acceptor's
        # missed-beat detector can tell replicas apart.
        from dtf_tpu.resilience.health import FileHeartbeatTransport
        transport = FileHeartbeatTransport(ns.health_dir,
                                           ns.replica_index or 0)
        heartbeat = transport.beat
    engine = _make_engine(ns, model, params, WallClock(), None, heartbeat,
                          chaos)
    if drain_target is not None:
        drain_target["engine"] = engine
        if drain_target.pop("early_sigterm", None):
            engine.request_drain()
    signal.signal(signal.SIGINT, lambda s, f: engine.request_drain())
    host, port = parse_listen(ns.listen)
    frontend = TCPFrontend(engine, host, port,
                           conn_timeout_s=ns.conn_timeout_s)
    addr = frontend.address
    print(f"serving on tcp://{addr[0]}:{addr[1]} "
          f"(preset={ns.preset}, slots={ns.slots}, "
          f"brownout={'on' if engine.brownout else 'off'})", flush=True)
    drain = frontend.run_loop(drain_timeout_s=ns.drain_timeout_s)
    if ns.logdir:
        os.makedirs(ns.logdir, exist_ok=True)
        engine.write_telemetry(ns.logdir, slo_ttft_ms=ns.slo_ttft_ms)
        path = _write_drain_file(engine, ns.logdir, ns.replica_index)
        if path:
            print(f"drained: {len(engine.drain_docs)} unfinished "
                  f"request(s) checkpointed to {path} "
                  f"(replay with --requests)", flush=True)
    print(json.dumps(engine.summary(slo_ttft_ms=ns.slo_ttft_ms),
                     indent=1, sort_keys=True))
    return 0 if (drain is None or not drain.get("timed_out")) else 1


def _fleet_config(ns):
    from dtf_tpu.serve.fleet import FleetConfig
    return FleetConfig(hedge_priority=ns.hedge_priority,
                       hedge_delay_ms=ns.hedge_delay_ms,
                       stream_timeout_s=ns.stream_timeout_s,
                       beat_stale_s=ns.beat_stale_s,
                       drain_timeout_s=ns.drain_timeout_s)


def _run_acceptor(ns, acc, banner: str) -> int:
    """Shared fleet-acceptor lifecycle: start, serve until SIGTERM or
    SIGINT, shut down, write the acceptor-side telemetry."""
    import threading

    stop = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda s, f: stop.set())
        signal.signal(signal.SIGINT, lambda s, f: stop.set())
    except ValueError:               # not the main thread (tests)
        pass
    acc.start()
    if ns.admin_port is not None:
        from dtf_tpu.telemetry.live import start_admin
        admin = start_admin(ns.admin_port, fleet_fn=acc.rollup,
                            logdir=ns.logdir or None)
        print(f"admin endpoint on http://127.0.0.1:{admin.port} "
              f"(/statz /healthz /tracez /slo /fleetz /memz /incidentz; "
              f"GET / for the full index)", flush=True)
    print(banner, flush=True)
    stop.wait()
    acc.shutdown()
    if ns.logdir:
        acc.write_telemetry(ns.logdir, slo_ttft_ms=ns.slo_ttft_ms)
    print(json.dumps(acc.summary(slo_ttft_ms=ns.slo_ttft_ms),
                     indent=1, sort_keys=True))
    return 0


def serve_fleet(ns, model, params) -> int:
    """--replicas N: the in-process fleet quickstart — N engine replicas
    (one seed, one driver thread) behind one acceptor socket."""
    from dtf_tpu.serve.fleet import build_local_fleet
    from dtf_tpu.serve.frontend import parse_listen

    chaos = None
    if ns.chaos:
        from dtf_tpu.resilience.chaos import FaultPlan
        chaos = FaultPlan.parse(ns.chaos, process_index=0)
    host, port = (parse_listen(ns.listen) if ns.listen
                  else ("127.0.0.1", 0))
    acc = build_local_fleet(
        model, params, ns.replicas, seed=ns.seed, host=host, port=port,
        config=_fleet_config(ns), chaos=chaos, logdir=ns.logdir,
        health_dir=ns.health_dir, conn_timeout_s=ns.conn_timeout_s,
        brownout=ns.brownout, slo_ttft_ms=ns.slo_ttft_ms,
        degrade_max_new=ns.degrade_max_new,
        engine_kwargs=dict(
            num_slots=ns.slots, block_size=ns.block_size,
            num_blocks=ns.pool_blocks, max_queue=ns.max_queue,
            aging_s=ns.aging_s, eos_id=ns.eos_id, spec_k=ns.spec_k,
            prefix_cache=getattr(ns, "prefix_cache", False)))
    return _run_acceptor(
        ns, acc,
        f"fleet serving on tcp://{acc.address[0]}:{acc.address[1]} "
        f"(replicas={ns.replicas}, preset={ns.preset}, "
        f"seed={ns.seed})")


def serve_acceptor(ns) -> int:
    """--connect: acceptor over already-running --listen replicas.  No
    model, no jax — this process is a pure routing/failover proxy, so
    it boots in milliseconds and can be restarted freely."""
    from dtf_tpu.serve.fleet import connect_remote_fleet
    from dtf_tpu.serve.frontend import parse_listen

    chaos = None
    if ns.chaos:
        from dtf_tpu.resilience.chaos import FaultPlan
        chaos = FaultPlan.parse(ns.chaos, process_index=0)
    addrs = []
    for part in ns.connect.split(","):
        host, _, port = part.strip().rpartition(":")
        addrs.append((host or "127.0.0.1", int(port)))
    bind_host, bind_port = (parse_listen(ns.listen) if ns.listen
                            else ("127.0.0.1", 0))
    acc = connect_remote_fleet(
        addrs, host=bind_host, port=bind_port, config=_fleet_config(ns),
        chaos=chaos, logdir=ns.logdir, health_dir=ns.health_dir,
        seed=ns.seed)
    return _run_acceptor(
        ns, acc,
        f"fleet acceptor on tcp://{acc.address[0]}:{acc.address[1]} "
        f"(replicas={len(addrs)})")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dtf_tpu.serve",
        description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "gpt2_small", "llama"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mode", choices=["continuous", "static"],
                   default="continuous")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--block_size", type=int, default=16)
    p.add_argument("--pool_blocks", type=int, default=None,
                   help="KV pool size in blocks (default: every slot "
                        "can hold a full window)")
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=1.0)
    p.add_argument("--eos_id", type=int, default=None)
    p.add_argument("--requests", default=None,
                   help="JSONL request file (see module docstring; a "
                        "drain.jsonl replays here)")
    p.add_argument("--demo", type=int, default=16,
                   help="no --requests: serve this many seeded demo "
                        "requests")
    p.add_argument("--qps", type=float, default=8.0,
                   help="demo arrival rate (Poisson)")
    p.add_argument("--qps_profile", default="constant",
                   choices=["constant", "ramp", "square", "sine"],
                   help="demo arrival-rate shape around --qps (same "
                        "seeded request CONTENTS for every profile — "
                        "only arrival times move; bench/serve_load.py "
                        "documents the shapes)")
    p.add_argument("--prompt_lens", default="4,8,16")
    p.add_argument("--output_lens", default="4,8,16")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="attach this completion deadline to every demo "
                        "request (0 = none); hopeless requests are shed "
                        "BEFORE prefill")
    p.add_argument("--priorities", default="0",
                   help="comma-separated priority pool demo requests "
                        "draw from (higher = sooner; brownout level 2 "
                        "sheds priority <= 0)")
    p.add_argument("--aging_s", type=float, default=2.0,
                   help="queue aging: +1 effective priority level per "
                        "this many seconds waited (anti-starvation)")
    p.add_argument("--brownout", action="store_true",
                   help="arm the overload controller against "
                        "--slo_ttft_ms (serve/brownout.py)")
    p.add_argument("--controller", action="store_true",
                   help="arm the self-tuning knob controller "
                        "(dtf_tpu/control): SLO-driven runtime tuning "
                        "of spec_k / prefill budget / brownout "
                        "thresholds with audited, bounded steps and "
                        "snap-back safety rails; inspect via /controlz")
    p.add_argument("--degrade_max_new", type=int, default=8,
                   help="brownout level-1 output-length ceiling")
    p.add_argument("--chaos", default=None,
                   help="serving fault plan, e.g. "
                        "'slow_decode@40:80ms:60,client_drop@20,"
                        "kv_poison@30' (iteration-keyed)")
    p.add_argument("--spec_k", type=int, default=0,
                   help="speculative decoding: up to this many "
                        "self-drafted (n-gram prompt-lookup) tokens "
                        "verified per iteration; greedy tokens stay "
                        "bitwise identical to spec_k=0 (0 = off)")
    p.add_argument("--prefix_cache", action="store_true",
                   help="share prompt-prefix KV across requests "
                        "(refcounted blocks + COW fork + suffix-only "
                        "prefill; DESIGN.md §7.7).  Demo traffic "
                        "switches to the shared-prefix chatbot mix so "
                        "the cache actually gets hits")
    p.add_argument("--no_prefill_coalesce", action="store_true",
                   help="disable batched multi-request prefill (the "
                        "determinism A/B's solo baseline)")
    p.add_argument("--no_narrow", action="store_true",
                   help="disable the narrowed decode data path (full "
                        "window / whole pool per step — the ladder's "
                        "baseline geometry)")
    p.add_argument("--clock", choices=["wall", "virtual"], default="wall")
    p.add_argument("--stream", action="store_true",
                   help="print each token as it is emitted")
    p.add_argument("--logdir", default=None)
    p.add_argument("--slo_ttft_ms", type=float, default=500.0)
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("--health_dir", default=None,
                   help="publish per-iteration liveness beats here "
                        "(resilience/health.py file transport)")
    p.add_argument("--wedge_at", type=int, default=None,
                   help="fault injection: crash at this iteration of "
                        "attempt 0 (supervisor-restart proof)")
    p.add_argument("--drain_at", type=int, default=None,
                   help="deterministic preemption: request a graceful "
                        "drain at this iteration of attempt 0 (the CI "
                        "spelling of SIGTERM)")
    p.add_argument("--drain_timeout_s", type=float, default=30.0,
                   help="graceful-drain grace window (in-flight decodes "
                        "past it are checkpointed, not finished)")
    p.add_argument("--admin_port", type=int, default=None,
                   help="mount the live introspection endpoint on "
                        "127.0.0.1:PORT (/statz /healthz /tracez /slo "
                        "/controlz /memz /incidentz; 0 = ephemeral "
                        "port, printed at startup)")
    p.add_argument("--listen", default=None, metavar="HOST:PORT",
                   help="run the TCP front end instead of a trace "
                        "(':8100' binds 127.0.0.1:8100; wall clock); "
                        "with --replicas/--connect this is the fleet "
                        "acceptor's bind address")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="fleet quickstart: N in-process engine replicas "
                        "(one seed, one driver thread) behind one "
                        "acceptor socket (serve/fleet.py)")
    p.add_argument("--connect", default=None, metavar="H:P,H:P,...",
                   help="fleet acceptor over already-running --listen "
                        "replica processes (no model in this process; "
                        "replicas must share --seed and, for missed-"
                        "beat detection, --health_dir)")
    p.add_argument("--replica_index", type=int, default=None, metavar="K",
                   help="this --listen process is fleet replica K: "
                        "heartbeats publish as hb_K and the drain "
                        "checkpoint namespaces to drain.rK.jsonl")
    p.add_argument("--hedge_priority", type=int, default=1,
                   help="fleet: priority classes >= this get hedged "
                        "dispatch (a duplicate leg on a second replica "
                        "after the hedge delay)")
    p.add_argument("--hedge_delay_ms", type=float, default=None,
                   help="fleet: fixed hedge delay (default: p99 of "
                        "observed TTFT, floored at 50ms)")
    p.add_argument("--stream_timeout_s", type=float, default=30.0,
                   help="fleet: per-event replica-stream wait before a "
                        "leg is declared wedged and failed over")
    p.add_argument("--beat_stale_s", type=float, default=10.0,
                   help="fleet: detach a replica whose heartbeat count "
                        "has not advanced for this long")
    p.add_argument("--conn_timeout_s", type=float, default=30.0,
                   help="TCP per-connection idle/read timeout")
    p.add_argument("--tokens_out", default=None,
                   help="write {rid: tokens} JSON for all completed "
                        "requests (the drain-replay identity check)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    ns = p.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if (ns.listen or ns.replicas or ns.connect) and ns.clock == "virtual":
        p.error("--listen serves real clients; it needs --clock wall")
    if ns.replicas is not None and ns.connect:
        p.error("--replicas builds local replicas; --connect attaches "
                "to remote ones — pick one")
    if ns.replicas is not None and ns.replicas < 1:
        p.error("--replicas must be >= 1")
    if ns.logdir:
        # span tracer (rotation-bounded): request lifecycle events and
        # the engine's prefill/decode iteration spans land here, the
        # inputs of `telemetry.report --request` and the Perfetto export
        from dtf_tpu import telemetry as tel
        tel.configure(ns.logdir)

    # Install the preemption handler BEFORE the multi-second jax/model
    # init: a SIGTERM that lands mid-init must buffer into a drain of
    # the first engine, not kill the process (the grace window starts
    # at signal delivery, not at "server finally came up").
    drain_target: Dict[str, object] = {}

    def _on_sigterm(signum, frame):
        eng = drain_target.get("engine")
        if eng is not None:
            eng.request_drain()
        else:
            drain_target["early_sigterm"] = True

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:               # not the main thread (tests)
        pass

    if ns.connect:
        # pure proxy: never initialise jax or build a model
        return serve_acceptor(ns)

    import jax

    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.from_preset(ns.preset)
    model = GPT(cfg)
    params = model.init(jax.random.key(ns.seed))
    if ns.replicas is not None:
        return serve_fleet(ns, model, params)
    if ns.listen:
        return serve_listen(ns, model, params, drain_target)
    trace = build_trace(ns, cfg.vocab_size, max_len=cfg.max_len)
    out = serve_session(ns, model, params, trace, drain_target)
    engine = out["engine"]
    summary = engine.summary(slo_ttft_ms=ns.slo_ttft_ms)
    summary["completed_all_attempts"] = len(out["completed"])
    print(json.dumps(summary, indent=1, sort_keys=True))
    if ns.tokens_out:
        with open(ns.tokens_out, "w") as f:
            json.dump({str(rid): r.tokens
                       for rid, r in sorted(out["completed"].items())},
                      f, sort_keys=True)
    wanted = {kw["rid"] for _, kw in trace}
    never_accepted = {
        r.rid for r in engine.results.values()
        if r.status in ("rejected", "shed", "cancelled", "failed",
                        "drained")}
    missing = wanted - set(out["completed"]) - never_accepted
    if missing and engine.drained:
        # clean preemption hand-off: everything missing is in the drain
        # file (or was never accepted); nothing accepted was lost
        in_drain = {d["rid"] for d in engine.drain_docs}
        missing -= in_drain
        # trace entries that never arrived before the drain were never
        # accepted either
        missing -= {kw["rid"] for t, kw in trace
                    if kw["rid"] not in engine.results}
    if missing:
        print(f"error: {len(missing)} request(s) never completed: "
              f"{sorted(missing)[:8]}...", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
