"""Serving CLI: run the continuous-batching engine as a process.

    # demo traffic: 32 seeded requests at ~8 QPS through the tiny preset
    python -m dtf_tpu.serve --preset tiny --demo 32 --qps 8 \
        --logdir /tmp/dtf_serve

    # requests from a JSONL file (one {"prompt": [...ids...],
    # "max_new_tokens": N, "temperature": T} per line), streamed tokens
    python -m dtf_tpu.serve --preset tiny --requests reqs.jsonl --stream

Resilience spine reuse (DESIGN.md §5): ``--max_restarts N`` wraps the
serve session in the bounded-restart supervisor — a crashed or wedged
server restarts and REPLAYS the unfinished requests (completed results
survive the attempt boundary); ``--health_dir`` publishes a liveness
heartbeat per engine iteration through ``resilience.health``'s file
transport, so an external monitor (or the chaos suite) can tell a
serving process that is decoding from one that is wedged.
``--wedge_at K`` injects a crash at iteration K of the first attempt —
the supervisor-path proof the CI lane drives.

Weights are seeded-random (this repo has no trained checkpoints to
ship); the engine, scheduler, cache, and telemetry paths are exactly
the production ones.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np


def build_trace(ns, vocab_size: int) -> List[Tuple[float, dict]]:
    """The request trace: JSONL file or a seeded Poisson demo mix."""
    trace: List[Tuple[float, dict]] = []
    if ns.requests:
        with open(ns.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                trace.append((float(doc.get("arrival_s", 0.0)), {
                    "rid": i,
                    "prompt": np.asarray(doc["prompt"], np.int32),
                    "max_new_tokens": int(doc.get("max_new_tokens", 16)),
                    "temperature": float(doc.get("temperature",
                                                 ns.temperature)),
                }))
        trace.sort(key=lambda e: e[0])
        return trace
    # ONE Poisson trace generator in the repo (the load bench's
    # unit-rate chain, rate-scaling invariant included).
    from dtf_tpu.bench.serve_load import poisson_trace
    return poisson_trace(
        seed=ns.seed, n_requests=ns.demo, qps=ns.qps,
        prompt_lens=[int(x) for x in ns.prompt_lens.split(",")],
        output_lens=[int(x) for x in ns.output_lens.split(",")],
        vocab_size=vocab_size, temperature=ns.temperature)


def serve_session(ns, model, params, trace) -> Dict:
    """Run the trace to completion under the supervisor: unfinished
    requests replay on restart (arrival re-stamped to the new attempt's
    clock — an external client would keep its own latency books across
    the gap), completed results survive."""
    from dtf_tpu.resilience.supervisor import run_supervised
    from dtf_tpu.serve import ServingEngine, VirtualClock, WallClock

    completed: Dict[int, object] = {}

    def printer(req, token, done):
        if ns.stream:
            tail = " <end>" if done else ""
            print(f"  [req {req.rid}] +{token}{tail}", flush=True)

    def make_heartbeat():
        if not ns.health_dir:
            return None
        from dtf_tpu.resilience.health import FileHeartbeatTransport
        transport = FileHeartbeatTransport(ns.health_dir, 0)
        return lambda count: transport.beat(count)

    def fit_once(attempt: int):
        clock = (VirtualClock() if ns.clock == "virtual" else WallClock())
        engine = ServingEngine(
            model, params, num_slots=ns.slots, block_size=ns.block_size,
            num_blocks=ns.pool_blocks, mode=ns.mode, top_k=ns.top_k,
            top_p=ns.top_p, eos_id=ns.eos_id, seed=ns.seed, clock=clock,
            max_queue=ns.max_queue, on_token=printer,
            heartbeat=make_heartbeat())
        if ns.wedge_at is not None and attempt == 0:
            real_step = engine.step

            def wedged_step():
                if engine.iterations == ns.wedge_at:
                    raise RuntimeError(
                        "chaos: serve wedged (injected --wedge_at)")
                return real_step()

            engine.step = wedged_step
        pending = [(0.0 if attempt else t, kw) for t, kw in trace
                   if kw["rid"] not in completed]
        try:
            engine.run(pending)
        finally:
            completed.update(
                {rid: r for rid, r in engine.results.items()
                 if r.status == "completed"})
            if ns.logdir:
                import os
                os.makedirs(ns.logdir, exist_ok=True)
                engine.write_telemetry(ns.logdir,
                                       slo_ttft_ms=ns.slo_ttft_ms)
        return engine

    engine = run_supervised(fit_once, max_restarts=ns.max_restarts,
                            needs_restart=lambda r: False)
    return {"engine": engine, "completed": completed}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dtf_tpu.serve",
        description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "gpt2_small", "llama"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mode", choices=["continuous", "static"],
                   default="continuous")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--block_size", type=int, default=16)
    p.add_argument("--pool_blocks", type=int, default=None,
                   help="KV pool size in blocks (default: every slot "
                        "can hold a full window)")
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=1.0)
    p.add_argument("--eos_id", type=int, default=None)
    p.add_argument("--requests", default=None,
                   help="JSONL request file (see module docstring)")
    p.add_argument("--demo", type=int, default=16,
                   help="no --requests: serve this many seeded demo "
                        "requests")
    p.add_argument("--qps", type=float, default=8.0,
                   help="demo arrival rate (Poisson)")
    p.add_argument("--prompt_lens", default="4,8,16")
    p.add_argument("--output_lens", default="4,8,16")
    p.add_argument("--clock", choices=["wall", "virtual"], default="wall")
    p.add_argument("--stream", action="store_true",
                   help="print each token as it is emitted")
    p.add_argument("--logdir", default=None)
    p.add_argument("--slo_ttft_ms", type=float, default=500.0)
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("--health_dir", default=None,
                   help="publish per-iteration liveness beats here "
                        "(resilience/health.py file transport)")
    p.add_argument("--wedge_at", type=int, default=None,
                   help="fault injection: crash at this iteration of "
                        "attempt 0 (supervisor-restart proof)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    ns = p.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax

    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.from_preset(ns.preset)
    model = GPT(cfg)
    params = model.init(jax.random.key(ns.seed))
    trace = build_trace(ns, cfg.vocab_size)
    out = serve_session(ns, model, params, trace)
    engine = out["engine"]
    summary = engine.summary(slo_ttft_ms=ns.slo_ttft_ms)
    summary["completed_all_attempts"] = len(out["completed"])
    print(json.dumps(summary, indent=1, sort_keys=True))
    wanted = {kw["rid"] for _, kw in trace}
    missing = wanted - set(out["completed"])
    if missing:
        print(f"error: {len(missing)} request(s) never completed: "
              f"{sorted(missing)[:8]}...", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
