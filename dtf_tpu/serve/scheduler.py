"""Request scheduler: admission control + continuous (in-flight) batching.

The scheduling core of the serving engine, deliberately jax-free so the
policy unit-tests run without compiling anything.  Responsibilities:

* **Admission control** — a bounded wait queue (``max_queue``; overflow
  is REJECTED loudly at submit, the backpressure signal a closed-loop
  client needs), a fits-the-window check (prompt + max_new must fit the
  per-slot block window and the model's max_len), and a KV-block
  reservation: a request is only admitted when the pool can hold its
  worst case (padded prompt + every token it may generate), so a
  mid-flight allocation failure is impossible by construction — no
  eviction/swap machinery needed.
* **Continuous batching** — every engine iteration, finished requests
  release their slot + blocks and queued requests join immediately
  (``admit`` is called every iteration).  The decode batch recomposes
  at token granularity, which is the whole throughput story the load
  bench measures.
* **Prefill/decode phase separation** — admissions per iteration are
  capped by ``prefill_token_budget`` prompt tokens (the first admission
  always goes through), so a burst of long prompts drips into the
  batch across iterations instead of stalling every in-flight decode
  behind one giant prefill wave.
* **Static batching baseline** — ``mode="static"``: requests are only
  admitted when the batch is EMPTY (the previous batch fully drained),
  in groups of up to ``num_slots`` (fill-or-timeout via
  ``static_batch_wait_s``).  This is the A/B foil for the load
  generator: same engine, same kernels, only the admission policy
  differs — so the measured goodput gap is attributable to continuous
  batching alone.

Determinism: decisions depend only on (queue order, slot/allocator
state, the injected clock).  Under a seeded virtual clock the same
arrival trace reproduces the same batch composition sequence exactly —
pinned by tests.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from dtf_tpu.serve.paged_kv import BlockAllocator, blocks_for

MODES = ("continuous", "static")


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Request:
    """One generation request.  ``temperature=0`` is greedy; sampling
    draws come from a per-request stream seeded by (engine seed, rid),
    so a request's tokens are independent of the batch composition it
    rode (continuous vs static modes emit identical tokens — tested)."""

    rid: int
    prompt: np.ndarray                 # (P,) int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    arrival_s: float = 0.0             # stamped at submit

    # runtime state (engine/scheduler owned)
    slot: Optional[int] = None
    blocks: Optional[List[int]] = None
    pos: int = 0                       # next KV write position
    tokens: Optional[List[int]] = None # generated tokens (first included)
    first_token_s: Optional[float] = None
    last_token_s: Optional[float] = None
    done_s: Optional[float] = None
    status: str = "queued"             # queued|running|completed|rejected

    @property
    def prompt_len(self) -> int:
        return int(len(self.prompt))

    def padded_prompt_len(self, block_size: int) -> int:
        return blocks_for(self.prompt_len, block_size) * block_size

    def n_generated(self) -> int:
        return len(self.tokens) if self.tokens else 0

    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None:
            return None
        return self.first_token_s - self.arrival_s

    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first (the streaming
        cadence a client sees); None until 2+ tokens exist."""
        n = self.n_generated()
        if n < 2 or self.last_token_s is None or self.first_token_s is None:
            return None
        return (self.last_token_s - self.first_token_s) / (n - 1)


# ---------------------------------------------------------------------------
# Clocks
# ---------------------------------------------------------------------------


class WallClock:
    """Real time.  ``charge`` is a no-op — the wall advanced on its own
    while the device computed."""

    def __init__(self):
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def charge(self, kind: str, *, tokens: int = 0, batch: int = 0) -> None:
        pass

    def advance_to(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            time.sleep(delta)


class VirtualClock:
    """Deterministic simulated time for CI and scheduling experiments:
    each engine compute call advances the clock by a fixed cost model
    instead of by noisy wall time.  The A/B between scheduling policies
    is then exactly reproducible — the lane asserts the continuous-vs-
    static goodput ratio against it.

    Cost model (milliseconds): ``prefill = prefill_base + prefill_per_token
    * tokens``; ``decode = decode_base + decode_per_seq * batch`` — the
    shape of real decode cost (a fixed dispatch floor plus a per-stream
    term), with defaults in the measured range of the CPU-sim tiny
    preset.  Calibrate per chip if the absolute numbers matter; the
    POLICY comparison only needs the shape.
    """

    def __init__(self, *, decode_base_ms: float = 8.0,
                 decode_per_seq_ms: float = 0.5,
                 prefill_base_ms: float = 2.0,
                 prefill_per_token_ms: float = 0.2):
        self._t = 0.0
        self.decode_base_ms = decode_base_ms
        self.decode_per_seq_ms = decode_per_seq_ms
        self.prefill_base_ms = prefill_base_ms
        self.prefill_per_token_ms = prefill_per_token_ms

    def now(self) -> float:
        return self._t

    def charge(self, kind: str, *, tokens: int = 0, batch: int = 0) -> None:
        if kind == "prefill":
            ms = self.prefill_base_ms + self.prefill_per_token_ms * tokens
        elif kind == "decode":
            ms = self.decode_base_ms + self.decode_per_seq_ms * batch
        else:
            raise ValueError(f"unknown charge kind {kind!r}")
        self._t += ms / 1e3

    def advance_to(self, t: float) -> None:
        self._t = max(self._t, t)


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


class Scheduler:
    """Slot + queue + block bookkeeping; see module docstring for the
    policy.  The engine calls, per iteration: :meth:`release` for each
    finished request, then :meth:`admit`, then runs prefill for the
    admissions and one decode step for the occupied slots."""

    def __init__(self, *, num_slots: int, allocator: BlockAllocator,
                 block_size: int, blocks_per_slot: int,
                 mode: str = "continuous", max_queue: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 static_batch_wait_s: float = 0.05,
                 max_len: Optional[int] = None):
        if mode not in MODES:
            raise ValueError(f"serving mode must be one of {MODES}, "
                             f"got {mode!r}")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self.allocator = allocator
        self.block_size = block_size
        self.blocks_per_slot = blocks_per_slot
        self.mode = mode
        self.max_queue = max_queue
        # Default budget: one slot window of prompt tokens per iteration
        # — enough to keep admissions flowing, small enough that a burst
        # of long prompts cannot freeze every in-flight decode at once.
        self.prefill_token_budget = (prefill_token_budget
                                     or blocks_per_slot * block_size)
        self.static_batch_wait_s = static_batch_wait_s
        self.max_len = max_len
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * num_slots

    # -- state queries ------------------------------------------------------

    def active(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def num_active(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    def has_work(self) -> bool:
        return bool(self.queue) or self.num_active() > 0

    # -- admission ----------------------------------------------------------

    def _blocks_needed(self, req: Request) -> int:
        """Worst-case block reservation: the padded prompt region plus
        every decode write (positions ``p .. p+max_new-2``; the final
        emitted token is never written back).  EOS may finish earlier —
        the reservation is the no-surprise upper bound that makes
        mid-flight pool exhaustion impossible."""
        p_pad = req.padded_prompt_len(self.block_size)
        rows = max(p_pad, req.prompt_len + req.max_new_tokens - 1)
        return blocks_for(rows, self.block_size)

    def submit(self, req: Request, now: float) -> str:
        """Admission control at the front door.  Returns the request's
        status: ``queued`` or ``rejected`` (``req.status`` matches, and a
        rejected request carries the reason in ``req.tokens is None`` +
        the return value; the engine counts both)."""
        req.arrival_s = now
        total = req.prompt_len + req.max_new_tokens
        window = self.blocks_per_slot * self.block_size
        limit = min(window, self.max_len) if self.max_len else window
        if req.max_new_tokens < 1 or req.prompt_len < 1:
            req.status = "rejected"
            return "rejected_empty"
        # Reject against BOTH ceilings: the per-slot window and the whole
        # pool.  A request needing more blocks than the pool holds would
        # otherwise queue forever (nothing in flight can free enough) and
        # head-of-line-block every request behind it — a wedged engine.
        pool_cap = self.allocator.num_blocks - 1
        if (total > limit
                or self._blocks_needed(req) > min(self.blocks_per_slot,
                                                  pool_cap)):
            req.status = "rejected"
            return "rejected_too_long"
        if len(self.queue) >= self.max_queue:
            req.status = "rejected"
            return "rejected_queue_full"
        req.status = "queued"
        self.queue.append(req)
        return "queued"

    def release(self, req: Request) -> None:
        """Return a finished request's slot and blocks to the pool (the
        continuous-batching eviction half; admissions refill the slot on
        the same iteration)."""
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        if req.blocks:
            self.allocator.free(req.blocks)
            req.blocks = None

    def _assign(self, req: Request) -> Tuple[int, Request]:
        slot = self.slots.index(None)
        req.blocks = self.allocator.allocate(self._blocks_needed(req))
        req.slot = slot
        req.status = "running"
        req.tokens = []
        self.slots[slot] = req
        return slot, req

    def admit(self, now: float) -> List[Tuple[int, Request]]:
        """The per-iteration admission decision (see module docstring
        for both policies).  Returns ``(slot, request)`` pairs the engine
        must prefill this iteration."""
        out: List[Tuple[int, Request]] = []
        if self.mode == "static":
            if self.num_active() or not self.queue:
                return out
            full = len(self.queue) >= self.num_slots
            # Same expression as the engine's batch-forming horizon
            # (arrival + wait): ``now - arrival >= wait`` is NOT
            # float-equivalent to ``now >= arrival + wait``, and the
            # mismatch once left a virtual clock parked one ulp short of
            # aging the batch out — forever.
            aged = (now
                    >= self.queue[0].arrival_s + self.static_batch_wait_s)
            if not (full or aged):
                return out
            while self.queue and self.num_active() < self.num_slots:
                req = self.queue[0]
                if not self.allocator.can_allocate(self._blocks_needed(req)):
                    break
                self.queue.popleft()
                out.append(self._assign(req))
            return out

        budget = self.prefill_token_budget
        while self.queue and self.num_active() < self.num_slots:
            req = self.queue[0]
            p_pad = req.padded_prompt_len(self.block_size)
            if out and p_pad > budget:
                break                   # phase separation: drip prefills
            if not self.allocator.can_allocate(self._blocks_needed(req)):
                break                   # blocks come back as decodes finish
            self.queue.popleft()
            out.append(self._assign(req))
            budget -= p_pad
        return out
