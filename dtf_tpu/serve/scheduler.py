"""Request scheduler: admission control + continuous (in-flight) batching.

The scheduling core of the serving engine, deliberately jax-free so the
policy unit-tests run without compiling anything.  Responsibilities:

* **Admission control** — a bounded wait queue (``max_queue``; overflow
  is REJECTED loudly at submit, the backpressure signal a closed-loop
  client needs), a fits-the-window check (prompt + max_new must fit the
  per-slot block window and the model's max_len), and a KV-block
  reservation: a request is only admitted when the pool can hold its
  worst case (padded prompt + every token it may generate), so a
  mid-flight allocation failure is impossible by construction — no
  eviction/swap machinery needed.
* **Continuous batching** — every engine iteration, finished requests
  release their slot + blocks and queued requests join immediately
  (``admit`` is called every iteration).  The decode batch recomposes
  at token granularity, which is the whole throughput story the load
  bench measures.
* **Prefill/decode phase separation** — admissions per iteration are
  capped by ``prefill_token_budget`` prompt tokens (the first admission
  always goes through), so a burst of long prompts drips into the
  batch across iterations instead of stalling every in-flight decode
  behind one giant prefill wave.
* **Static batching baseline** — ``mode="static"``: requests are only
  admitted when the batch is EMPTY (the previous batch fully drained),
  in groups of up to ``num_slots`` (fill-or-timeout via
  ``static_batch_wait_s``).  This is the A/B foil for the load
  generator: same engine, same kernels, only the admission policy
  differs — so the measured goodput gap is attributable to continuous
  batching alone.
* **Deadline-aware shedding** — a request carrying ``deadline_ms``
  (completion budget relative to arrival) is SHED — cheaply, before
  any prefill work — the moment the scheduler can prove it hopeless:
  already expired, or unmeetable under the current decode-rate
  estimate (EWMAs of measured prefill-per-token and decode-iteration
  cost, fed by the engine).  Shedding before prefill is the whole
  point: an evicted mid-decode request has already burned prefill
  FLOPs and KV blocks; a shed one cost a queue entry.  Sheds are
  reported through ``on_shed`` with a reason so the engine can book
  them (``serve/shed_total`` + per-reason counters).
* **Priority with aging** — ``Request.priority`` (higher = sooner)
  orders continuous-mode admission; FIFO within a class (stable sort
  on arrival), and a queued request gains one effective priority level
  per ``aging_s`` waited, so a stream of high-priority arrivals cannot
  starve a low-priority request forever.  Candidates are considered
  in effective-priority order and the walk STOPS at the first
  candidate that does not fit (no skip-ahead past a block-starved
  request): a long request at the head keeps its claim on the next
  freed blocks — the other half of the starvation story.  Both halves
  are pinned by tests.
* **Draining** — ``draining = True`` freezes the front door (submits
  rejected, nothing admitted) while in-flight decodes finish; the
  engine's graceful-drain path (SIGTERM) owns the flag.

Determinism: decisions depend only on (queue order, slot/allocator
state, the injected clock).  Under a seeded virtual clock the same
arrival trace reproduces the same batch composition sequence exactly —
pinned by tests.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

import numpy as np

from dtf_tpu.serve.paged_kv import BlockAllocator, blocks_for

MODES = ("continuous", "static")


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class Request:
    """One generation request.  ``temperature=0`` is greedy; sampling
    draws come from a per-request stream seeded by (engine seed, rid),
    so a request's tokens are independent of the batch composition it
    rode (continuous vs static modes emit identical tokens — tested).

    ``eq=False``: a request is identified by OBJECT, not by field value.
    Two live Request objects may share a rid (a fleet acceptor's
    failover/hedge resubmits the same rid while the original copy is
    still queued on the old replica), and field equality on such a pair
    walks into ``prompt`` — a numpy array whose ``==`` is elementwise,
    so ``queue.remove``/``in`` membership raised "truth value of an
    array is ambiguous" and crashed the engine driver.  cancel() must
    tear out THE object it was handed, never an equal-valued twin."""

    rid: int
    prompt: np.ndarray                 # (P,) int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    arrival_s: float = 0.0             # stamped at submit
    deadline_ms: Optional[float] = None  # completion budget from arrival
    priority: int = 0                  # higher = admitted sooner
    #: Distributed-tracing id (telemetry/reqtrace.py): minted at the TCP
    #: front end or at submit, carried through drain/replay so a
    #: replayed request links to its pre-SIGTERM timeline.
    trace_id: Optional[str] = None
    #: True only on the drain/supervisor REPLAY of a previously-accepted
    #: request (stamped on the reqtrace submit event).  Explicit, never
    #: inferred from trace_id presence — a TCP client's fresh request
    #: also carries a front-door-minted id.
    resubmit: bool = False

    # runtime state (engine/scheduler owned)
    slot: Optional[int] = None
    blocks: Optional[List[int]] = None
    pos: int = 0                       # next KV write position
    # prefix-cache state (engine-owned; serve/paged_kv.py sharing): the
    # shared full blocks matched + ACQUIRED at submit time — pinned so
    # lazy cache reclaim can never invalidate the reservation discount
    # between submit and admission.  _assign folds them into ``blocks``
    # (table prefix) and clears the field; release frees whichever of
    # the two is still held, so a request cancelled at ANY lifecycle
    # point returns its pins exactly once.
    prefix_blocks: Optional[List[int]] = None
    cached_prefix_blocks: int = 0      # = len(prefix_blocks) at match
    # full-content chain digests of the prompt (prompt_len // block_size
    # entries), computed once at submit: the match walk reads a prefix
    # of them, registration after prefill publishes them all
    prefix_digests: Optional[List[bytes]] = None
    tokens: Optional[List[int]] = None # generated tokens (first included)
    first_token_s: Optional[float] = None
    last_token_s: Optional[float] = None
    done_s: Optional[float] = None
    # queued|running|completed|rejected|shed|cancelled|failed|drained
    status: str = "queued"
    shed_reason: Optional[str] = None  # set when status == "shed"
    degraded: bool = False             # brownout clamped max_new_tokens
    # speculative-decoding adaptive state (engine-owned): drafting
    # credit — decremented on zero-acceptance verify rounds, restored
    # by accepted drafts; at 0 the request rides verify windows for
    # free (n_in=1) until the periodic retry.  Purely a cost policy:
    # token identity never depends on whether a row drafted (the
    # verify step emits the model's own choices either way).
    spec_credit: int = 2
    spec_idle: int = 0                 # iterations since last draft try

    @property
    def prompt_len(self) -> int:
        return int(len(self.prompt))

    def padded_prompt_len(self, block_size: int) -> int:
        return blocks_for(self.prompt_len, block_size) * block_size

    def n_generated(self) -> int:
        return len(self.tokens) if self.tokens else 0

    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None:
            return None
        return self.first_token_s - self.arrival_s

    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first (the streaming
        cadence a client sees); None until 2+ tokens exist."""
        n = self.n_generated()
        if n < 2 or self.last_token_s is None or self.first_token_s is None:
            return None
        return (self.last_token_s - self.first_token_s) / (n - 1)

    def deadline_at_s(self) -> Optional[float]:
        """Absolute completion deadline on the engine clock (None = no
        deadline)."""
        if self.deadline_ms is None:
            return None
        return self.arrival_s + self.deadline_ms / 1e3

    def completion_s(self) -> Optional[float]:
        """Arrival-to-done latency; None until completed."""
        if self.done_s is None:
            return None
        return self.done_s - self.arrival_s

    def replay_doc(self) -> dict:
        """The request's replayable identity — everything a restarted
        engine needs to redraw the SAME tokens (per-request rng streams
        are seeded by (engine seed, rid), so replay is token-identical
        regardless of batch composition).  Runtime state is deliberately
        absent: a drained request replays from scratch."""
        return {"rid": int(self.rid),
                "prompt": np.asarray(self.prompt).tolist(),
                "max_new_tokens": int(self.max_new_tokens),
                "temperature": float(self.temperature),
                "eos_id": None if self.eos_id is None else int(self.eos_id),
                "deadline_ms": self.deadline_ms,
                "priority": int(self.priority),
                # continuity: the replay engine re-submits under the SAME
                # trace id, so --request <rid> shows one timeline across
                # the SIGTERM boundary instead of a fresh unlinked one;
                # a doc only exists because this request WAS accepted, so
                # any submission built from it is by construction a replay
                "trace_id": self.trace_id,
                "resubmit": True}


# ---------------------------------------------------------------------------
# Clocks
# ---------------------------------------------------------------------------


class WallClock:
    """Real time.  ``charge`` is a no-op — the wall advanced on its own
    while the device computed."""

    def __init__(self):
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def charge(self, kind: str, *, tokens: int = 0, batch: int = 0) -> None:
        pass

    def advance_to(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            time.sleep(delta)


class VirtualClock:
    """Deterministic simulated time for CI and scheduling experiments:
    each engine compute call advances the clock by a fixed cost model
    instead of by noisy wall time.  The A/B between scheduling policies
    is then exactly reproducible — the lane asserts the continuous-vs-
    static goodput ratio against it.

    Cost model (milliseconds): ``prefill = prefill_base + prefill_per_token
    * tokens``; ``decode = decode_base + decode_per_seq * batch``;
    ``verify = decode + verify_per_token * drafted_tokens`` — the shape
    of real decode cost (a fixed dispatch floor plus a per-stream term;
    a speculative verify pays the SAME dispatch floor once for its whole
    window plus a small per-extra-token compute term, which is exactly
    why acceptance buys TPOT), with defaults in the measured range of
    the CPU-sim tiny preset.  Calibrate per chip if the absolute
    numbers matter; the POLICY comparison only needs the shape.
    Batched prefill deliberately charges per member (see the engine) so
    policy A/Bs are prefill-dispatch-mode independent.
    """

    def __init__(self, *, decode_base_ms: float = 8.0,
                 decode_per_seq_ms: float = 0.5,
                 prefill_base_ms: float = 2.0,
                 prefill_per_token_ms: float = 0.2,
                 # an extra verify-window token is prefill-like work (one
                 # more row in an already-dispatched batched matmul), so
                 # it prices BELOW the prefill per-token rate — it shares
                 # the decode dispatch it rides on
                 verify_per_token_ms: float = 0.1):
        self._t = 0.0
        self.decode_base_ms = decode_base_ms
        self.decode_per_seq_ms = decode_per_seq_ms
        self.prefill_base_ms = prefill_base_ms
        self.prefill_per_token_ms = prefill_per_token_ms
        self.verify_per_token_ms = verify_per_token_ms

    def now(self) -> float:
        return self._t

    def charge(self, kind: str, *, tokens: int = 0, batch: int = 0) -> None:
        if kind == "prefill":
            ms = self.prefill_base_ms + self.prefill_per_token_ms * tokens
        elif kind == "decode":
            ms = self.decode_base_ms + self.decode_per_seq_ms * batch
        elif kind == "verify":
            # one decode dispatch + the window's extra (drafted) tokens
            ms = (self.decode_base_ms + self.decode_per_seq_ms * batch
                  + self.verify_per_token_ms * tokens)
        else:
            raise ValueError(f"unknown charge kind {kind!r}")
        self._t += ms / 1e3

    def advance_to(self, t: float) -> None:
        self._t = max(self._t, t)


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


class Scheduler:
    """Slot + queue + block bookkeeping; see module docstring for the
    policy.  The engine calls, per iteration: :meth:`release` for each
    finished request, then :meth:`admit`, then runs prefill for the
    admissions and one decode step for the occupied slots."""

    def __init__(self, *, num_slots: int, allocator: BlockAllocator,
                 block_size: int, blocks_per_slot: int,
                 mode: str = "continuous", max_queue: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 static_batch_wait_s: float = 0.05,
                 max_len: Optional[int] = None,
                 aging_s: float = 2.0):
        if mode not in MODES:
            raise ValueError(f"serving mode must be one of {MODES}, "
                             f"got {mode!r}")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self.allocator = allocator
        self.block_size = block_size
        self.blocks_per_slot = blocks_per_slot
        self.mode = mode
        self.max_queue = max_queue
        # Default budget: one slot window of prompt tokens per iteration
        # — enough to keep admissions flowing, small enough that a burst
        # of long prompts cannot freeze every in-flight decode at once.
        self.prefill_token_budget = (prefill_token_budget
                                     or blocks_per_slot * block_size)
        self.static_batch_wait_s = static_batch_wait_s
        self.max_len = max_len
        self.aging_s = aging_s
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * num_slots
        #: Front door freeze for graceful drain: submits are rejected,
        #: admit returns nothing, in-flight decodes keep stepping.
        self.draining = False
        #: Engine hook — called with (request, reason) for every shed so
        #: sheds are booked exactly once, wherever they happen.
        self.on_shed: Optional[Callable[[Request, str], None]] = None
        # Decode-rate estimate (EWMA, fed by the engine's measured
        # clock durations).  0.0 = no observation yet: the estimator is
        # optimistic until the first prefill/decode lands, so a cold
        # engine never sheds on a fictitious rate.
        self.prefill_s_per_token = 0.0
        self.decode_iter_s = 0.0
        self._ewma_alpha = 0.3

    # -- state queries ------------------------------------------------------

    def active(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def num_active(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    def has_work(self) -> bool:
        return bool(self.queue) or self.num_active() > 0

    def oldest_queued_wait_s(self, now: float) -> float:
        """Longest current queue wait (0 when empty) — the brownout
        controller's early-warning signal: under overload nothing
        completes, so TTFT observations dry up exactly when they matter;
        the head-of-queue wait keeps rising regardless."""
        if not self.queue:
            return 0.0
        return max(0.0, now - min(r.arrival_s for r in self.queue))

    # -- decode-rate estimate (engine feeds, shedding reads) ----------------

    def observe_prefill(self, tokens: int, seconds: float) -> None:
        if tokens <= 0 or seconds <= 0:
            return
        per = seconds / tokens
        a = self._ewma_alpha
        self.prefill_s_per_token = (
            per if self.prefill_s_per_token == 0.0
            else a * per + (1 - a) * self.prefill_s_per_token)

    def observe_decode(self, seconds: float,
                       tokens_per_slot: float = 1.0) -> None:
        """Feed one decode (or speculative verify) iteration's measured
        cost.  ``tokens_per_slot`` is the mean tokens EMITTED per active
        slot this iteration (1 for plain decode; >1 when speculation
        accepted drafts) — the EWMA tracks seconds per emitted token,
        so deadline feasibility learns the speculative rate instead of
        overestimating by the acceptance factor."""
        if seconds <= 0 or tokens_per_slot <= 0:
            return
        per = seconds / tokens_per_slot
        a = self._ewma_alpha
        self.decode_iter_s = (
            per if self.decode_iter_s == 0.0
            else a * per + (1 - a) * self.decode_iter_s)

    def estimate_completion_s(self, req: Request) -> float:
        """Best-effort time from "admitted now" to the request's LAST
        token under the current rate estimate: one prefill (yields the
        first token) plus one decode iteration per remaining token.
        0.0 on a cold engine (no observations yet) — optimistic by
        design, so shedding only ever acts on measured slowness."""
        prefill = self.prefill_s_per_token * req.padded_prompt_len(
            self.block_size)
        return prefill + max(req.max_new_tokens - 1, 0) * self.decode_iter_s

    # -- shedding -----------------------------------------------------------

    def _shed(self, req: Request, reason: str) -> str:
        req.status = "shed"
        req.shed_reason = reason
        if req.prefix_blocks:
            # a queued request shed after its submit-time prefix match
            # must return its pins — shed is terminal
            self.allocator.free(req.prefix_blocks)
            req.prefix_blocks = None
        if self.on_shed is not None:
            self.on_shed(req, reason)
        return f"shed_{reason}"

    def _deadline_verdict(self, req: Request, now: float) -> Optional[str]:
        """None = keep; otherwise the shed reason.  Called BEFORE any
        prefill work — the cheap moment to drop a hopeless request."""
        at = req.deadline_at_s()
        if at is None:
            return None
        if now >= at:
            return "deadline_expired"
        if now + self.estimate_completion_s(req) > at:
            return "deadline_unmeetable"
        return None

    # -- admission ----------------------------------------------------------

    def _blocks_needed(self, req: Request) -> int:
        """Worst-case block reservation: the padded prompt region plus
        every decode write (positions ``p .. p+max_new-2``; the final
        emitted token is never written back).  EOS may finish earlier —
        the reservation is the no-surprise upper bound that makes
        mid-flight pool exhaustion impossible."""
        p_pad = req.padded_prompt_len(self.block_size)
        rows = max(p_pad, req.prompt_len + req.max_new_tokens - 1)
        return blocks_for(rows, self.block_size)

    def _fresh_blocks_needed(self, req: Request) -> int:
        """Blocks the allocator must actually hand out: the worst-case
        table minus the matched prefix blocks the request already holds
        (acquired at submit — resident by construction, so the
        admission walk must not count them against the pool)."""
        held = len(req.prefix_blocks) if req.prefix_blocks else 0
        return self._blocks_needed(req) - held

    def submit(self, req: Request, now: float) -> str:
        """Admission control at the front door.  Returns the request's
        status: ``queued`` or ``rejected`` (``req.status`` matches, and a
        rejected request carries the reason in ``req.tokens is None`` +
        the return value; the engine counts both)."""
        req.arrival_s = now
        if self.draining:
            req.status = "rejected"
            return "rejected_draining"
        total = req.prompt_len + req.max_new_tokens
        window = self.blocks_per_slot * self.block_size
        limit = min(window, self.max_len) if self.max_len else window
        if req.max_new_tokens < 1 or req.prompt_len < 1:
            req.status = "rejected"
            return "rejected_empty"
        # A deadline the rate estimate already rules out is shed at the
        # front door — the cheapest possible outcome for the request AND
        # the queue (it never occupies an entry another request wants).
        verdict = self._deadline_verdict(req, now)
        if verdict is not None:
            return self._shed(req, verdict)
        # Reject against BOTH ceilings: the per-slot window and the whole
        # pool.  A request needing more blocks than the pool holds would
        # otherwise queue forever (nothing in flight can free enough) and
        # head-of-line-block every request behind it — a wedged engine.
        pool_cap = self.allocator.num_blocks - 1
        if (total > limit
                or self._blocks_needed(req) > min(self.blocks_per_slot,
                                                  pool_cap)):
            req.status = "rejected"
            return "rejected_too_long"
        if len(self.queue) >= self.max_queue:
            req.status = "rejected"
            return "rejected_queue_full"
        req.status = "queued"
        self.queue.append(req)
        return "queued"

    def release(self, req: Request) -> None:
        """Return a finished request's slot and blocks to the pool (the
        continuous-batching eviction half; admissions refill the slot on
        the same iteration).  ALSO the one true release path for every
        early exit — cancel, kv-poison eviction, drain timeout — so a
        request's blocks cannot leak no matter how it dies: blocks are
        freed iff ``req.blocks`` is set, and the field is cleared
        atomically with the free (a second release is a no-op, not a
        double free)."""
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        if req.blocks:
            self.allocator.free(req.blocks)
            req.blocks = None
        if req.prefix_blocks:
            # matched-but-never-assigned holds (cancel/drain while
            # queued): exactly one of blocks/prefix_blocks is ever set —
            # _assign consumes prefix_blocks into blocks
            self.allocator.free(req.prefix_blocks)
            req.prefix_blocks = None

    def cancel(self, req: Request, status: str = "cancelled") -> str:
        """Tear a request out wherever it currently lives — queued (drop
        the entry), or running (free slot + every reserved block,
        including blocks a prefill wrote moments ago).  Returns where it
        was found: ``queued`` / ``running`` / ``gone`` (already
        finished or never here — cancel is idempotent)."""
        if req in self.queue:
            self.queue.remove(req)
            self.release(req)       # frees submit-time prefix pins
            req.status = status
            return "queued"
        if req.slot is not None or req.blocks or req.prefix_blocks:
            self.release(req)
            req.status = status
            return "running"
        return "gone"

    def _assign(self, req: Request) -> Tuple[int, Request]:
        slot = self.slots.index(None)
        fresh = self.allocator.allocate(self._fresh_blocks_needed(req))
        # table order: matched shared blocks cover logical blocks
        # 0..k-1 (read-only — decode writes land at pos >= prompt_len,
        # always inside the fresh region), fresh blocks the rest
        req.blocks = list(req.prefix_blocks or []) + fresh
        req.prefix_blocks = None
        req.slot = slot
        req.status = "running"
        req.tokens = []
        self.slots[slot] = req
        return slot, req

    def effective_priority(self, req: Request, now: float) -> int:
        """Declared priority plus one level per ``aging_s`` waited — the
        anti-starvation escalator (aging_s <= 0 disables aging)."""
        if self.aging_s <= 0:
            return req.priority
        return req.priority + int(max(0.0, now - req.arrival_s)
                                  / self.aging_s)

    def admit(self, now: float) -> List[Tuple[int, Request]]:
        """The per-iteration admission decision (see module docstring
        for both policies).  Returns ``(slot, request)`` pairs the engine
        must prefill this iteration."""
        out: List[Tuple[int, Request]] = []
        if self.draining:
            return out
        if self.mode == "static":
            if self.num_active() or not self.queue:
                return out
            full = len(self.queue) >= self.num_slots
            # Same expression as the engine's batch-forming horizon
            # (arrival + wait): ``now - arrival >= wait`` is NOT
            # float-equivalent to ``now >= arrival + wait``, and the
            # mismatch once left a virtual clock parked one ulp short of
            # aging the batch out — forever.
            aged = (now
                    >= self.queue[0].arrival_s + self.static_batch_wait_s)
            if not (full or aged):
                return out
            while self.queue and self.num_active() < self.num_slots:
                req = self.queue[0]
                if not self.allocator.can_allocate(
                        self._fresh_blocks_needed(req)):
                    break
                self.queue.popleft()
                out.append(self._assign(req))
            return out

        # Continuous mode: walk candidates in (effective priority desc,
        # arrival, rid) order — FIFO within a class, aging lifts
        # long-waiters across classes.  The walk STOPS at the first
        # candidate that doesn't fit (budget or blocks): skipping past a
        # block-starved request would let a stream of small requests
        # starve a big one forever, so the head keeps its claim on the
        # next freed blocks.  Deadline sheds happen IN the walk, before
        # the fit checks — a hopeless request must not block the line.
        budget = self.prefill_token_budget
        order = sorted(self.queue,
                       key=lambda r: (-self.effective_priority(r, now),
                                      r.arrival_s, r.rid))
        for req in order:
            if self.num_active() >= self.num_slots:
                break
            verdict = self._deadline_verdict(req, now)
            if verdict is not None:
                self.queue.remove(req)
                self._shed(req, verdict)
                continue
            p_pad = req.padded_prompt_len(self.block_size)
            if out and p_pad > budget:
                break                   # phase separation: drip prefills
            if not self.allocator.can_allocate(
                    self._fresh_blocks_needed(req)):
                break                   # blocks come back as decodes finish
            self.queue.remove(req)
            out.append(self._assign(req))
            budget -= p_pad
        return out
