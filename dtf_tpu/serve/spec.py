"""Self-drafting speculation: n-gram prompt-lookup draft proposals.

The cheapest possible draft model — the request's OWN context.  Decode
streams are heavily self-similar (system prompts, quoted spans, the
repetition loops small greedy models fall into), so the most recent
earlier occurrence of the current suffix n-gram is a strong predictor
of what comes next ("prompt lookup decoding").  The drafter proposes
the ``k`` tokens that followed that occurrence; the target model
verifies the whole window in ONE paged step
(``serve/decode.py build_verify_fn``) and the engine emits the longest
prefix of drafts the model itself would have chosen, plus the bonus
token at the first mismatch.

Correctness does not depend on the drafter AT ALL: every emitted token
is the verify step's own (greedy or (seed, rid, count)-keyed) choice,
so a terrible drafter costs only wasted verify lanes, never a wrong
token — the spec-decode token-identity pin in tests/test_serve.py is a
pin on the verify step, and this module only moves the acceptance rate.

Deliberately jax-free (numpy over the host-side token lists) so drafts
cost microseconds against the multi-millisecond step they amortize.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

#: Longest suffix n-gram tried first; shorter suffixes are fallbacks.
#: 3..1 is the standard prompt-lookup ladder — longer matches are rarer
#: but much more predictive.
MAX_NGRAM = 3


def propose_drafts(context: Sequence[int], k: int,
                   max_ngram: int = MAX_NGRAM) -> List[int]:
    """Up to ``k`` draft tokens for ``context`` (prompt + generated so
    far, most recent last), or ``[]`` when no suffix n-gram of length
    ``max_ngram..1`` recurs earlier in the context.

    Matching prefers the longest suffix, and within a suffix length the
    MOST RECENT earlier occurrence (recency beats frequency for decode
    streams).  Deterministic: same context, same drafts — the
    speculative engine's batch log stays a pure function of the trace.
    """
    if k <= 0:
        return []
    ctx = np.asarray(context, dtype=np.int64).reshape(-1)
    n = ctx.shape[0]
    for g in range(min(max_ngram, n - 1), 0, -1):
        suffix = ctx[n - g:]
        # one vectorized sliding-window compare per suffix length (the
        # drafter runs per slot per engine iteration — a Python
        # per-position scan here would cost milliseconds on long
        # contexts, rivaling the device step it amortizes): candidate
        # start positions are windows [i, i+g) strictly before the
        # suffix itself, most recent wins
        windows = np.lib.stride_tricks.sliding_window_view(
            ctx[:n - 1], g)
        hits = np.nonzero((windows == suffix).all(axis=1))[0]
        if hits.size:
            i = int(hits[-1])
            cont = ctx[i + g:i + g + k]
            if cont.size:
                return [int(t) for t in cont]
    return []
