"""Serving engine: continuous batching over the decode path.

The inference half of the stack used to be all parts, no engine —
``ops/decode_kernel.py`` and ``nn/sampling.py`` could time fused decode
but nothing accepted *requests*.  This package is the engine:

* :mod:`.paged_kv` — paged/blocked KV cache: one shared HBM pool of
  fixed-size blocks, a deterministic free-list allocator, per-request
  block tables (streams of different lengths share the pool instead of
  each padding to max_len);
* :mod:`.scheduler` — admission control + continuous (in-flight)
  batching with prefill/decode phase separation, plus the
  static-batching baseline policy and the wall/virtual clocks;
* :mod:`.decode` — the jitted paged prefill/decode steps (one compile
  per geometry; token-identical to the contiguous cache path — pinned
  by parity tests, single-device and TP mesh);
* :mod:`.engine` — :class:`ServingEngine`: streaming per-request
  output, TTFT/TPOT histograms into the telemetry spine, goodput books,
  deadline-aware shedding, graceful drain with replay checkpointing;
* :mod:`.brownout` — :class:`BrownoutController`: hysteretic overload
  control (degrade -> reject-low-priority -> reject-all) off a smoothed
  p99 TTFT vs the SLO budget;
* :mod:`.frontend` — the line-oriented JSON-over-TCP front end
  (per-connection timeouts, malformed-request rejection, disconnects
  free KV blocks immediately).

``python -m dtf_tpu.serve`` runs a server process (supervisor restarts,
health beats, ``--listen`` for the TCP front end, SIGTERM drains
gracefully); ``python -m dtf_tpu.bench.serve_load`` is the closed-loop
load generator (p50/p99 TTFT/TPOT vs offered QPS, the static-batching
A/B, and the ``--chaos`` overload/brownout gate).
"""

from dtf_tpu.serve.brownout import BrownoutController
from dtf_tpu.serve.engine import ServingEngine
from dtf_tpu.serve.paged_kv import (BlockAllocator, KVPool, PoolExhausted,
                                    blocks_for, contiguous_table)
from dtf_tpu.serve.scheduler import (Request, Scheduler, VirtualClock,
                                     WallClock)

__all__ = [
    "BlockAllocator", "BrownoutController", "KVPool", "PoolExhausted",
    "Request", "Scheduler", "ServingEngine", "VirtualClock", "WallClock",
    "blocks_for", "contiguous_table",
]
