"""dtf_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of the TF1
parameter-server demo ``KimJeongChul/distributed-tensorflow`` (reference at
``/root/reference``):

* cluster bootstrap & rank dispatch (ref: ``tf.train.ClusterSpec`` /
  ``tf.train.Server``, tf_distributed.py:9-18) -> :mod:`dtf_tpu.cluster` over
  ``jax.distributed`` + ``jax.sharding.Mesh``;
* placement / replication policy (ref: ``tf.train.replica_device_setter``,
  tf_distributed.py:34-36) -> :mod:`dtf_tpu.parallel` NamedSharding rules;
* async parameter-server SGD (ref: tf_distributed.py:73-76) -> synchronous
  data parallelism with ``lax.psum`` gradient all-reduce over ICI;
* workloads: MNIST MLP (tf_distributed.py:39-89), the 1000x1000 matmul
  benchmark (tf_distributed_1000Matrix.py:42-48), plus ResNet-50/CIFAR-10 and
  BERT-base per BASELINE.md;
* driver loop, eval and the reference's console log contract
  (tf_distributed.py:100-128) -> :mod:`dtf_tpu.train`.

The reference's capabilities are re-expressed TPU-first, not translated.
"""

from dtf_tpu.version import __version__
from dtf_tpu import cluster, config
from dtf_tpu.parallel import mesh, sharding

__all__ = ["__version__", "cluster", "config", "mesh", "sharding"]
