"""dtf_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of the TF1
parameter-server demo ``KimJeongChul/distributed-tensorflow`` (reference at
``/root/reference``):

* cluster bootstrap & rank dispatch (ref: ``tf.train.ClusterSpec`` /
  ``tf.train.Server``, tf_distributed.py:9-18) -> :mod:`dtf_tpu.cluster` over
  ``jax.distributed`` + ``jax.sharding.Mesh``;
* placement / replication policy (ref: ``tf.train.replica_device_setter``,
  tf_distributed.py:34-36) -> :mod:`dtf_tpu.parallel` NamedSharding rules;
* async parameter-server SGD (ref: tf_distributed.py:73-76) -> synchronous
  data parallelism with ``lax.psum`` gradient all-reduce over ICI;
* workloads: MNIST MLP (tf_distributed.py:39-89), the 1000x1000 matmul
  benchmark (tf_distributed_1000Matrix.py:42-48), plus ResNet-50/CIFAR-10,
  BERT-base MLM, GPT (LLaMA-style options), and a T5-style encoder-decoder
  per BASELINE.md;
* driver loop, eval and the reference's console log contract
  (tf_distributed.py:100-128) -> :mod:`dtf_tpu.train`.

The reference's capabilities are re-expressed TPU-first, not translated.

Typical use::

    import dtf_tpu

    cluster = dtf_tpu.bootstrap()          # mesh from flags/defaults
    opt = dtf_tpu.optim.adam(1e-3)
    state = dtf_tpu.init_state(model, opt, seed=0, mesh=cluster.mesh)
    step = dtf_tpu.make_train_step(model.loss, opt, cluster.mesh)
    state, metrics = step(state, dtf_tpu.put_global_batch(cluster.mesh, b),
                          rng)
"""

from dtf_tpu.version import __version__
from dtf_tpu import cluster, config, optim, telemetry
from dtf_tpu.cluster import Cluster, bootstrap
from dtf_tpu.config import ClusterConfig, TrainConfig, parse_args
from dtf_tpu.parallel import mesh, sharding
from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.train.trainer import (Trainer, init_state, make_eval_fn,
                                   make_train_step, put_global_batch,
                                   put_process_batch)

__all__ = [
    "__version__", "cluster", "config", "mesh", "sharding", "optim",
    "telemetry",
    "Cluster", "bootstrap", "ClusterConfig", "TrainConfig", "parse_args",
    "make_mesh", "Trainer", "init_state", "make_eval_fn", "make_train_step",
    "put_global_batch", "put_process_batch",
]
