"""Transformer train-step time breakdown — where the non-MFU time goes.

The reference's only benchmark apparatus was a wall-clock print around
``sess.run`` (`/root/reference/tf_distributed.py:116-122`); it could never
say WHERE a step's time went.  This module ladder-times (time_linfit — the
only honest method through the axon relay, see BASELINE.md round 3) each
component of a transformer layer at the exact benchmark shapes, so MFU
claims decompose into per-kernel facts:

* the three matmul families (qkv/attn-proj, fc1, fc2) in isolation,
* LayerNorm / GELU elementwise passes,
* flash attention forward and forward+backward,
* one full block forward, forward+backward, and the complete train step.

Each row reports achieved TFLOP/s (for FLOP-carrying ops) or GB/s (for
bandwidth-bound ops) against the device's roofline, plus the implied
fraction of a layer's step time.  Usage::

    python -m dtf_tpu.bench.breakdown --family bert   # B=64 T=512 (base)
    python -m dtf_tpu.bench.breakdown --family gpt    # B=32 T=1024 (small)
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.bench.matmul import peak_flops_per_chip
from dtf_tpu.utils.timing import time_linfit

# chain lengths for the marginal-timing fit; long enough that per-iter
# device time dominates the fit range against ~100 ms relay jitter.
# Every ladder point is a separate XLA compile (~20-40 s at these
# shapes), so the ladder stays short: 3 points x ~10 rows.
LADDER = (2, 8, 24)


def _chain(fn, n, x0, tag="?"):
    """n dependent applications of fn inside one jit (no CSE/hoist).
    The jit is wrapped by the cost observatory so each ladder point's
    compile lands as a bench/breakdown CostCard (geometry = the row's
    op tag + chain length + operand shape — the tag is what keeps two
    different ops over the same operand from folding into one card);
    capture happens at the compile the first call pays anyway, so the
    timed region is unchanged."""
    from dtf_tpu.telemetry import costobs

    @jax.jit
    def run(x):
        def body(c, _):
            return fn(c), None
        out, _ = lax.scan(body, x, None, length=n)
        return out

    inst = costobs.instrument(
        run, "bench/breakdown",
        (tag, n, tuple(jnp.shape(x0)), str(getattr(x0, "dtype", "?"))))
    return lambda: inst(x0)


def _time(fn, x0, reps=4, tag="?"):
    fit = time_linfit(lambda n: _chain(fn, n, x0, tag), LADDER, reps=reps)
    return fit.per_iter_s


@dataclasses.dataclass
class Row:
    name: str
    seconds: float
    flops: float = 0.0          # per application
    bytes_moved: float = 0.0    # per application (HBM, approximate)

    def line(self, peak: Optional[float]) -> str:
        cols = [f"{self.name:<34}", f"{self.seconds * 1e6:9.0f} us"]
        if self.flops:
            tf = self.flops / self.seconds / 1e12
            cols.append(f"{tf:7.1f} TF/s")
            if peak:
                cols.append(f"{tf * 1e12 / peak * 100:5.1f}% peak")
        elif self.bytes_moved:
            cols.append(f"{self.bytes_moved / self.seconds / 1e9:7.0f} GB/s")
        return "  ".join(cols)


def _attn_rows(rows, b, t, h, hd, bq, bk, causal, tag):
    """Time flash fwd and fwd+bwd at (B, h, T, hd) with the given block
    sizes and append two Rows.  ONE home for the non-obvious accounting —
    the causal block-skip discount ((nb+1)/2nb of the dense FLOPs) and
    the 3.5x fwd+bwd multiplier (bwd recomputes s/p once and computes
    dq+dk+dv in one fused kernel) — shared by breakdown() and
    attn_sweep() so the two cannot drift.  Block sizes are resolved via
    _block_sizes first so tags always name what actually ran."""
    from dtf_tpu.ops.flash_attention import flash_attention, _block_sizes

    mk = lambda k, shape: jax.random.normal(jax.random.key(k), shape,
                                            jnp.bfloat16)
    rbq, rbk = _block_sizes(t, bq, bk)
    q = mk(6, (b, h, t, hd))
    flops = 4.0 * b * h * t * t * hd               # qk + pv
    if causal:
        # the kernel skips blocks above the diagonal: of nb^2 block pairs
        # only nb(nb+1)/2 execute (diagonal blocks half-masked but still
        # computed, so credit them fully).  The credit uses the REFERENCE
        # 512 tiling's block count for every row, NOT the row's own
        # tiling: finer tiles execute fewer wasted above-diagonal FLOPs,
        # and crediting each tiling its own executed count would make
        # TF/s incomparable across the sweep (a faster config could
        # print a lower TF/s).  Fixed credit = fixed useful-work proxy;
        # rows then rank identically by TF/s and by seconds.
        nb = t // _block_sizes(t, 512, 512)[0]
        flops *= (nb + 1) / (2 * nb)
    fa = functools.partial(flash_attention, causal=causal,
                           block_q=rbq, block_k=rbk)
    full_tag = f"{tag} bq{rbq} bk{rbk}"
    s = _time(lambda x: fa(x, q, q).astype(jnp.bfloat16), q,
              tag=f"fwd {full_tag}")
    rows.append(Row(f"fwd {full_tag}", s, flops=flops))

    def fa_grad(x):
        g = jax.grad(lambda y: jnp.sum(fa(y, q, q) * 1e-6))(x)
        return g.astype(jnp.bfloat16)
    s = _time(fa_grad, q, tag=f"fwd+bwd {full_tag}")
    rows.append(Row(f"fwd+bwd {full_tag}", s, flops=3.5 * flops))
    return flops


def breakdown(family: str = "bert", batch: Optional[int] = None,
              seq: Optional[int] = None) -> list[Row]:
    if family == "bert":
        b, t, d, f, h = batch or 64, seq or 512, 768, 3072, 12
        causal = False
    else:
        b, t, d, f, h = batch or 32, seq or 1024, 768, 3072, 12
        causal = True
    bt = b * t
    key = jax.random.key(0)
    mk = lambda k, shape: jax.random.normal(jax.random.key(k), shape,
                                            jnp.bfloat16)
    rows: list[Row] = []

    # --- isolated matmuls at the layer's shapes ----------------------
    for name, (m, k_, n) in [("matmul qkv (BT,D)x(D,3D)", (bt, d, 3 * d)),
                             ("matmul fc1 (BT,D)x(D,F)", (bt, d, f))]:
        w = mk(1, (k_, n))
        # chain through a slice so output feeds the next input
        def mm(x, w=w, k_=k_):
            y = jnp.dot(x, w, preferred_element_type=jnp.float32)
            return y[:, :k_].astype(jnp.bfloat16)
        s = _time(mm, mk(2, (m, k_)), tag=name)
        rows.append(Row(name, s, flops=2.0 * m * k_ * n))
    # fc2 shrinks (BT,F)->(BT,D), so it cannot chain alone; time the
    # full matmul-only MLP pair (fc1 -> gelu -> fc2), the shape that a
    # fused kernel would have to beat.
    w1, w2 = mk(12, (d, f)), mk(13, (f, d))
    def mlp(x):
        u = jax.nn.gelu(jnp.dot(x, w1, preferred_element_type=jnp.float32))
        return jnp.dot(u.astype(jnp.bfloat16), w2,
                       preferred_element_type=jnp.float32).astype(jnp.bfloat16)
    s = _time(mlp, mk(14, (bt, d)), tag="mlp pair fc1+gelu+fc2")
    rows.append(Row("mlp pair fc1+gelu+fc2", s, flops=4.0 * bt * d * f))

    # --- elementwise / normalization ---------------------------------
    from dtf_tpu.nn.layers import LayerNorm
    ln = LayerNorm(d)
    lnp = ln.init(jax.random.key(3))
    s = _time(lambda x: ln.apply(lnp, x), mk(4, (b, t, d)),
              tag="layernorm")
    rows.append(Row("layernorm (B,T,D)", s, bytes_moved=2.0 * bt * d * 2))
    s = _time(lambda x: jax.nn.gelu(x), mk(5, (b, t, f)), tag="gelu")
    rows.append(Row("gelu (B,T,F)", s, bytes_moved=2.0 * bt * f * 2))

    # --- attention (shared accounting: _attn_rows) --------------------
    hd = d // h
    attn_flops = _attn_rows(rows, b, t, h, hd, 512, 512, causal,
                            "flash attention")

    # --- one whole block: fwd, then fwd+bwd --------------------------
    from dtf_tpu.models.gpt import GPTBlock, GPTConfig
    cfg = GPTConfig(dim=d, num_heads=h, mlp_dim=f, max_len=t,
                    dtype=jnp.bfloat16, vocab_size=1024)
    block = GPTBlock(cfg)
    bp = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), block.init(jax.random.key(7)))
    # 6·p_layer·(per-token) convention: params ≈ 12 D² per layer
    p_layer = sum(x.size for x in jax.tree_util.tree_leaves(bp))
    blk_fwd_flops = 2.0 * p_layer * bt + attn_flops
    s = _time(lambda x: block.apply(bp, x), mk(8, (b, t, d)),
              tag="block fwd")
    rows.append(Row("block fwd", s, flops=blk_fwd_flops))

    def blk_grad(x):
        g = jax.grad(lambda y: jnp.sum(block.apply(bp, y)
                                       .astype(jnp.float32)) * 1e-6)(x)
        return g.astype(jnp.bfloat16)
    s = _time(blk_grad, mk(9, (b, t, d)), tag="block fwd+bwd x-grad")
    # grad wrt x alone never computes the dW matmuls: dx costs ~1x the
    # forward matmul FLOPs, so the executed total is ~2x fwd, not 3x.
    rows.append(Row("block fwd+bwd (x-grad only)", s,
                    flops=2.0 * blk_fwd_flops))

    def _fold_w_grads(gp, gx):
        """Mix every weight-grad leaf into the timed output: a discarded
        gp is dead code and XLA deletes the dW matmuls the row exists to
        measure (verified in HLO: 3 dots -> 2 when gp is dropped)."""
        acc = sum(jnp.sum(l.astype(jnp.float32))
                  for l in jax.tree_util.tree_leaves(gp))
        return (gx + acc * 1e-20).astype(jnp.bfloat16)

    def blk_grad_w(x):
        gp, gx = jax.grad(
            lambda pp, y: jnp.sum(block.apply(pp, y)
                                  .astype(jnp.float32)) * 1e-6,
            argnums=(0, 1))(bp, x)
        return _fold_w_grads(gp, gx)
    s = _time(blk_grad_w, mk(10, (b, t, d)), tag="block fwd+bwd x+w")
    rows.append(Row("block fwd+bwd (x+w grads)", s,
                    flops=3.0 * blk_fwd_flops))

    def blk_grad_remat(x):
        fn = jax.checkpoint(lambda y: block.apply(bp, y))
        gx = jax.grad(lambda y: jnp.sum(fn(y).astype(jnp.float32))
                      * 1e-6)(x)
        return gx.astype(jnp.bfloat16)
    s = _time(blk_grad_remat, mk(11, (b, t, d)), tag="block remat")
    # x-grad only (see above) + one full recompute: ~3x fwd executed.
    rows.append(Row("block fwd+bwd x-grad, full remat", s,
                    flops=3.0 * blk_fwd_flops))

    # --- the same block through the fused megakernels ----------------
    # (ops/block_kernel.py; same params tree, apply() routes to the
    # kernels) — the isolated fused-vs-unfused comparison the round-5
    # MFU push rests on, free of workload noise.  SKIP (never crash: on
    # chip the rows above are already-spent minutes) when T is outside
    # the fused kernels' scope.
    try:
        from dtf_tpu.ops.block_kernel import _check_block_args, _q_block
        _check_block_args(t, d, h, None)
        _q_block(t)
    except ValueError as exc:
        print(f"# fused-block rows skipped: {exc}")
        return rows
    cfg_f = GPTConfig(dim=d, num_heads=h, mlp_dim=f, max_len=t,
                      dtype=jnp.bfloat16, vocab_size=1024,
                      fused_block=True)
    block_f = GPTBlock(cfg_f)
    s = _time(lambda x: block_f.apply(bp, x), mk(8, (b, t, d)),
              tag="block fwd fused")
    rows.append(Row("block fwd (fused kernels)", s, flops=blk_fwd_flops))

    def blk_f_grad_w(x):
        gp, gx = jax.grad(
            lambda pp, y: jnp.sum(block_f.apply(pp, y)
                                  .astype(jnp.float32)) * 1e-6,
            argnums=(0, 1))(bp, x)
        return _fold_w_grads(gp, gx)
    s = _time(blk_f_grad_w, mk(10, (b, t, d)),
              tag="block fwd+bwd x+w fused")
    rows.append(Row("block fwd+bwd x+w grads (fused kernels)", s,
                    flops=3.0 * blk_fwd_flops))

    return rows


def attn_sweep(family: str = "bert", batch: Optional[int] = None,
               seq: Optional[int] = None,
               blocks=(128, 256, 512)) -> list[Row]:
    """Attention-kernel efficiency sweep for the MFU close-or-retire
    question (r3 VERDICT #2): is the flash kernel at its SHAPE ceiling?

    Two experiments at the benchmark shapes:

    * **block-size sweep**: fwd and fwd+bwd at every (block_q, block_k)
      in ``blocks``² — if no config beats the 512/512 default, tiling is
      not the bottleneck;
    * **Dh ablation**: (B, 12, T, 64) vs (B, 6, T, 128) — SAME total
      FLOPs (H·Dh = 768 fixed), so if TF/s ~doubles at Dh=128 the gap is
      shape-imposed (Dh=64 fills half the 128-lane MXU contraction on
      the q·kᵀ matmul) and the kernel is at its ceiling; if it does not,
      the kernel is leaving performance on the table.

    The shape ceiling to compare against is ~peak/2 at Dh=64.
    """
    from dtf_tpu.ops.flash_attention import _block_sizes

    if family == "bert":
        b, t, causal = batch or 64, seq or 512, False
    else:
        b, t, causal = batch or 32, seq or 1024, True
    rows: list[Row] = []

    seen = set()
    for bq in blocks:
        for bk in blocks:
            # _block_sizes clamps to divisors of T; dedupe combos that
            # resolve identically (at T=128 the whole grid collapses).
            resolved = _block_sizes(t, bq, bk)
            if resolved in seen:
                continue
            seen.add(resolved)
            _attn_rows(rows, b, t, 12, 64, *resolved, causal, "H12 Dh64")
    # Dh ablation at the default tiling: same FLOPs, double the MXU
    # contraction depth.
    _attn_rows(rows, b, t, 6, 128, 512, 512, causal,
               "H6 Dh128 (same FLOPs)")
    return rows


def grad_sync_ab(steps: int = 8, batch: int = 512,
                 bucket_mb: float = 0.1) -> dict:
    """Dense vs zero1 vs zero1_overlap A/B on the MNIST MLP workload shapes
    (ISSUE 5 acceptance): per-strategy full-step time, the ISOLATED
    gradient-sync+update time (its own jitted shard_map program, timed
    under the ``comm/grad_sync`` span and exported as ``comm/grad_sync_s``),
    measured per-device optimizer-state bytes, per-device wire bytes, and
    — where the backend reports memory_stats (TPU; CPU returns null) —
    LIVE bytes in use right after state allocation (each strategy runs in
    its own scope so the reading is per-strategy, not a process-lifetime
    peak).

    Wire-dtype dimension (ISSUE 6 acceptance): ``wire_dtypes`` re-runs
    zero1 under each ``--grad_comm_dtype`` (f32 / bf16 / int8) at the
    SAME bucket layout class, reporting per-dtype step time, sync time,
    gradient wire bytes (int8 counts its per-block scales) and the
    measured quantization error; ``int8_vs_bf16_wire_ratio`` is the
    headline (~0.51: 1 payload byte + 1.6% scales vs 2 bytes).  Returns
    the JSON-ready comparison dict."""
    import time

    import numpy as np

    from dtf_tpu import optim
    from dtf_tpu import telemetry as tel
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.parallel.collectives import shard_map_fn
    from dtf_tpu.parallel.grad_sync import (GradSyncEngine, STRATEGIES,
                                            WIRE_DTYPES,
                                            opt_state_bytes_per_device)
    from dtf_tpu.parallel.mesh import local_mesh
    from dtf_tpu.train.trainer import (init_state, make_train_step,
                                       put_global_batch)
    from dtf_tpu.utils.timing import block
    from jax.sharding import PartitionSpec as P

    mesh = local_mesh("data=-1")
    model = MnistMLP(init_scale="fan_in")
    opt = optim.adam(1e-3)
    rng = np.random.default_rng(0)
    host_batch = (rng.random((batch, 784)).astype(np.float32),
                  np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])

    def make_sync_only(eng):
        """The sync+update REGION as its own program, so the A/B can time
        it free of forward/backward noise."""
        if eng is None:
            def f(grads, opt_state, params):
                g = jax.tree_util.tree_map(
                    lambda v: lax.pmean(v, "data"), grads)
                updates, new_opt = opt.update(g, opt_state, params)
                return optim.apply_updates(params, updates), new_opt
            spec = P()
        else:
            def f(grads, opt_state, params):
                p, o, _ = eng.sync_and_update(grads, opt_state, params)
                return p, o
            spec = eng.opt_state_spec
        return jax.jit(shard_map_fn(
            f, mesh=mesh, in_specs=(P(), spec, P()),
            out_specs=(P(), spec)))

    out = {"workload": "mnist_mlp_784_100_10", "backend": jax.default_backend(),
           "data_axis": int(mesh.shape["data"]), "global_batch": batch,
           "steps_timed": steps, "bucket_mb": bucket_mb, "strategies": {},
           "wire_dtypes": {}}
    if out["data_axis"] == 1:
        # A 1-device mesh degenerates every strategy to the same math:
        # zero1's "shard" is the whole vector plus padding, so the state
        # bytes come out slightly ABOVE dense — the opposite of the
        # (N-1)/N comparison this A/B exists to show.  Emit the JSON
        # (step-time rows are still valid) but flag it loudly.
        import sys as _sys
        out["warning"] = ("data axis is 1 — the zero1 memory comparison "
                          "is degenerate; run on a multi-device mesh "
                          "(e.g. --simulated_devices 8 on CPU)")
        print(f"# WARNING: {out['warning']}", file=_sys.stderr)
    def run_strategy(strat, comm_dtype=None):
        """One (strategy, wire dtype) cell, in its own scope: the
        previous cell's device arrays are refcount-freed before this one
        allocates, so the LIVE bytes_in_use reading below reflects THIS
        cell's footprint (the process-lifetime peak_bytes_in_use is
        monotone across cells sharing the process and could never show
        zero1's savings)."""
        eng = None
        accum = 1
        if strat != "dense":
            eng = GradSyncEngine(strat, opt, mesh, bucket_mb=bucket_mb,
                                 comm_dtype=comm_dtype).prepare(
                jax.eval_shape(model.init, jax.random.key(1)))
            if strat == "zero1_overlap":
                accum = 2      # the overlap schedule needs microbatches
        state = init_state(model, opt, seed=1, mesh=mesh, grad_sync=eng)
        hbm_after_init = (jax.local_devices()[0].memory_stats()
                          or {}).get("bytes_in_use")
        step = make_train_step(model.loss, opt, mesh, mode="explicit",
                               donate=False, grad_sync=eng,
                               grad_accum=accum,
                               grad_comm_dtype=(comm_dtype
                                                if eng is None else None))
        b = put_global_batch(mesh, host_batch)
        state, m = step(state, b, jax.random.key(0))      # compile
        block(state)
        t0 = time.perf_counter()
        for i in range(steps):
            state, m = step(state, b, jax.random.key(i + 1))
        block(state)
        step_ms = (time.perf_counter() - t0) / steps * 1e3

        # isolated sync+update: same replicated grads tree per strategy
        grads = jax.tree_util.tree_map(
            lambda p: (p * 1e-3).astype(jnp.float32), state["params"])
        sync_fn = make_sync_only(eng)
        p2, o2 = sync_fn(grads, state["opt_state"], state["params"])
        block(p2)
        with tel.span("comm/grad_sync", strategy=strat):
            t0 = time.perf_counter()
            for _ in range(steps):
                p2, o2 = sync_fn(grads, o2, p2)
            block(p2)
            sync_s = (time.perf_counter() - t0) / steps
        tel.gauge("comm/grad_sync_s").set(sync_s)

        if eng is not None:
            stats = eng.comm_stats(accum)
        else:
            from dtf_tpu.parallel.grad_sync import (comm_dtype_of,
                                                    wire_bytes_per_elem)
            wire = float(sum(
                np.prod(l.shape)
                for l in jax.tree_util.tree_leaves(state["params"]))
                * wire_bytes_per_elem(comm_dtype_of(comm_dtype)))
            stats = {"grad_sync_bytes": wire, "wire_bytes": wire,
                     "bucket_count": 0.0}
        row = {
            "step_ms": round(step_ms, 4),
            "grad_sync_ms": round(sync_s * 1e3, 4),
            "grad_accum": accum,
            "opt_state_bytes_per_device":
                opt_state_bytes_per_device(state["opt_state"]),
            "comm_bytes_per_step": stats["grad_sync_bytes"],
            "wire_bytes_per_step": stats["wire_bytes"],
            "bucket_count": int(stats["bucket_count"]),
            "hbm_bytes_in_use_after_init": hbm_after_init,
        }
        if "quant_error" in m:
            row["quant_error_rms"] = float(m["quant_error"])
        return row

    for strat in STRATEGIES:
        out["strategies"][strat] = run_strategy(strat)
    # Wire-dtype dimension: zero1 at every --grad_comm_dtype, equal
    # bucket layout class (the int8 cell's padding quantum grows by
    # QBLOCK, which is exactly what a real int8 run pays).
    out["wire_dtypes"]["f32"] = out["strategies"]["zero1"]
    for dt in WIRE_DTYPES[1:]:
        out["wire_dtypes"][dt] = run_strategy("zero1", comm_dtype=dt)
    d = out["strategies"]
    out["opt_state_drop_ratio"] = round(
        1.0 - (d["zero1"]["opt_state_bytes_per_device"]
               / max(d["dense"]["opt_state_bytes_per_device"], 1.0)), 4)
    w = out["wire_dtypes"]
    out["int8_vs_bf16_wire_ratio"] = round(
        w["int8"]["wire_bytes_per_step"]
        / max(w["bf16"]["wire_bytes_per_step"], 1.0), 4)
    out["int8_vs_f32_wire_ratio"] = round(
        w["int8"]["wire_bytes_per_step"]
        / max(w["f32"]["wire_bytes_per_step"], 1.0), 4)
    return out


#: Pinned plan_ab acceptance knobs (ISSUE 19): the planner's HBM
#: prediction must land within MAX_HBM_PRED_REL_ERR of the compile-time
#: measured peak once a cost card exists, and the planned cell's step
#: time must stay within STEP_TIME_TOL_PCT of the hand-pinned cell.
MAX_HBM_PRED_REL_ERR = 0.05
STEP_TIME_TOL_PCT = 10.0


def plan_ab(steps: int = 8, batch: int = 512,
            bucket_mb: float = 0.1) -> dict:
    """Hand-pinned gradient path vs ``--plan auto`` A/B (ISSUE 19
    acceptance): cell A runs the PR-6 pinned flags (the dense path's
    one-shot ``--grad_comm_dtype int8`` wire, exactly what PR 6
    shipped); cell B lets the planner derive everything.  Reports per-
    cell step time and wire bytes (the planned cell's int8_ring wire
    must ship strictly fewer scatter-leg bytes on a multi-way mesh),
    plus the planner's predicted-vs-measured peak HBM: the step compile
    is captured as a train/step CostCard (compile-time memory analysis,
    available on CPU), the planner re-plans against the card library,
    and the relative prediction error is gated at MAX_HBM_PRED_REL_ERR.
    The JSON lands in PLAN_r*.json rounds and scripts/bench_ledger.py
    folds it as the ``plan`` rig kind."""
    import tempfile
    import time

    import numpy as np

    from dtf_tpu import optim
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.parallel import planner as plan_mod
    from dtf_tpu.parallel.grad_sync import GradSyncEngine
    from dtf_tpu.parallel.mesh import local_mesh
    from dtf_tpu.telemetry import costobs
    from dtf_tpu.train.trainer import (init_state, make_train_step,
                                       put_global_batch)
    from dtf_tpu.utils.timing import block

    mesh = local_mesh("data=-1")
    model = MnistMLP(init_scale="fan_in")
    opt = optim.adam(1e-3)
    rng = np.random.default_rng(0)
    host_batch = (rng.random((batch, 784)).astype(np.float32),
                  np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    n_dev = int(mesh.shape["data"])

    def run_cell(grad_sync, comm_dtype, tag):
        eng = None
        if grad_sync != "dense":
            eng = GradSyncEngine(grad_sync, opt, mesh,
                                 bucket_mb=bucket_mb,
                                 comm_dtype=comm_dtype).prepare(
                jax.eval_shape(model.init, jax.random.key(1)))
        state = init_state(model, opt, seed=1, mesh=mesh, grad_sync=eng)
        step = make_train_step(model.loss, opt, mesh, mode="explicit",
                               donate=False, grad_sync=eng,
                               grad_comm_dtype=(comm_dtype
                                                if eng is None else None))
        b = put_global_batch(mesh, host_batch)
        # AOT capture: the same compile the trainer's warmup observes,
        # giving the cell a compile-time peak-HBM measurement.
        lowered = jax.jit(lambda s, bb, k: step(s, bb, k)).lower(
            state, b, jax.random.key(0)).compile()
        card = costobs.observe(f"plan_ab/{tag}", ("aot", batch), lowered)
        for i in range(3):                                # warm
            state, m = step(state, b, jax.random.key(i))
        block(state)
        # Median of per-step wall times: a single mean over the loop is
        # hostage to one scheduler hiccup on shared CPU rigs, and this
        # number gates step_time_ok.
        t_per = []
        for i in range(steps):
            t0 = time.perf_counter()
            state, m = step(state, b, jax.random.key(i + 3))
            block(state)
            t_per.append(time.perf_counter() - t0)
        step_ms = float(np.median(t_per)) * 1e3

        if eng is not None:
            stats = eng.comm_stats(1)
        else:
            from dtf_tpu.parallel import quantize as qz
            from dtf_tpu.parallel.grad_sync import (comm_dtype_of,
                                                    wire_bytes_per_elem)
            n_elems = int(sum(
                np.prod(l.shape)
                for l in jax.tree_util.tree_leaves(state["params"])))
            resolved = comm_dtype_of(comm_dtype)
            if resolved in ("int8", "int8_ring"):
                flat = -(-n_elems // n_dev) * n_dev
                elems = (qz.ring_wire_elems if resolved == "int8_ring"
                         else qz.wire_elems)
                scatter = float(elems(flat, n_dev)
                                * qz.WIRE_BYTES_PER_ELEM["int8"])
                gather = float(qz.wire_elems(flat, n_dev)
                               * qz.WIRE_BYTES_PER_ELEM["int8"])
                stats = {"grad_sync_bytes": scatter + gather,
                         "wire_bytes": scatter,
                         "hops": (n_dev - 1 if resolved == "int8_ring"
                                  else 1)}
            else:
                wire = float(n_elems) * wire_bytes_per_elem(resolved)
                stats = {"grad_sync_bytes": wire, "wire_bytes": wire,
                         "hops": 1}
        row = {
            "grad_sync": grad_sync,
            "grad_comm_dtype": comm_dtype,
            "step_ms": round(step_ms, 4),
            "wire_bytes_per_step": stats["wire_bytes"],
            "comm_bytes_per_step": stats["grad_sync_bytes"],
            "hops": int(stats.get("hops", 1)),
            "measured_peak_hbm_bytes": card.peak_hbm_bytes,
        }
        if "quant_error" in m:
            row["quant_error_rms"] = float(m["quant_error"])
        return row, card

    out = {"workload": "mnist_mlp_784_100_10",
           "backend": jax.default_backend(),
           "data_axis": n_dev, "global_batch": batch,
           "steps_timed": steps, "bucket_mb": bucket_mb,
           "max_hbm_prediction_rel_err": MAX_HBM_PRED_REL_ERR,
           "step_time_tol_pct": STEP_TIME_TOL_PCT}
    if n_dev == 1:
        import sys as _sys
        out["warning"] = ("data axis is 1 — the ring wire degenerates "
                          "to zero hops; run on a multi-device mesh "
                          "(e.g. --simulated_devices 8 on CPU)")
        print(f"# WARNING: {out['warning']}", file=_sys.stderr)

    # Cell A: the PR-6 hand-pinned gradient path (dense + one-shot int8).
    pinned_row, _ = run_cell("dense", "int8", "pinned")
    out["pinned"] = pinned_row

    # Cell B: --plan auto.  Plan analytically, run the planned knobs,
    # then re-plan against the captured cost card — the measurement-
    # driven pass whose prediction the gate audits.
    plan0 = plan_mod.make_plan(model, mesh, batch_size=batch,
                               optimizer=opt,
                               pinned={"grad_bucket_mb": bucket_mb})
    auto_row, card = run_cell(plan0.grad_sync, plan0.grad_comm_dtype,
                              "plan_auto")
    with tempfile.TemporaryDirectory() as td:
        obs = costobs.get_observatory()
        # expose the captured compile under the trainer's card site so
        # the planner's geometry match finds it
        costobs.observe("train/step", ("aot", batch),
                        _ReplayCompiled(card))
        obs.write_jsonl(td)
        plan1 = plan_mod.make_plan(model, mesh, batch_size=batch,
                                   optimizer=opt, logdir=td,
                                   pinned={"grad_bucket_mb": bucket_mb})
    auto_row["plan"] = plan1.to_doc()
    auto_row["predicted_hbm_bytes_analytic"] = plan0.predicted_hbm_bytes
    auto_row["predicted_hbm_bytes"] = plan1.predicted_hbm_bytes
    measured = auto_row["measured_peak_hbm_bytes"]
    rel = (abs(plan1.predicted_hbm_bytes - measured) / measured
           if measured else None)
    auto_row["hbm_prediction_rel_err"] = rel
    out["plan_auto"] = auto_row

    out["wire_bytes_ratio"] = round(
        auto_row["wire_bytes_per_step"]
        / max(pinned_row["wire_bytes_per_step"], 1.0), 4)
    out["wire_reduction"] = round(1.0 - out["wire_bytes_ratio"], 4)
    out["wire_win"] = (auto_row["wire_bytes_per_step"]
                       < pinned_row["wire_bytes_per_step"])
    out["step_time_ratio"] = round(
        auto_row["step_ms"] / max(pinned_row["step_ms"], 1e-9), 4)
    out["step_time_ok"] = (out["step_time_ratio"]
                           <= 1.0 + STEP_TIME_TOL_PCT / 100.0)
    out["hbm_prediction_ok"] = (rel is not None
                                and rel <= MAX_HBM_PRED_REL_ERR)
    out["ok"] = bool(out["wire_win"] and out["step_time_ok"]
                     and out["hbm_prediction_ok"])
    return out


class _ReplayCompiled:
    """Adapter replaying a captured CostCard through CostObservatory.
    observe() under a different (site, geometry) key: quacks like a
    compiled executable for cost_analysis/memory_analysis only."""

    def __init__(self, card):
        self._card = card

    def cost_analysis(self):
        return {"flops": self._card.flops,
                "bytes accessed": self._card.bytes_accessed}

    def memory_analysis(self):
        card = self._card
        parts = sum(p for p in (card.argument_bytes, card.output_bytes,
                                card.temp_bytes) if p is not None)

        class _M:
            argument_size_in_bytes = card.argument_bytes
            output_size_in_bytes = card.output_bytes
            temp_size_in_bytes = card.temp_bytes
            generated_code_size_in_bytes = card.generated_code_bytes
            # back out the alias so the replayed peak reproduces the
            # card's exactly (parts - alias == card.peak_hbm_bytes)
            alias_size_in_bytes = parts - (card.peak_hbm_bytes or parts)
        return _M()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--family", choices=["bert", "gpt"], default="bert")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--seq", type=int, default=None)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (reliable even when "
                             "a TPU plugin is registered)")
    parser.add_argument("--attn_sweep", action="store_true",
                        help="attention block-size sweep + Dh shape "
                             "ablation instead of the layer breakdown "
                             "(the r4 MFU close-or-retire evidence)")
    parser.add_argument("--grad_sync_ab", action="store_true",
                        help="dense vs zero1 vs zero1_overlap A/B "
                             "(parallel/grad_sync.py): JSON with per-"
                             "strategy step time, isolated sync+update "
                             "time, per-device optimizer-state bytes and "
                             "wire bytes")
    parser.add_argument("--plan_ab", action="store_true",
                        help="hand-pinned flags vs --plan auto A/B "
                             "(parallel/planner.py): JSON with per-cell "
                             "step time + wire bytes, the planned "
                             "int8_ring wire reduction, and the "
                             "planner's predicted-vs-measured peak HBM "
                             "(gated at MAX_HBM_PRED_REL_ERR); rounds "
                             "land in PLAN_r*.json for the ledger")
    parser.add_argument("--ab_steps", type=int, default=8,
                        help="timed steps per strategy in the A/Bs")
    parser.add_argument("--ab_batch", type=int, default=512,
                        help="global batch in the A/Bs")
    parser.add_argument("--simulated_devices", type=int, default=0,
                        help="run on N simulated CPU devices (the "
                             "grad_sync A/B needs a multi-way data axis "
                             "to show the zero1 memory drop)")
    parser.add_argument("--compile_cache", default=None, metavar="DIR",
                        help="persistent XLA compile cache: every ladder "
                             "point is its own 20-40s compile at these "
                             "shapes, so a re-run against the same DIR "
                             "skips straight to the timed region")
    ns = parser.parse_args(argv)
    if ns.cpu:
        jax.config.update("jax_platforms", "cpu")
    if ns.simulated_devices > 0:
        from dtf_tpu.cluster import simulate_cpu_devices
        simulate_cpu_devices(ns.simulated_devices)
    if ns.compile_cache:
        from dtf_tpu.train.compile_cache import enable
        enable(ns.compile_cache)
    if ns.grad_sync_ab:
        import json
        print(json.dumps(grad_sync_ab(steps=ns.ab_steps, batch=ns.ab_batch),
                         indent=1, sort_keys=True))
        return 0
    if ns.plan_ab:
        import json
        doc = plan_ab(steps=ns.ab_steps, batch=ns.ab_batch)
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0 if doc["ok"] else 1
    peak = peak_flops_per_chip()
    if ns.attn_sweep:
        rows = attn_sweep(ns.family, ns.batch, ns.seq)
        print(f"# {ns.family} attention sweep "
              f"(peak {peak / 1e12 if peak else float('nan'):.0f} TF/s "
              f"bf16; Dh=64 shape ceiling ~peak/2)")
    else:
        rows = breakdown(ns.family, ns.batch, ns.seq)
        print(f"# {ns.family} layer breakdown "
              f"(peak {peak / 1e12 if peak else float('nan'):.0f} TF/s bf16)")
    for r in rows:
        print(r.line(peak))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
