from dtf_tpu.bench.matmul import MatmulBenchConfig, run_matmul_bench  # noqa: F401
