"""Closed-loop serving load generator: latency vs offered QPS.

The MLPerf-pods acceptance discipline (PAPERS.md, arxiv 1909.09756)
applied to the serving engine: the gate is **measured latency under
load**, not a ladder slope.  For each offered-QPS point the generator

* draws a seeded Poisson arrival trace with mixed prompt/output lengths
  (the heavy-traffic shape: short chat turns next to long documents),
* drives ONE :class:`~dtf_tpu.serve.engine.ServingEngine` closed-loop —
  requests are submitted as the engine's own clock passes their arrival
  instants, so an overloaded server sees its queue grow exactly as a
  real one would (no open-loop "fire and forget" flattery),
* reports p50/p99 TTFT and TPOT, completed QPS, and — against an SLO
  TTFT budget — **goodput QPS** (completed requests that met the
  budget, per second of makespan).

Running the sweep in ``--mode both`` replays the *same* trace through
the continuous-batching engine and the static-batching baseline
(identical kernels, identical cache — only the admission policy
differs), so the headline number

    sustained goodput QPS at p99 TTFT <= budget,  continuous / static

is an A/B attribution to continuous batching alone, not a claim.

Deterministic CI mode: ``--clock virtual`` swaps wall time for the
seeded VirtualClock cost model, making every percentile reproducible —
the full-suite ``serve`` lane asserts the continuous/static ratio on
the CPU sim with it.  ``--clock wall`` measures the real engine.

    python -m dtf_tpu.bench.serve_load --preset tiny --clock virtual \
        --qps 4,8,16,24 --requests 48 --mode both --json /tmp/serve_ab.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

#: The A/B acceptance bar the full-suite serve lane enforces (ISSUE 7):
#: continuous batching must sustain at least this multiple of the
#: static baseline's goodput QPS at the same p99 TTFT budget.
AB_MIN_RATIO = 1.5

#: Fleet bar (ISSUE 16): an N-replica fleet must deliver at least this
#: multiple of the single-replica goodput QPS on the SAME trace at the
#: pinned operating point (offered load past single-replica capacity,
#: so the single arm's queue waits blow the TTFT SLO while the fleet's
#: N-way concurrency holds it).
FLEET_AB_MIN_RATIO = 1.6

#: Prefix-cache bar (ISSUE 20): with the prefix KV cache on, p50 TTFT
#: on the shared-prefix chatbot trace must improve by at least this
#: factor over the identical cache-off run (suffix-only prefill skips
#: the shared tokens), while p99 strictly improves and every token
#: stream stays bitwise identical.
PREFIX_AB_MIN_RATIO = 1.5


#: qps_profile shapes: multiplicative modulation of the base rate over
#: the trace's expected constant-rate makespan ``span = n/qps``.  Every
#: shape stays within [0.5, 1.5]x (never zero — arrivals always make
#: progress) and every profile REUSES the same unit-rate exponential
#: chain and the same per-request draws, so request CONTENTS are
#: identical across profiles — only arrival instants move.
QPS_PROFILES = ("constant", "ramp", "square", "sine")


def _profile_rate(profile: str, qps: float, t: float,
                  span: float) -> float:
    """Instantaneous arrival rate at trace time ``t``."""
    if profile == "constant" or span <= 0.0:
        return qps
    if profile == "ramp":
        # 0.5x -> 1.5x linearly over the span, held at 1.5x past it
        return qps * (0.5 + min(t / span, 1.0))
    if profile == "square":
        # oscillating load: 1.5x / 0.5x alternating, period span/4
        level = int(t // (span / 8.0)) % 2
        return qps * (1.5 if level == 0 else 0.5)
    if profile == "sine":
        # two full cycles over the span, 1.0x mean
        import math
        return qps * (1.0 + 0.5 * math.sin(4.0 * math.pi * t / span))
    raise ValueError(f"unknown qps_profile {profile!r} "
                     f"(choices: {QPS_PROFILES})")


def poisson_trace(*, seed: int, n_requests: int, qps: float,
                  prompt_lens: List[int], output_lens: List[int],
                  vocab_size: int, temperature: float = 0.0,
                  deadline_ms: Optional[float] = None,
                  priorities: Optional[List[int]] = None,
                  qps_profile: str = "constant",
                  ) -> List[Tuple[float, dict]]:
    """Seeded Poisson arrivals with lengths drawn uniformly from the
    mixed pools.  The arrival process is a UNIT-RATE exponential chain
    scaled by ``1/qps``: every sweep point (and both modes of the A/B)
    replays the same requests with the same relative burst structure,
    only faster — so the latency-vs-QPS curve is a monotone load
    experiment, not per-point trace lottery.

    ``deadline_ms`` attaches one completion deadline to every request;
    ``priorities`` is a pool each request's priority class is drawn
    from (uniform, seeded — drawn LAST so traces with the default
    single-class pool keep the exact token streams of older traces).

    ``qps_profile`` shapes the arrival RATE over time (inhomogeneous
    Poisson, rate held constant across each inter-arrival gap): the rng
    draw order is untouched, so every profile serves the exact same
    request contents — an adversarial-load A/B moves only WHEN requests
    land, never WHAT they are."""
    if qps_profile not in QPS_PROFILES:
        raise ValueError(f"unknown qps_profile {qps_profile!r} "
                         f"(choices: {QPS_PROFILES})")
    rng = np.random.default_rng(seed)
    trace: List[Tuple[float, dict]] = []
    span = n_requests / qps     # the profile's time base
    t = 0.0
    for rid in range(n_requests):
        t += (float(rng.exponential(1.0))
              / _profile_rate(qps_profile, qps, t, span))
        p = int(rng.choice(prompt_lens))
        kw = {
            "rid": rid,
            "prompt": rng.integers(0, vocab_size, (p,)).astype(np.int32),
            "max_new_tokens": int(rng.choice(output_lens)),
            "temperature": temperature,
        }
        if deadline_ms is not None:
            kw["deadline_ms"] = float(deadline_ms)
        if priorities and len(priorities) > 1:
            kw["priority"] = int(rng.choice(priorities))
        elif priorities:
            kw["priority"] = int(priorities[0])
        trace.append((t, kw))
    return trace


def shared_prefix_trace(*, seed: int, n_requests: int, qps: float,
                        n_prefixes: int, prefix_len: int,
                        suffix_lens: List[int], output_lens: List[int],
                        vocab_size: int, sampled_temperature: float = 0.8,
                        ) -> List[Tuple[float, dict]]:
    """Seeded chatbot-shaped trace for the prefix-cache A/B: a small
    pool of long shared "system prompts" (the prefixes), each request
    drawing one of them plus a short fresh user suffix.  Arrivals are
    the same unit-rate exponential chain :func:`poisson_trace` uses.
    Requests ALTERNATE greedy and sampled decoding so the cache-on/off
    token-identity gate exercises both paths on one trace — a prefix
    cache that only preserves argmax streams is not a cache, it is a
    different model."""
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab_size, (prefix_len,)).astype(np.int32)
                for _ in range(n_prefixes)]
    trace: List[Tuple[float, dict]] = []
    t = 0.0
    for rid in range(n_requests):
        t += float(rng.exponential(1.0)) / qps
        pfx = prefixes[int(rng.integers(0, n_prefixes))]
        sfx_len = int(rng.choice(suffix_lens))
        sfx = rng.integers(0, vocab_size, (sfx_len,)).astype(np.int32)
        trace.append((t, {
            "rid": rid,
            "prompt": np.concatenate([pfx, sfx]),
            "max_new_tokens": int(rng.choice(output_lens)),
            "temperature": 0.0 if rid % 2 == 0 else sampled_temperature,
        }))
    return trace


def _trace_vocab(model, ns) -> int:
    cap = getattr(ns, "trace_vocab", None)
    return min(model.cfg.vocab_size, cap) if cap else model.cfg.vocab_size


def run_point(model, params, *, mode: str, qps: float, ns,
              spec_k: int = 0, prefix_cache: bool = False,
              trace: Optional[List[Tuple[float, dict]]] = None) -> Dict:
    """One sweep point: fresh engine + fresh clock, the seeded trace for
    this QPS (or a caller-supplied one), closed-loop to drain.  Returns
    ``(summary, engine)`` — the summary carries the offered rate; the
    engine lets A/B callers (``spec_ab``, ``prefix_ab``) read per-rid
    token streams for identity gates."""
    from dtf_tpu.serve import ServingEngine, VirtualClock, WallClock

    clock = VirtualClock() if ns.clock == "virtual" else WallClock()
    engine = ServingEngine(
        model, params, num_slots=ns.slots, block_size=ns.block_size,
        num_blocks=ns.pool_blocks, mode=mode, seed=ns.seed, clock=clock,
        max_queue=ns.max_queue, top_k=ns.top_k, top_p=ns.top_p,
        spec_k=spec_k, prefix_cache=prefix_cache)
    if trace is None:
        trace = poisson_trace(
            seed=ns.seed, n_requests=ns.requests,
            qps=qps, prompt_lens=ns.prompt_lens_list,
            output_lens=ns.output_lens_list,
            vocab_size=_trace_vocab(model, ns),
            temperature=ns.temperature,
            qps_profile=getattr(ns, "qps_profile", "constant"))
    engine.run(trace)
    out = engine.summary(slo_ttft_ms=ns.slo_ttft_ms)
    out["offered_qps"] = qps
    out["requests_offered"] = len(trace)
    return out, engine


def sustained_goodput(points: List[Dict], budget_ms: float) -> Dict:
    """The headline scalar per mode: the best goodput QPS among sweep
    points whose p99 TTFT stayed inside the budget.  A mode that blows
    the budget at every offered rate sustains 0 — it cannot serve this
    SLO at any load level the sweep tried."""
    ok = [p for p in points
          if p.get("ttft_ms_p99") is not None
          and p["ttft_ms_p99"] <= budget_ms]
    if not ok:
        return {"sustained_goodput_qps": 0.0, "at_offered_qps": None}
    best = max(ok, key=lambda p: p.get("goodput_qps", 0.0))
    return {"sustained_goodput_qps": float(best.get("goodput_qps", 0.0)),
            "at_offered_qps": best["offered_qps"]}


def run_chaos_point(model, params, *, controller: bool, ns) -> Dict:
    """One overload run: the seeded trace (deadlines + mixed priority
    classes) through a chaos'd engine, with or without the brownout
    controller.  Fresh engine, fresh clock, fresh fault plan (fired
    latches are per-run state) — the ONLY difference between the two
    arms is the controller."""
    from dtf_tpu.resilience.chaos import FaultPlan
    from dtf_tpu.serve import (BrownoutController, ServingEngine,
                               VirtualClock, WallClock)
    from dtf_tpu.telemetry.slo import BurnRateMonitor

    clock = VirtualClock() if ns.clock == "virtual" else WallClock()
    brownout = (BrownoutController(ns.slo_ttft_ms,
                                   degrade_max_new=ns.degrade_max_new)
                if controller else None)
    chaos = FaultPlan.parse(ns.chaos, process_index=0)
    # burn-rate monitor in BOTH arms (it is passive): the controller arm
    # additionally gates alert-leads-control — the fast-burn alert must
    # fire before brownout walks to reject_all under the same spike
    slo = BurnRateMonitor.for_serving(ns.slo_ttft_ms)
    engine = ServingEngine(
        model, params, num_slots=ns.slots, block_size=ns.block_size,
        num_blocks=ns.pool_blocks, mode="continuous", seed=ns.seed,
        clock=clock, max_queue=ns.max_queue, top_k=ns.top_k,
        top_p=ns.top_p, brownout=brownout, chaos=chaos, slo=slo)
    trace = poisson_trace(
        seed=ns.seed, n_requests=ns.requests, qps=ns.qps_list[0],
        prompt_lens=ns.prompt_lens_list, output_lens=ns.output_lens_list,
        vocab_size=model.cfg.vocab_size, temperature=ns.temperature,
        deadline_ms=ns.deadline_ms or None,
        priorities=ns.priorities_list,
        qps_profile=getattr(ns, "qps_profile", "constant"))
    engine.run(trace)
    out = engine.summary(slo_ttft_ms=ns.slo_ttft_ms)
    out["controller"] = controller
    out["offered_qps"] = ns.qps_list[0]
    out["chaos"] = ns.chaos
    return out


def chaos_gates(on: Dict, off: Dict) -> Tuple[bool, List[str]]:
    """The overload acceptance gates (ISSUE 10):

    * **zero deadline violations** among admitted-and-completed
      requests in the controller arm (beyond the SLO grace the summary
      already folds in) — overload must shed, not silently blow
      deadlines;
    * **sheds are booked with reasons** — load was actually dropped at
      the front door, observably;
    * **the controller strictly improves goodput QPS** on the same
      trace under the same injected spike — brownout pays for itself;
    * **alert leads control** (ISSUE 11) — the SLO monitor's fast-burn
      alert fires STRICTLY before the brownout controller escalates to
      ``reject_all`` on the same trace: the operator's pager rings
      while the system is still degrading gracefully, not after it has
      already slammed the front door.
    """
    lines: List[str] = []
    ok = True

    def gate(name, passed, detail):
        nonlocal ok
        ok = ok and passed
        lines.append(f"gate {name}: {'OK' if passed else 'FAIL'} — "
                     f"{detail}")

    viol = on.get("deadline_violations")
    gate("deadline_violations",
         viol == 0,
         f"{viol} violation(s) among "
         f"{on.get('deadline_requests_completed', 0)} completed "
         f"deadline-carrying request(s) (controller arm)"
         if viol is not None else "deadlines not armed (set "
         "--deadline_ms)")
    shed = on.get("shed", 0)
    gate("sheds_booked", shed > 0 and bool(on.get("shed_reasons")),
         f"{shed} shed with reasons {on.get('shed_reasons')}")
    g_on = on.get("goodput_qps", 0.0)
    g_off = off.get("goodput_qps", 0.0)
    gate("controller_improves_goodput", g_on > g_off,
         f"goodput {g_on:.3f} qps with controller vs {g_off:.3f} "
         f"without (same trace, same spike)")
    # alert-leads-control: compare iteration marks on the SAME run (the
    # controller arm) — both events must exist under the pinned spike,
    # and the alert must be strictly earlier.
    slo = on.get("slo", {})
    first = (slo.get("objectives", {}).get("ttft", {})
             .get("first_alert", {}).get("fast"))
    alert_it = None if first is None else first.get("iteration")
    ra_it = on.get("brownout", {}).get("reject_all_iteration")
    gate("alert_leads_control",
         alert_it is not None and ra_it is not None and alert_it < ra_it,
         f"fast-burn alert at iteration {alert_it} vs brownout "
         f"reject_all at iteration {ra_it} (alert must exist and lead)")
    return ok, lines


def spec_gates(on: Dict, off: Dict, identical: Dict,
               max_tpot_p99_ms: Optional[float]) -> Tuple[bool, List[str]]:
    """The speculative-decoding acceptance gates (ISSUE 14):

    * **token identity** — every commonly-completed request's token
      stream is bitwise identical with and without speculation on the
      same trace (the verify step emits the model's own choices; the
      PR 9 token-identity contract survives); completion-set
      differences (a scheduling effect of the arms' different clock
      trajectories) are surfaced in the detail, not conflated with
      divergence;
    * **p99 TPOT strictly drops** at the fixed offered rate — the
      speculative win is a latency claim, measured end to end;
    * **drafts accepted** — acceptance > 0, so the win is attributable
      to speculation, not noise;
    * optional absolute ceiling ``--max_tpot_p99_ms``, enforced through
      the ONE :func:`telemetry.report.check_gates` path so the same
      threshold is CI-armable anywhere a telemetry.json lands.
    """
    from dtf_tpu.telemetry.report import check_gates

    lines: List[str] = []
    ok = True

    def gate(name, passed, detail):
        nonlocal ok
        ok = ok and passed
        lines.append(f"gate {name}: {'OK' if passed else 'FAIL'} — "
                     f"{detail}")

    gate("spec_token_identity", identical["ok"],
         (f"{identical['common']} common completed stream(s) bitwise "
          f"identical" if identical["ok"]
          else f"{len(identical['diverged'])} common stream(s) "
               f"DIVERGED: rids {identical['diverged'][:8]}")
         + (f"; completion sets differ (only-spec "
            f"{identical['only_on']}, only-baseline "
            f"{identical['only_off']})"
            if identical["only_on"] or identical["only_off"] else ""))
    t_on = on.get("tpot_ms_p99")
    t_off = off.get("tpot_ms_p99")
    gate("spec_tpot_p99_drops",
         t_on is not None and t_off is not None and t_on < t_off,
         f"p99 TPOT {t_on} ms with spec_k={on.get('spec_k')} vs "
         f"{t_off} ms without (same trace, qps {on.get('offered_qps')})")
    acc = on.get("spec_acceptance")
    gate("spec_drafts_accepted", bool(on.get("spec_accepted", 0) > 0),
         f"{on.get('spec_accepted', 0)}/{on.get('spec_proposed', 0)} "
         f"drafts accepted"
         + (f" (rate {acc:.3f})" if acc is not None else ""))
    if max_tpot_p99_ms:
        g_ok, g_lines = check_gates(
            {"telemetry": {"serving": on}},
            max_tpot_p99_ms=max_tpot_p99_ms)
        ok = ok and g_ok
        lines.extend(g_lines)
    return ok, lines


def spec_ab(model, params, ns) -> Dict:
    """Same-trace speculative-decoding on/off A/B at the fixed offered
    rate (the FIRST --qps point): identical trace, identical engine
    geometry, the only difference is ``spec_k``."""
    qps = ns.qps_list[0]
    on, eng_on = run_point(model, params, mode="continuous", qps=qps,
                           ns=ns, spec_k=ns.spec_k)
    off, eng_off = run_point(model, params, mode="continuous", qps=qps,
                             ns=ns, spec_k=0)
    tokens = []
    for eng in (eng_on, eng_off):
        tokens.append({r.rid: list(r.tokens or [])
                       for r in eng.results.values()
                       if r.status == "completed"})
    # Identity is judged per request over the INTERSECTION of completed
    # sets: the arms' clocks advance differently, so near a shed/
    # deadline boundary one arm may complete a request the other
    # dropped — a scheduling difference, not a token-identity
    # violation.  Set differences are surfaced in the gate detail.
    common = sorted(set(tokens[0]) & set(tokens[1]))
    diverged = [rid for rid in common if tokens[0][rid] != tokens[1][rid]]
    identical = {
        "ok": not diverged, "common": len(common), "diverged": diverged,
        "only_on": len(set(tokens[0]) - set(tokens[1])),
        "only_off": len(set(tokens[1]) - set(tokens[0])),
    }
    ok, lines = spec_gates(on, off, identical, ns.max_tpot_p99_ms or None)
    if ns.logdir:
        import os
        os.makedirs(ns.logdir, exist_ok=True)
        eng_on.write_telemetry(ns.logdir, slo_ttft_ms=ns.slo_ttft_ms)
    for arm, s in (("spec", on), ("no_spec", off)):
        acc = s.get("spec_acceptance")
        print(f"  [{arm:>8}] completed {s.get('completed', 0):3d}  "
              f"tpot p50/p99 {s.get('tpot_ms_p50', float('nan')):6.2f}"
              f"/{s.get('tpot_ms_p99', float('nan')):6.2f} ms  "
              f"ttft p99 {s.get('ttft_ms_p99', float('nan')):7.1f} ms"
              + (f"  acceptance {acc:.2f}" if acc is not None else ""),
              flush=True)
    return {"spec_k": ns.spec_k, "offered_qps": qps, "clock": ns.clock,
            "spec": on, "no_spec": off,
            "token_identity": identical["ok"],
            "token_identity_detail": identical,
            "gates": lines, "ok": ok}


def _churn_with_cancels(engine, trace, *, seed: int,
                        cancel_frac: float = 0.4,
                        max_iterations: int = 1_000_000) -> int:
    """Drive ``trace`` through a live engine while cancelling a seeded
    random subset of requests a few iterations after submission — the
    leak hunt for the prefix cache's refcount/pin lifecycle.  Cancels
    land in every phase (queued holding prefix pins, mid-prefill
    reservation, mid-decode on shared blocks).  Returns the number of
    cancels issued."""
    rng = np.random.default_rng(seed)
    pending: Dict[int, int] = {}
    cancels = 0
    i = 0
    it = 0
    while i < len(trace) or engine.scheduler.has_work():
        if it >= max_iterations:
            raise RuntimeError("churn did not drain — wedged scheduler?")
        now = engine.clock.now()
        while i < len(trace) and trace[i][0] <= now:
            t_arr, kw = trace[i]
            engine.submit(arrival_s=t_arr, **kw)
            if rng.random() < cancel_frac:
                pending[kw["rid"]] = int(rng.integers(0, 5))
            i += 1
        if not engine.scheduler.has_work():
            if i >= len(trace):
                break
            engine.clock.advance_to(trace[i][0])
            continue
        engine.step()
        it += 1
        for rid in list(pending):
            if pending[rid] <= 0:
                if engine.cancel(rid):
                    cancels += 1
                del pending[rid]
            else:
                pending[rid] -= 1
    return cancels


def prefix_gates(on: Dict, off: Dict, identical: Dict,
                 churn: Dict) -> Tuple[bool, List[str]]:
    """The prefix-cache acceptance gates (ISSUE 20):

    * **token identity** — every commonly-completed request's stream is
      bitwise identical with the cache on and off, and the comparison
      must cover BOTH greedy and sampled requests (suffix-only prefill
      emits the same logits as cold prefill or it does not ship);
    * **p50 TTFT >= {PREFIX_AB_MIN_RATIO}x** — the cache-off p50 over
      the cache-on p50 on the same trace (the headline: shared tokens
      are not recomputed);
    * **p99 TTFT strictly improves** — the tail moves too, not just the
      median (a cache that helps the median while starving the tail is
      a regression in SLO terms);
    * **prefix hits observed** — ``serve/prefix_hit_blocks_total`` > 0
      in the cache-on arm, so the win is attributable to the cache;
    * **zero leaked blocks** — after a churn wave with seeded random
      cancels on the cache-on engine, every non-trash block is back in
      the free/cached tiers (refcounts, queued pins and COW forks all
      unwound), and the cache-off arm leaks nothing either.
    """
    lines: List[str] = []
    ok = True

    def gate(name, passed, detail):
        nonlocal ok
        ok = ok and passed
        lines.append(f"gate {name}: {'OK' if passed else 'FAIL'} — "
                     f"{detail}")

    gate("prefix_token_identity",
         identical["ok"] and identical["greedy"] > 0
         and identical["sampled"] > 0,
         (f"{identical['common']} common completed stream(s) bitwise "
          f"identical ({identical['greedy']} greedy, "
          f"{identical['sampled']} sampled)" if identical["ok"]
          else f"{len(identical['diverged'])} common stream(s) "
               f"DIVERGED: rids {identical['diverged'][:8]}")
         + (f"; completion sets differ (only-on {identical['only_on']}, "
            f"only-off {identical['only_off']})"
            if identical["only_on"] or identical["only_off"] else ""))
    p50_on, p50_off = on.get("ttft_ms_p50"), off.get("ttft_ms_p50")
    ratio = (None if not p50_on or p50_off is None
             else p50_off / p50_on)
    gate("prefix_ttft_p50",
         ratio is not None and ratio >= PREFIX_AB_MIN_RATIO,
         f"p50 TTFT {p50_off} ms off / {p50_on} ms on = ratio "
         + ("n/a" if ratio is None else f"{ratio:.2f}")
         + f" (bar {PREFIX_AB_MIN_RATIO})")
    p99_on, p99_off = on.get("ttft_ms_p99"), off.get("ttft_ms_p99")
    gate("prefix_ttft_p99_improves",
         p99_on is not None and p99_off is not None and p99_on < p99_off,
         f"p99 TTFT {p99_on} ms on vs {p99_off} ms off (must strictly "
         f"improve)")
    hits = on.get("prefix_hit_blocks", 0)
    gate("prefix_hits_observed",
         hits > 0,
         f"{hits} prefix block(s) hit over {on.get('prefix_lookups', 0)} "
         f"lookup(s), hit rate {on.get('prefix_hit_rate', 0.0):.3f}")
    gate("prefix_zero_leaks",
         churn["leaked_on"] == 0 and churn["leaked_off"] == 0,
         f"{churn['leaked_on']} block(s) leaked cache-on / "
         f"{churn['leaked_off']} cache-off after churn with "
         f"{churn['cancels']} random cancel(s) "
         f"({churn['cached_blocks']} block(s) parked in the cached "
         f"tier, which is reclaimable, not leaked)")
    return ok, lines


def prefix_ab(model, params, ns) -> Dict:
    """Same-trace prefix-cache on/off A/B at the FIRST --qps point:
    identical shared-prefix chatbot trace, identical engine geometry,
    the only difference is ``prefix_cache``.  After the measured run
    each arm eats a second churn wave with seeded random cancels; the
    leak gate then requires every non-trash block back in the
    free/cached tiers."""
    qps = ns.qps_list[0]
    prefix_len = ns.prefix_len or 5 * ns.block_size
    trace = shared_prefix_trace(
        seed=ns.seed, n_requests=ns.requests, qps=qps,
        n_prefixes=ns.n_prefixes, prefix_len=prefix_len,
        suffix_lens=ns.prompt_lens_list, output_lens=ns.output_lens_list,
        vocab_size=_trace_vocab(model, ns))

    def churn_wave(offset: int) -> List[Tuple[float, dict]]:
        return [(t, {**kw, "rid": kw["rid"] + offset})
                for t, kw in trace]

    on, eng_on = run_point(model, params, mode="continuous", qps=qps,
                           ns=ns, prefix_cache=True, trace=trace)
    off, eng_off = run_point(model, params, mode="continuous", qps=qps,
                             ns=ns, prefix_cache=False, trace=trace)
    tokens = []
    for eng in (eng_on, eng_off):
        tokens.append({r.rid: list(r.tokens or [])
                       for r in eng.results.values()
                       if r.status == "completed"})
    # Identity over the INTERSECTION of completed sets (same rationale
    # as spec_ab: near a shed boundary the arms' different clock
    # trajectories may complete different sets — a scheduling effect,
    # surfaced in the detail, not a token-identity violation).
    common = sorted(set(tokens[0]) & set(tokens[1]))
    diverged = [rid for rid in common if tokens[0][rid] != tokens[1][rid]]
    identical = {
        "ok": not diverged, "common": len(common), "diverged": diverged,
        "greedy": sum(1 for rid in common if rid % 2 == 0),
        "sampled": sum(1 for rid in common if rid % 2 == 1),
        "only_on": len(set(tokens[0]) - set(tokens[1])),
        "only_off": len(set(tokens[1]) - set(tokens[0])),
    }
    # churn-with-cancels on BOTH live engines (fresh rids), then the
    # leak audit: every block outside the trash sentinel must be free
    # or parked in the reclaimable cached tier
    cancels = _churn_with_cancels(eng_on, churn_wave(len(trace)),
                                  seed=ns.seed + 1)
    cancels += _churn_with_cancels(eng_off, churn_wave(len(trace)),
                                   seed=ns.seed + 1)

    def leaked(eng) -> int:
        alloc = eng.scheduler.allocator
        return alloc.num_blocks - 1 - alloc.free_blocks

    churn = {"cancels": cancels,
             "leaked_on": leaked(eng_on), "leaked_off": leaked(eng_off),
             "cached_blocks": eng_on.scheduler.allocator.cached_blocks}
    ok, lines = prefix_gates(on, off, identical, churn)
    if ns.logdir:
        import os
        os.makedirs(ns.logdir, exist_ok=True)
        eng_on.write_telemetry(ns.logdir, slo_ttft_ms=ns.slo_ttft_ms)
    for arm, s in (("cache_on", on), ("cache_off", off)):
        print(f"  [{arm:>9}] completed {s.get('completed', 0):3d}  "
              f"ttft p50/p99 {s.get('ttft_ms_p50', float('nan')):7.1f}"
              f"/{s.get('ttft_ms_p99', float('nan')):7.1f} ms  "
              f"goodput {s.get('goodput_qps', 0.0):6.2f} qps"
              + (f"  hit rate {s.get('prefix_hit_rate', 0.0):.3f}"
                 if s.get("prefix_cache") else ""), flush=True)
    p50_on = float(on.get("ttft_ms_p50") or 0.0)
    p50_off = float(off.get("ttft_ms_p50") or 0.0)
    return {"offered_qps": qps, "clock": ns.clock,
            "prefix_len": prefix_len, "n_prefixes": ns.n_prefixes,
            # rig names the arm geometry so a deliberately-different
            # shape (other block size / prefix depth) never aliases onto
            # this rig's regression history in the ledger
            "rig": (f"prefix_bs{ns.block_size}_p{prefix_len}"
                    f"_n{ns.n_prefixes}"),
            "ttft_p50_ratio": (p50_off / p50_on) if p50_on > 0 else None,
            "cache_on": on, "cache_off": off,
            "token_identity": identical["ok"],
            "token_identity_detail": identical,
            "churn": churn, "min_ratio": PREFIX_AB_MIN_RATIO,
            "gates": lines, "ok": ok}


def fleet_gates(fleet: Dict, single: Dict, identical: Dict,
                totals: Dict, chaos_armed: bool) -> Tuple[bool, List[str]]:
    """The fleet A/B acceptance gates (ISSUE 16):

    * **zero lost** — every offered request reached a terminal in the
      fleet arm (a killed replica's accepted work fails over, it does
      not vanish);
    * **token identity** — every fleet completion is bitwise identical
      to the uninterrupted single-engine reference (failover replay and
      hedging may move a request between replicas, never change its
      tokens);
    * **goodput ratio** (chaos arms) — fleet goodput QPS >=
      {FLEET_AB_MIN_RATIO}x single-replica UNDER THE SAME FAULT.  Both
      arms eat the identical ``replica_down`` plan on the identical
      trace; the single arm's only replica IS the target, so its
      goodput collapses to the pre-kill completions while the fleet
      fails over and keeps serving — the survival margin is the
      product's value, measured, not a parallel-speedup claim (on a
      1-core rig N in-process replicas share one driver thread and
      cannot beat one replica on raw throughput);
    * **fleet completes all** + **failover exercised** (chaos arms) —
      the fleet arm completes every offered request even though the
      plan killed a replica, and at least one in-flight request was
      replayed on a survivor.
    """
    lines: List[str] = []
    ok = True

    def gate(name, passed, detail):
        nonlocal ok
        ok = ok and passed
        lines.append(f"  gate {name:<22} "
                     f"{'PASS' if passed else 'FAIL'}  {detail}")

    lost = fleet.get("lost", 0)
    gate("fleet_zero_lost", lost == 0,
         f"{lost} lost of {fleet.get('offered', 0)} offered "
         f"(statuses {fleet.get('statuses')})")
    gate("fleet_token_identity", identical["ok"],
         f"{identical['compared']} compared, "
         f"diverged {identical['diverged'][:4]}, "
         f"missing_ref {identical['missing_ref'][:4]}")
    if chaos_armed:
        done, offered = fleet.get("completed", 0), fleet.get("offered", 0)
        gate("fleet_completes_all", done == offered,
             f"{done}/{offered} completed through the fault "
             f"(statuses {fleet.get('statuses')})")
        fg = fleet.get("goodput_qps", 0.0)
        sg = single.get("goodput_qps", 0.0)
        ratio = None if sg <= 0 else fg / sg
        gate("fleet_goodput_ab",
             (fg > 0 if ratio is None else ratio >= FLEET_AB_MIN_RATIO),
             f"fleet {fg:.2f} qps vs single {sg:.2f} qps under the "
             f"same fault (ratio "
             + ("inf" if ratio is None else f"{ratio:.2f}")
             + f", bar {FLEET_AB_MIN_RATIO})")
        gate("fleet_failover",
             totals.get("failovers", 0) >= 1
             and totals.get("replayed", 0) >= 1,
             f"failovers {totals.get('failovers', 0)}, replayed "
             f"{totals.get('replayed', 0)} (chaos arm must exercise "
             f"the replay path)")
    return ok, lines


def fleet_ab(model, params, ns) -> Dict:
    """Same-trace fleet-vs-single A/B over real sockets (--replicas N).

    Three arms, one seeded trace at the FIRST --qps point:

    * **reference** — one uninterrupted engine on the virtual clock:
      the token ground truth (temperature 0, so tokens depend only on
      the prompt — rid assignment order cannot perturb them);
    * **single** — a 1-replica fleet (same acceptor, same sockets, same
      measurement path — the honest baseline);
    * **fleet** — N replicas.

    With ``--chaos replica_down@S:P``, BOTH measured arms eat the same
    plan (the single arm's target clamps to its only replica): the A/B
    is survival under the identical fault, which is the fleet's actual
    value on any rig — not a parallel-speedup claim.

    Chaos arms AFTER the warmup barrage, so ``@S`` counts measured
    dispatches.  Both measured arms warm every replica's compile cache
    first (n_replicas x slots tiny requests) — on the wall clock a
    first-step XLA compile would otherwise dominate every TTFT."""
    from dtf_tpu.serve import ServingEngine, VirtualClock
    from dtf_tpu.serve.fleet import (FleetConfig, build_local_fleet,
                                     client_summary, drive_trace)

    qps = ns.qps_list[0]
    trace = poisson_trace(
        seed=ns.seed, n_requests=ns.requests, qps=qps,
        prompt_lens=ns.prompt_lens_list, output_lens=ns.output_lens_list,
        vocab_size=_trace_vocab(model, ns), temperature=0.0,
        priorities=ns.priorities_list)
    ekw = dict(num_slots=ns.slots, block_size=ns.block_size,
               num_blocks=ns.pool_blocks, max_queue=ns.max_queue)

    ref_eng = ServingEngine(model, params, seed=ns.seed,
                            clock=VirtualClock(), **ekw)
    ref_eng.run(trace)
    ref = {rid: list(r.tokens or [])
           for rid, r in ref_eng.results.items()
           if r.status == "completed"}

    def run_arm(n: int, chaos_spec: Optional[str]):
        cfg = FleetConfig(stream_timeout_s=10.0, beat_stale_s=3.0,
                          monitor_interval_s=0.1, connect_timeout_s=2.0)
        acc = build_local_fleet(model, params, n, seed=ns.seed,
                                config=cfg, engine_kwargs=ekw).start()
        try:
            warm = [(0.0, {"prompt": np.arange(1, 4, dtype=np.int32),
                           "max_new_tokens": 2, "temperature": 0.0})
                    for _ in range(n * ns.slots)]
            drive_trace(acc.address, warm, request_timeout_s=120.0)
            if chaos_spec:
                from dtf_tpu.resilience.chaos import (_FLEET_KINDS,
                                                      FaultPlan)
                plan = FaultPlan.parse(chaos_spec, process_index=0)
                for f in plan.faults:
                    # the single arm has one failure domain: a fleet
                    # fault aimed at replica P >= n hits replica 0 (the
                    # same fault, the only possible target)
                    if f.kind in _FLEET_KINDS and (f.process or 0) >= n:
                        f.process = 0
                acc.arm_chaos(plan)
            res = drive_trace(acc.address, trace, request_timeout_s=120.0)
            summ = client_summary(res, slo_ttft_ms=ns.slo_ttft_ms)
            return res, summ, acc.totals()
        finally:
            acc.shutdown()

    fleet_res, fleet_sum, fleet_tot = run_arm(ns.replicas, ns.chaos)
    single_res, single_sum, single_tot = run_arm(1, ns.chaos)

    # Identity of every fleet COMPLETION vs the reference (poisson_trace
    # rids are the trace indices, so res[i] pairs with ref[i]).
    diverged, missing_ref, compared = [], [], 0
    for i, rec in sorted(fleet_res.items()):
        if rec["status"] != "completed":
            continue
        if i not in ref:
            missing_ref.append(i)
            continue
        compared += 1
        if rec["tokens"] != ref[i]:
            diverged.append(i)
    identical = {"ok": not diverged and not missing_ref,
                 "compared": compared, "diverged": diverged,
                 "missing_ref": missing_ref}

    ok, lines = fleet_gates(fleet_sum, single_sum, identical, fleet_tot,
                            chaos_armed=bool(ns.chaos))
    for arm, s in (("fleet", fleet_sum), ("single", single_sum)):
        print(f"  [{arm:>7}] completed {s.get('completed', 0):3d}/"
              f"{s.get('offered', 0):3d}  lost {s.get('lost', 0):2d}  "
              f"ttft p50/p99 {s.get('ttft_ms_p50', float('nan')):7.1f}/"
              f"{s.get('ttft_ms_p99', float('nan')):7.1f} ms  "
              f"goodput {s.get('goodput_qps', 0.0):6.2f} qps", flush=True)
    print(f"  [  fleet] failovers {fleet_tot.get('failovers', 0)}  "
          f"replayed {fleet_tot.get('replayed', 0)}  "
          f"hedged {fleet_tot.get('hedged', 0)}", flush=True)
    return {"replicas": ns.replicas, "offered_qps": qps,
            "chaos": ns.chaos, "slo_ttft_ms": ns.slo_ttft_ms,
            "fleet": fleet_sum, "single": single_sum,
            "fleet_totals": fleet_tot, "single_totals": single_tot,
            "token_identity": identical["ok"],
            "token_identity_detail": identical,
            "min_ratio": FLEET_AB_MIN_RATIO,
            "gates": lines, "ok": ok}


def chaos_ab(model, params, ns) -> Dict:
    """Same-trace controller-on/off A/B under the injected spike."""
    on = run_chaos_point(model, params, controller=True, ns=ns)
    off = run_chaos_point(model, params, controller=False, ns=ns)
    ok, lines = chaos_gates(on, off)
    for arm, s in (("controller", on), ("no_controller", off)):
        print(f"  [{arm:>13}] completed {s.get('completed', 0):3d}  "
              f"shed {s.get('shed', 0):3d}  "
              f"ttft p99 {s.get('ttft_ms_p99', float('nan')):8.1f} ms  "
              f"goodput {s.get('goodput_qps', 0.0):6.2f} qps  "
              f"violations {s.get('deadline_violations', '-')}",
              flush=True)
    return {"chaos": ns.chaos, "slo_ttft_ms": ns.slo_ttft_ms,
            "clock": ns.clock, "controller": on, "no_controller": off,
            "gates": lines, "ok": ok}


def run_knob_point(model, params, *, knobs: bool, ns) -> Tuple[Dict, object]:
    """One adversarial-load run with or without the self-tuning knob
    controller (dtf_tpu/control).  The two arms share EVERYTHING — the
    seeded trace (same qps_profile shape), the fault plan, the brownout
    config, the SLO monitor, the engine geometry — so the delta is
    attributable to the knob controller alone.  Returns ``(summary,
    engine)``; the summary's ``control`` section (knob positions,
    decisions, rollbacks + reasons) is what :func:`knob_gates` judges."""
    from dtf_tpu.serve import (BrownoutController, ServingEngine,
                               VirtualClock, WallClock)
    from dtf_tpu.telemetry.slo import BurnRateMonitor

    clock = VirtualClock() if ns.clock == "virtual" else WallClock()
    chaos = None
    if ns.chaos:
        from dtf_tpu.resilience.chaos import FaultPlan
        chaos = FaultPlan.parse(ns.chaos, process_index=0)
    brownout = BrownoutController(ns.slo_ttft_ms,
                                  degrade_max_new=ns.degrade_max_new)
    slo = BurnRateMonitor.for_serving(ns.slo_ttft_ms)
    engine = ServingEngine(
        model, params, num_slots=ns.slots, block_size=ns.block_size,
        num_blocks=ns.pool_blocks, mode="continuous", seed=ns.seed,
        clock=clock, max_queue=ns.max_queue, top_k=ns.top_k,
        top_p=ns.top_p, brownout=brownout, chaos=chaos, slo=slo,
        spec_k=ns.spec_k)
    if knobs:
        from dtf_tpu.control import arm_controller
        arm_controller(engine)
    trace = poisson_trace(
        seed=ns.seed, n_requests=ns.requests, qps=ns.qps_list[0],
        prompt_lens=ns.prompt_lens_list, output_lens=ns.output_lens_list,
        vocab_size=_trace_vocab(model, ns), temperature=ns.temperature,
        deadline_ms=ns.deadline_ms or None,
        priorities=ns.priorities_list, qps_profile=ns.qps_profile)
    engine.run(trace)
    out = engine.summary(slo_ttft_ms=ns.slo_ttft_ms)
    out["knob_controller"] = knobs
    out["offered_qps"] = ns.qps_list[0]
    out["qps_profile"] = ns.qps_profile
    out["chaos"] = ns.chaos
    return out, engine


def knob_gates(on: Dict, off: Dict,
               max_rollbacks: Optional[int]) -> Tuple[bool, List[str]]:
    """The self-tuning control-plane acceptance gates (ISSUE 17):

    * **goodput strictly improves** — the knob-controller arm beats the
      pinned-knob arm on the same trace under the same adversarial load
      shape (the controller pays for itself or it does not ship);
    * **latency no worse** — p99 TTFT and p99 TPOT do not regress
      versus the pinned arm (a goodput win bought with a latency
      blow-up is not a win);
    * **knobs actually moved** — the controller made decisions AND at
      least one audited knob set landed, so the delta is attributable
      to knob motion, not noise;
    * **every rollback explained** — each snap-back is booked with a
      reason (``fast_burn`` / ``no_improvement``); an unexplained
      rollback means an unaudited mutation path exists.  With
      ``max_rollbacks`` armed the count is also bounded.
    """
    lines: List[str] = []
    ok = True

    def gate(name, passed, detail):
        nonlocal ok
        ok = ok and passed
        lines.append(f"gate {name}: {'OK' if passed else 'FAIL'} — "
                     f"{detail}")

    g_on = on.get("goodput_qps", 0.0)
    g_off = off.get("goodput_qps", 0.0)
    gate("knob_controller_improves_goodput", g_on > g_off,
         f"goodput {g_on:.3f} qps with knob controller vs {g_off:.3f} "
         f"pinned (same trace, same load shape)")
    t_on, t_off = on.get("ttft_ms_p99"), off.get("ttft_ms_p99")
    d_on, d_off = on.get("tpot_ms_p99"), off.get("tpot_ms_p99")
    gate("knob_latency_no_worse",
         (t_on is not None and t_off is not None and t_on <= t_off
          and d_on is not None and d_off is not None and d_on <= d_off),
         f"ttft p99 {t_on} vs {t_off} ms, tpot p99 {d_on} vs {d_off} ms "
         f"(controller vs pinned)")
    ctl = on.get("control") or {}
    gate("knob_decisions_made",
         ctl.get("decisions", 0) > 0 and ctl.get("sets", 0) > 0,
         f"{ctl.get('decisions', 0)} decision(s), "
         f"{ctl.get('sets', 0)} audited knob set(s), final knobs "
         f"{ctl.get('knobs')}")
    rb = ctl.get("rollbacks", 0)
    explained = sum((ctl.get("rollback_reasons") or {}).values())
    bounded = max_rollbacks is None or rb <= max_rollbacks
    gate("knob_rollbacks_explained", rb == explained and bounded,
         f"{rb} rollback(s), {explained} with reasons "
         f"{ctl.get('rollback_reasons')}"
         + (f", bound {max_rollbacks}" if max_rollbacks is not None
            else ""))
    return ok, lines


def knob_ab(model, params, ns) -> Dict:
    """Same-trace knob-controller on/off A/B under the adversarial load
    shape (--qps_profile) and/or fault plan (--chaos)."""
    on, eng_on = run_knob_point(model, params, knobs=True, ns=ns)
    off, _ = run_knob_point(model, params, knobs=False, ns=ns)
    ok, lines = knob_gates(on, off, ns.max_control_rollbacks)
    if ns.logdir:
        import os
        os.makedirs(ns.logdir, exist_ok=True)
        eng_on.write_telemetry(ns.logdir, slo_ttft_ms=ns.slo_ttft_ms)
    for arm, s in (("knobs", on), ("pinned", off)):
        ctl = s.get("control") or {}
        print(f"  [{arm:>6}] completed {s.get('completed', 0):3d}  "
              f"ttft p99 {s.get('ttft_ms_p99', float('nan')):8.1f} ms  "
              f"tpot p99 {s.get('tpot_ms_p99', float('nan')):6.2f} ms  "
              f"goodput {s.get('goodput_qps', 0.0):6.2f} qps"
              + (f"  sets {ctl.get('sets', 0)} "
                 f"rollbacks {ctl.get('rollbacks', 0)}"
                 if ctl else ""), flush=True)
    return {"qps_profile": ns.qps_profile, "chaos": ns.chaos,
            "slo_ttft_ms": ns.slo_ttft_ms, "clock": ns.clock,
            "knobs": on, "pinned": off, "gates": lines, "ok": ok}


def sweep(model, params, ns) -> Dict:
    modes = (["continuous", "static"] if ns.mode == "both" else [ns.mode])
    points: List[Dict] = []
    for mode in modes:
        for qps in ns.qps_list:
            pt, _ = run_point(model, params, mode=mode, qps=qps, ns=ns,
                              spec_k=getattr(ns, "spec_k", 0))
            points.append(pt)
            print(f"  [{mode:>10}] offered {qps:6.1f} qps -> "
                  f"ttft p50/p99 {pt.get('ttft_ms_p50', float('nan')):7.1f}"
                  f"/{pt.get('ttft_ms_p99', float('nan')):7.1f} ms  "
                  f"tpot p50 {pt.get('tpot_ms_p50', float('nan')):6.2f} ms  "
                  f"goodput {pt.get('goodput_qps', 0.0):6.2f} qps  "
                  f"rejected {pt.get('rejected', 0)}", flush=True)
    out: Dict = {"slo_ttft_ms": ns.slo_ttft_ms, "clock": ns.clock,
                 "requests_per_point": ns.requests, "points": points}
    by_mode = {m: [p for p in points if p["mode"] == m] for m in modes}
    out["sustained"] = {m: sustained_goodput(by_mode[m], ns.slo_ttft_ms)
                        for m in modes}
    if len(modes) == 2:
        cont = out["sustained"]["continuous"]["sustained_goodput_qps"]
        stat = out["sustained"]["static"]["sustained_goodput_qps"]
        if cont <= 0.0:
            ratio = 0.0          # continuous sustained nothing: hard fail
        elif stat <= 0.0:
            # static cannot serve this SLO at any offered rate: no finite
            # ratio exists.  None (JSON null) rather than float('inf') —
            # json.dump would emit the non-standard token Infinity and
            # break every strict parser reading the --json artifact.
            ratio = None
        else:
            ratio = cont / stat
        out["ab"] = {
            "continuous_sustained_qps": cont,
            "static_sustained_qps": stat,
            "ratio": ratio,
            "min_ratio": AB_MIN_RATIO,
        }
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dtf_tpu.bench.serve_load",
        description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "gpt2_small", "llama"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mode", choices=["continuous", "static", "both"],
                   default="both")
    p.add_argument("--qps", default="6,12,20,28",
                   help="comma-separated offered-QPS sweep points")
    p.add_argument("--requests", type=int, default=64,
                   help="requests per sweep point")
    p.add_argument("--prompt_lens", default="4,8,16")
    # Wide output spread on purpose: static batching holds every slot
    # until the LONGEST member drains (utilization ~ mean/max output
    # length), so a mixed 2..32 pool is exactly the traffic shape that
    # separates the two policies — and the realistic one.
    p.add_argument("--output_lens", default="2,8,32")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=1.0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--block_size", type=int, default=16)
    p.add_argument("--pool_blocks", type=int, default=None)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--slo_ttft_ms", type=float, default=400.0,
                   help="the p99 TTFT budget goodput is gated on")
    p.add_argument("--chaos", default=None,
                   help="serving fault plan for the overload gate, e.g. "
                        "'slow_decode@30:60ms:50' (engine-iteration "
                        "keyed; needs --mode continuous).  --check then "
                        "gates zero deadline violations + booked sheds "
                        "+ controller-on beats controller-off on the "
                        "same trace")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="attach this completion deadline to every "
                        "request (0 = none); the scheduler sheds "
                        "hopeless requests before prefill")
    p.add_argument("--priorities", default="0",
                   help="comma-separated priority pool requests draw "
                        "from (brownout level 2 sheds priority <= 0)")
    p.add_argument("--degrade_max_new", type=int, default=8,
                   help="brownout level-1 output-length ceiling")
    p.add_argument("--clock", choices=["wall", "virtual"],
                   default="virtual",
                   help="virtual = deterministic cost-model time (CI); "
                        "wall = measure the real engine")
    p.add_argument("--spec_k", type=int, default=0,
                   help="speculative decoding: drafts per iteration "
                        "(applies to every continuous-mode point)")
    p.add_argument("--spec_ab", action="store_true",
                   help="same-trace spec-on/off A/B at the FIRST --qps "
                        "point (fixed-rate mode); --check gates token "
                        "identity + strict p99 TPOT improvement + "
                        "acceptance > 0")
    p.add_argument("--qps_profile", default="constant",
                   choices=list(QPS_PROFILES),
                   help="arrival-rate shape around the offered rate "
                        "(same seeded request contents, only arrival "
                        "times move): ramp 0.5x->1.5x, square "
                        "oscillation, sine — the adversarial shapes "
                        "the knob controller is judged under")
    p.add_argument("--knob_ab", action="store_true",
                   help="same-trace self-tuning knob-controller on/off "
                        "A/B (dtf_tpu/control) at the FIRST --qps "
                        "point under --qps_profile and/or --chaos; "
                        "--check gates strict goodput improvement + "
                        "latency no worse + audited knob motion + "
                        "zero unexplained rollbacks")
    p.add_argument("--max_control_rollbacks", type=int, default=None,
                   help="with --knob_ab: also bound the controller "
                        "arm's snap-back count (same threshold "
                        "telemetry.report --max_control_rollbacks "
                        "arms on a telemetry.json)")
    p.add_argument("--prefix_ab", action="store_true",
                   help="same-trace prefix-KV-cache on/off A/B at the "
                        "FIRST --qps point on a seeded shared-prefix "
                        "chatbot trace (requests alternate greedy and "
                        "sampled); --check gates token identity + p50 "
                        f"TTFT >= {PREFIX_AB_MIN_RATIO}x + strict p99 "
                        "improvement + prefix hits > 0 + zero leaked "
                        "blocks after churn with random cancels")
    p.add_argument("--n_prefixes", type=int, default=3,
                   help="with --prefix_ab: size of the shared system-"
                        "prompt pool requests draw their prefix from")
    p.add_argument("--prefix_len", type=int, default=0,
                   help="with --prefix_ab: shared prefix length in "
                        "tokens (0 = 5 * block_size)")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="fleet A/B (serve/fleet.py): N replicas vs a "
                        "single replica on the SAME trace over real "
                        "sockets at the FIRST --qps point; --chaos "
                        "takes the fleet kinds (replica_down@S:P, "
                        "replica_wedge@S:DURms, conn_flake@S:P, keyed "
                        "on measured dispatch sequence); --check gates "
                        "zero lost + token identity vs an uninterrupted "
                        f"reference + goodput >= {FLEET_AB_MIN_RATIO}x "
                        "single-replica")
    p.add_argument("--trace_vocab", type=int, default=None,
                   help="cap the trace's prompt token alphabet (small "
                        "alphabets give the n-gram drafter material)")
    p.add_argument("--max_tpot_p99_ms", type=float, default=0.0,
                   help="absolute p99 TPOT ceiling, enforced through "
                        "telemetry.report.check_gates (0 = off)")
    p.add_argument("--logdir", default=None,
                   help="write the (spec arm's) engine telemetry.json "
                        "here for report --check")
    p.add_argument("--json", default=None,
                   help="write the full sweep result here")
    p.add_argument("--check", action="store_true",
                   help="exit nonzero unless continuous sustains >= "
                        f"{AB_MIN_RATIO}x static goodput at the budget "
                        f"(requires --mode both)")
    p.add_argument("--cpu", action="store_true")
    ns = p.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    ns.qps_list = [float(x) for x in ns.qps.split(",")]
    ns.prompt_lens_list = [int(x) for x in ns.prompt_lens.split(",")]
    ns.output_lens_list = [int(x) for x in ns.output_lens.split(",")]
    ns.priorities_list = [int(x) for x in ns.priorities.split(",")]
    if ns.replicas is not None:
        if ns.replicas < 2:
            p.error("--replicas needs N >= 2 (the single arm is built "
                    "in as the baseline)")
        if ns.spec_ab:
            p.error("--replicas and --spec_ab are separate A/Bs; run "
                    "them as separate invocations")
        if ns.temperature != 0.0:
            p.error("--replicas gates token identity across replicas; "
                    "that needs greedy decoding (--temperature 0)")
        if ns.clock != "wall":
            # fleet arms serve real sockets; force the wall clock the
            # same way --listen does
            ns.clock = "wall"
    if (ns.chaos and ns.replicas is None and not ns.knob_ab
            and ns.mode != "continuous"):
        p.error("--chaos is the overload/brownout gate; it runs the "
                "continuous engine (--mode continuous)")
    if ns.spec_ab and ns.spec_k < 1:
        p.error("--spec_ab needs --spec_k >= 1 (the speculative arm)")
    if ns.spec_ab and ns.chaos:
        p.error("--spec_ab and --chaos are separate A/Bs; run them "
                "as separate invocations")
    if ns.knob_ab and (ns.spec_ab or ns.replicas is not None):
        p.error("--knob_ab is its own A/B; run --spec_ab/--replicas "
                "as separate invocations")
    if ns.prefix_ab and (ns.spec_ab or ns.knob_ab or ns.chaos
                         or ns.replicas is not None):
        p.error("--prefix_ab is its own A/B; run --spec_ab/--knob_ab/"
                "--chaos/--replicas as separate invocations")
    if (ns.check and not ns.chaos and not ns.spec_ab and not ns.knob_ab
            and not ns.prefix_ab
            and ns.replicas is None and ns.mode != "both"):
        p.error("--check needs --mode both (it asserts the A/B ratio), "
                "--chaos (the overload gates), --spec_ab (the "
                "speculative-decoding gates), --knob_ab (the control-"
                "plane gates), --prefix_ab (the prefix-cache gates), "
                "or --replicas (the fleet gates)")

    import jax

    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.from_preset(ns.preset)
    model = GPT(cfg)
    params = model.init(jax.random.key(ns.seed))
    print(f"serve_load: preset={ns.preset} slots={ns.slots} "
          f"block_size={ns.block_size} clock={ns.clock} "
          f"slo_ttft_ms={ns.slo_ttft_ms}"
          + (f" chaos={ns.chaos}" if ns.chaos else "")
          + (f" spec_k={ns.spec_k}" if ns.spec_k else "")
          + (f" qps_profile={ns.qps_profile}"
             if ns.qps_profile != "constant" else ""), flush=True)
    if ns.replicas is not None:
        result = fleet_ab(model, params, ns)
        for line in result["gates"]:
            print(line, flush=True)
        if ns.json:
            with open(ns.json, "w") as f:
                json.dump(result, f, indent=1, sort_keys=True)
            print(f"wrote {ns.json}")
        if ns.check:
            if not result["ok"]:
                print("CHECK FAILED: fleet gates (see above)",
                      file=sys.stderr)
                return 1
            print("CHECK OK")
        return 0
    if ns.prefix_ab:
        result = prefix_ab(model, params, ns)
        for line in result["gates"]:
            print(line, flush=True)
        if ns.json:
            with open(ns.json, "w") as f:
                json.dump(result, f, indent=1, sort_keys=True)
            print(f"wrote {ns.json}")
        if ns.check:
            if not result["ok"]:
                print("CHECK FAILED: prefix-cache gates (see above)",
                      file=sys.stderr)
                return 1
            print("CHECK OK")
        return 0
    if ns.spec_ab:
        result = spec_ab(model, params, ns)
        for line in result["gates"]:
            print(line, flush=True)
        if ns.json:
            with open(ns.json, "w") as f:
                json.dump(result, f, indent=1, sort_keys=True)
            print(f"wrote {ns.json}")
        if ns.check:
            if not result["ok"]:
                print("CHECK FAILED: speculative-decoding gates "
                      "(see above)", file=sys.stderr)
                return 1
            print("CHECK OK")
        return 0
    if ns.knob_ab:
        result = knob_ab(model, params, ns)
        for line in result["gates"]:
            print(line, flush=True)
        if ns.json:
            with open(ns.json, "w") as f:
                json.dump(result, f, indent=1, sort_keys=True)
            print(f"wrote {ns.json}")
        if ns.check:
            if not result["ok"]:
                print("CHECK FAILED: control-plane gates (see above)",
                      file=sys.stderr)
                return 1
            print("CHECK OK")
        return 0
    if ns.chaos:
        result = chaos_ab(model, params, ns)
        for line in result["gates"]:
            print(line, flush=True)
        if ns.json:
            with open(ns.json, "w") as f:
                json.dump(result, f, indent=1, sort_keys=True)
            print(f"wrote {ns.json}")
        if ns.check:
            if not result["ok"]:
                print("CHECK FAILED: overload gates (see above)",
                      file=sys.stderr)
                return 1
            print("CHECK OK")
        return 0
    result = sweep(model, params, ns)
    if "ab" in result:
        ab = result["ab"]
        shown = ("inf (static sustains 0)" if ab["ratio"] is None
                 else f"{ab['ratio']:.2f}")
        print(f"A/B at p99 TTFT <= {ns.slo_ttft_ms:.0f} ms: continuous "
              f"sustains {ab['continuous_sustained_qps']:.2f} qps vs "
              f"static {ab['static_sustained_qps']:.2f} qps "
              f"(ratio {shown}, bar {AB_MIN_RATIO})",
              flush=True)
    if ns.json:
        with open(ns.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
        print(f"wrote {ns.json}")
    if ns.check:
        ab = result["ab"]
        # ratio None = static sustained nothing at the SLO: continuous
        # wins by any margin, so the gate passes.
        if ab["ratio"] is not None and ab["ratio"] < AB_MIN_RATIO:
            print(f"CHECK FAILED: continuous/static sustained-goodput "
                  f"ratio {ab['ratio']:.3f} < {AB_MIN_RATIO}",
                  file=sys.stderr)
            return 1
        print("CHECK OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
