"""fp-vs-int8 quality measurement: decode weights AND training paths.

Two harnesses in one module (they gate the same question — how much
does int8 cost? — at the two places the framework spends int8):

* the original **decode-weight** harness (below): perplexity ratio and
  greedy agreement of per-channel int8-quantized decode weights;
* the **loss-trajectory** harness (``--trajectory``): train the tiny
  GPT LM workload twice from the same seed — an fp32 baseline and a
  quantized variant (``--grad_comm_dtype int8`` wire and/or
  ``--matmul_dtype int8|fp8|bf16`` compute) — and measure the per-step
  loss deviation against a PINNED envelope (:data:`TRAJ_ENVELOPE`).
  This is the quality gate for the training-side quantization (ISSUE 6
  acceptance: equal convergence, measured not asserted — the harness
  reports the verdict; the full-suite lane asserts it).

Original decode-harness notes follow.

fp-vs-int8 decode-quality measurement (BASELINE.md round 3).

Applies the decode path's per-output-channel int8 quantization
(`ops.decode_kernel.quantize_cols`, the one definition shared by fused and
unfused ``--decode_int8``) to a dequantized copy of the GPT weights, then
reports the teacher-forced perplexity ratio and the greedy-decode
agreement against the fp weights.  The quantization-noise numbers are
device-independent — the same dequantized weights produce the same
logits — so this runs anywhere; the throughput rows in BASELINE.md are
what need the chip.

This harness is a conservative UPPER BOUND on the deployed path's
damage, for two documented reasons: (a) the q·scale product is re-rounded
to the param dtype (one extra bf16 rounding the deployed
``(x @ w8)·fp32_scale`` form avoids), and (b) quantizing the tied token
table also perturbs the input-embedding lookup, which the deployed path
keeps in fp (only the head-side copy is quantized in ``_decode_pack``).
Both effects ADD noise here, so a near-1.0 perplexity ratio from this
harness implies at-least-as-good deployed quality.

    python -m dtf_tpu.bench.int8_quality [--preset gpt2_small]
        [--batch 8] [--seq 512] [--gen 256] [--ckpt DIR]

``--ckpt`` scores TRAINED weights (a checkpoint directory written by the
trainer's CheckpointManager) instead of random init.  This matters
because random-init weights have benign per-channel dynamic range;
training grows outlier channels — the case per-channel int8 quantization
exists for — so the random-init ratio likely overstates the deployed
quality margin (r3 VERDICT weak #4).  ``scale_stats`` quantifies exactly
that: the per-matrix max/median ratio of the per-output-channel scales
(1.0 = perfectly uniform channels; large = outliers dominate).
"""

from __future__ import annotations

import argparse


def dequantized_params(params):
    """params with every decode-quantized operand replaced by its
    dequantize(quantize(w)) round trip: qkv / o / fc1 / fc2(, gate) and
    the tied vocab head, per ``GPT._decode_pack``'s contract (see the
    module docstring for the two upper-bound caveats)."""
    import jax.numpy as jnp

    from dtf_tpu.ops.decode_kernel import quantize_cols

    def dq(w):
        q, s = quantize_cols(w)
        return (q.astype(jnp.float32) * s).astype(w.dtype)

    lay = dict(params["layers"])
    attn = dict(lay["attn"])
    for k in ("q", "k", "v"):
        e = dict(attn[k])
        n_l, d = e["w"].shape[0], e["w"].shape[1]
        e["w"] = dq(e["w"].reshape(n_l, d, -1)).reshape(e["w"].shape)
        attn[k] = e
    e = dict(attn["o"])
    n_l, d = e["w"].shape[0], e["w"].shape[-1]
    e["w"] = dq(e["w"].reshape(n_l, -1, d)).reshape(e["w"].shape)
    attn["o"] = e
    lay["attn"] = attn
    for k in ("fc1", "fc2", "fc_gate"):
        if k in lay:
            e = dict(lay[k])
            e["w"] = dq(e["w"])
            lay[k] = e
    out = dict(params)
    out["layers"] = lay
    tok = dict(out["tok"])
    tok["table"] = dq(tok["table"].T).T
    out["tok"] = tok
    return out


def scale_stats(params, cfg) -> dict:
    """Per-output-channel scale dispersion of every decode-quantized
    matrix: ratio = max(scale)/median(scale) per matrix (per layer for
    stacked weights).  Near 1.0 means channels are uniform (int8 is
    easy); large ratios mean outlier channels emerged — the regime
    per-channel quantization exists for.  The scales are read off
    ``fused_decode_pack(int8=True)`` (plus ``_decode_pack``'s head
    quantization), i.e. the DEPLOYED layouts, so the stat cannot drift
    from what the kernel actually quantizes.  Returns the worst and
    median ratio over all matrices plus a per-family breakdown."""
    import jax
    import numpy as np

    from dtf_tpu.ops.decode_kernel import fused_decode_pack, quantize_cols

    def ratios(sc):
        s = np.asarray(sc, np.float64)
        s = s.reshape(-1, s.shape[-1])          # (L|1, N)
        med = np.median(s, axis=-1)
        return (s.max(axis=-1) / np.maximum(med, 1e-30)).tolist()

    # jit: at GPT-2-small scale an eager op-by-op quantization of ~124M
    # params is seconds of host time.
    pack = jax.jit(lambda p: fused_decode_pack(p, cfg, int8=True))(params)
    fams = {key[2:]: ratios(pack[key + "_sc"])
            for key in ("w_qkv", "w_o", "w_fc1", "w_fc2", "w_gate")
            if key + "_sc" in pack}
    head_sc = jax.jit(
        lambda t: quantize_cols(t.T)[1])(params["tok"]["table"])  # as _decode_pack
    fams["head"] = ratios(head_sc)
    allr = [r for v in fams.values() for r in v]
    return {
        "max_scale_ratio": float(np.max(allr)),
        "median_scale_ratio": float(np.median(allr)),
        "per_family_max": {k: float(np.max(v)) for k, v in fams.items()},
    }


def load_checkpoint_params(ckpt_dir: str):
    """Load the params subtree from a trainer CheckpointManager directory
    (no state template needed: orbax restores with saved metadata).
    Deliberate tradeoff: the whole TrainState (params + optimizer
    moments, ~3x the params bytes) is materialized and the rest dropped —
    a params-only orbax partial restore needs a state template this
    harness by design does not have.  ~1 GB transient host memory at
    GPT-2-small scale; acceptable for an offline quality harness."""
    import orbax.checkpoint as ocp

    import contextlib

    with contextlib.closing(ocp.CheckpointManager(ckpt_dir)) as mgr:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps under {ckpt_dir}")
        state = mgr.restore(step)
    return state["params"], step


def _load_params_for(model_cfg, ckpt: str):
    """Checkpoint params for a model config, with the position-table
    bounds guard (positions beyond the trained table would be a SILENT
    clamped gather — garbage numbers that look valid).  Shared by run()
    and kv_run() so neither can drop the check."""
    import jax
    import jax.numpy as jnp

    params, step = load_checkpoint_params(ckpt)
    params = jax.tree_util.tree_map(jnp.asarray, params)
    if "pos" in params:
        avail = params["pos"]["table"].shape[0]
        if model_cfg.max_len > avail:
            raise ValueError(
                f"checkpoint position table covers {avail} positions "
                f"but --seq/--gen need {model_cfg.max_len}; rerun with "
                f"--seq/--gen within the trained max_len ({avail})")
    return params, step


def run(preset: str = "gpt2_small", batch: int = 8, seq: int = 512,
        gen: int = 256, seed: int = 0, ckpt: str | None = None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.from_preset(preset, dtype=jnp.bfloat16,
                                max_len=max(seq, gen + 8))
    model = GPT(cfg)
    ckpt_step = None
    if ckpt is not None:
        params, ckpt_step = _load_params_for(cfg, ckpt)
    else:
        params = model.init(jax.random.key(seed))
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16),
                                    params)
    p8 = jax.jit(dequantized_params)(params)

    toks = jnp.asarray(synthetic_text(batch, seq, cfg.vocab_size,
                                      seed=seed + 9))
    loss_fn = jax.jit(lambda p, t: model.loss(p, {"tokens": t})[0])
    l_fp = float(loss_fn(params, toks))
    l_i8 = float(loss_fn(p8, toks))

    prompt = toks[:1, :8]
    g = jax.jit(lambda p, pr: model.generate(p, pr, gen, temperature=0.0))
    a = np.asarray(g(params, prompt))
    b = np.asarray(g(p8, prompt))
    agree = float((a[0, 8:] == b[0, 8:]).mean())
    div = int(np.argmax(a[0, 8:] != b[0, 8:])) if agree < 1.0 else gen
    out = {
        "tokens_scored": batch * (seq - 1),
        "loss_fp": l_fp, "loss_int8": l_i8,
        "ppl_ratio": float(np.exp(l_i8 - l_fp)),
        "greedy_agreement": agree,
        "first_divergence": div,
        "gen_tokens": gen,
        "weights": "random-init" if ckpt is None else f"trained ({ckpt})",
        "ckpt_step": ckpt_step,
    }
    out.update(scale_stats(params, cfg))
    return out


def kv_run(preset: str = "gpt2_small", batch: int = 4, seq: int = 256,
           seed: int = 0, prompt_len: int = 8,
           ckpt: str | None = None) -> dict:
    """KV-cache int8 quality: teacher-forced perplexity through the FUSED
    DECODE path with an fp cache vs an int8 cache (``quantize_rows``).

    Weight quantization is measured by ``run`` on the parallel forward;
    the KV cache only exists on the decode path, so its damage must be
    measured there: feed the ground-truth token at every position and
    score the next-token log-prob, once per cache mode.  Also returns
    ``fp_vs_parallel_delta`` — the fp-cache decode loss minus the same
    positions' loss from the parallel forward — as a self-check of the
    harness (must be ~bf16 noise; a bug in the decode loop would show
    here first).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.from_preset(preset, dtype=jnp.bfloat16,
                                max_len=max(seq, 128))
    model = GPT(cfg)
    if ckpt is not None:
        params, _ = _load_params_for(cfg, ckpt)
    else:
        params = model.init(jax.random.key(seed))
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16),
                                    params)
    if seq - 1 <= prompt_len:
        raise ValueError(f"seq ({seq}) must exceed prompt_len + 1 "
                         f"({prompt_len + 1}): nothing to teacher-force")
    toks = jnp.asarray(synthetic_text(batch, seq, cfg.vocab_size,
                                      seed=seed + 9))
    positions = jnp.arange(prompt_len, seq - 1)

    import functools

    @functools.partial(jax.jit, static_argnums=(2,))
    def decode_loss(params, toks, kv_int8):
        cache, _ = model._prefill_cache(params, toks[:, :prompt_len],
                                        model._cache_len(seq))
        pack, head_q, kv = model._fused_decode_setup(
            params, cache, False, kv_int8)

        def step(carry, pos):
            kv, total = carry
            tok = lax.dynamic_slice(toks, (0, pos), (batch, 1))
            logits, kv = model._fused_token_logits(
                params, pack, head_q, kv, tok, pos)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            tgt = lax.dynamic_slice(toks, (0, pos + 1), (batch, 1))[:, 0]
            total += -jnp.take_along_axis(logp, tgt[:, None], 1).sum()
            return (kv, total), None

        (_, total), _ = lax.scan(step, (kv, jnp.float32(0)), positions)
        return total / (batch * positions.size)

    l_fp = float(decode_loss(params, toks, False))
    l_i8 = float(decode_loss(params, toks, True))

    # Same positions' loss from the parallel forward (harness self-check):
    # the decode loop scores targets prompt_len+1 .. seq-1 (predicted from
    # rows prompt_len .. seq-2), so slice exactly those.
    logits = model.apply(params, toks).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    pred_rows = logp[:, prompt_len:seq - 1, :]
    tgt = toks[:, prompt_len + 1:seq]
    par = float(-jnp.take_along_axis(
        pred_rows, tgt[..., None], -1).mean())
    return {
        "tokens_scored": batch * int(positions.size),
        "loss_fp_cache": l_fp, "loss_int8_cache": l_i8,
        "kv_ppl_ratio": float(np.exp(l_i8 - l_fp)),
        "fp_vs_parallel_delta": l_fp - par,
        "weights": "random-init" if ckpt is None else f"trained ({ckpt})",
    }


#: The pinned loss envelope the quantized trajectory must stay inside:
#: per-step relative deviation from the fp32 baseline, and the final-
#: step deviation (tighter — early steps see the largest gradients and
#: the largest rounding noise; convergence is judged at the end).
#: Changing these numbers is changing the quality bar: do it in review,
#: not in a failing run.
TRAJ_ENVELOPE = {"max_rel_dev": 0.02, "final_rel_dev": 0.01}


def traj_run(steps: int = 24, batch: int = 16, seq: int = 64,
             seed: int = 0, grad_sync: str = "zero1",
             grad_comm_dtype: "str | None" = "int8",
             matmul_dtype: str = "fp32",
             quant_rounding: str = "nearest",
             bucket_mb: float = 0.25) -> dict:
    """Loss-trajectory A/B on the LM workload: fp32 baseline vs the
    quantized variant, same seed, same batches, same step count.

    Baseline: ``--grad_sync dense``, exact f32 wire, fp32 matmuls.
    Variant: the requested ``grad_sync`` strategy with
    ``grad_comm_dtype`` on the wire and ``matmul_dtype`` in the forward.
    Runs on whatever mesh the backend offers (``--simulated_devices 8``
    for the wire A/B — a 1-device mesh makes every collective the
    identity and the wire comparison vacuous, flagged in the output).

    Returns per-step losses for both runs, the max/final relative
    deviations, and the PINNED-envelope verdict (measured, not
    asserted)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu import optim
    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.parallel.grad_sync import GradSyncEngine
    from dtf_tpu.parallel.mesh import local_mesh
    from dtf_tpu.train.trainer import (init_state, make_train_step,
                                       put_global_batch)

    mesh = local_mesh("data=-1")
    n_dev = int(mesh.shape["data"])
    toks = np.asarray(synthetic_text(batch * steps, seq, 128,
                                     seed=seed + 9))

    def run(variant: bool):
        cfg = GPTConfig.tiny(
            matmul_dtype=matmul_dtype if variant else "fp32")
        model = GPT(cfg)
        opt = optim.adam(1e-3)
        eng = None
        cd = grad_comm_dtype if variant else None
        strat = grad_sync if variant else "dense"
        if strat != "dense":
            eng = GradSyncEngine(
                strat, opt, mesh, bucket_mb=bucket_mb, comm_dtype=cd,
                quant_rounding=quant_rounding).prepare(
                    jax.eval_shape(model.init, jax.random.key(seed + 1)))
        state = init_state(model, opt, seed=seed + 1, mesh=mesh,
                           grad_sync=eng)
        step = make_train_step(
            model.loss, opt, mesh, mode="explicit", donate=False,
            grad_sync=eng, grad_comm_dtype=cd if eng is None else None,
            quant_rounding=quant_rounding)
        losses, qerr = [], None
        for i in range(steps):
            b = put_global_batch(mesh, toks[i * batch:(i + 1) * batch])
            state, m = step(state, b, jax.random.key(i))
            losses.append(float(m["loss"]))
            if "quant_error" in m:
                qerr = float(m["quant_error"])
        return losses, qerr

    base, _ = run(variant=False)
    quant, qerr = run(variant=True)
    dev = [abs(q - b) / max(abs(b), 1e-9) for b, q in zip(base, quant)]
    out = {
        "workload": "gpt_tiny_lm", "steps": steps,
        "global_batch": batch, "seq": seq, "data_axis": n_dev,
        "grad_sync": grad_sync, "grad_comm_dtype": grad_comm_dtype,
        "matmul_dtype": matmul_dtype, "quant_rounding": quant_rounding,
        "loss_fp32": base, "loss_quant": quant,
        "max_rel_dev": max(dev), "final_rel_dev": dev[-1],
        "quant_error_rms": qerr,
        "envelope": dict(TRAJ_ENVELOPE),
        "within_envelope": (max(dev) <= TRAJ_ENVELOPE["max_rel_dev"]
                            and dev[-1] <= TRAJ_ENVELOPE["final_rel_dev"]),
    }
    if n_dev == 1 and grad_comm_dtype not in (None, "f32"):
        out["warning"] = ("data axis is 1: collectives are the identity, "
                          "so the wire-dtype comparison is vacuous — rerun "
                          "with --simulated_devices 8")
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--preset", default="gpt2_small",
                        choices=["gpt2_small", "llama", "tiny"])
    # Defaults resolve per path (decode quality: 8/512; --trajectory:
    # 16/64) so an explicitly typed value is always honored as-is.
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--seq", type=int, default=None)
    parser.add_argument("--gen", type=int, default=256)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--kv", action="store_true",
                        help="ALSO measure int8 KV-cache quality via "
                             "teacher-forced fused decode (kv_run)")
    parser.add_argument("--ckpt", default=None, metavar="DIR",
                        help="score TRAINED weights from this trainer "
                             "checkpoint directory (must match --preset); "
                             "default: random init")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (reliable even when "
                             "a TPU plugin is registered: jax.config "
                             "beats the env var — see "
                             ".claude/skills/verify)")
    parser.add_argument("--trajectory", action="store_true",
                        help="loss-trajectory quality harness instead of "
                             "the decode-weight one: fp32 vs quantized "
                             "TRAINING run on the tiny GPT LM workload, "
                             "measured against the pinned envelope")
    parser.add_argument("--traj_steps", type=int, default=24)
    parser.add_argument("--grad_sync", default="zero1",
                        choices=["dense", "zero1", "zero1_overlap"])
    parser.add_argument("--grad_comm_dtype", default="int8",
                        choices=["f32", "bf16", "int8", "int8_ring"],
                        help="gradient wire format for the quantized leg "
                             "(int8_ring: per-hop requantizing segmented "
                             "ring reduce-scatter)")
    parser.add_argument("--matmul_dtype", default="fp32",
                        choices=["fp32", "bf16", "int8", "fp8"],
                        help="forward compute format for the quantized leg")
    parser.add_argument("--quant_rounding", default="nearest",
                        choices=["nearest", "stochastic"])
    parser.add_argument("--simulated_devices", type=int, default=0,
                        help="run the trajectory A/B on N simulated CPU "
                             "devices (the wire comparison needs a "
                             "multi-way data axis)")
    parser.add_argument("--json", action="store_true",
                        help="emit the trajectory result as JSON")
    ns = parser.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if ns.simulated_devices > 0:
        from dtf_tpu.cluster import simulate_cpu_devices
        simulate_cpu_devices(ns.simulated_devices)
    if ns.trajectory:
        import json
        if (ns.quant_rounding == "stochastic"
                and ns.grad_comm_dtype not in ("int8", "int8_ring")):
            # Same rejection as TrainConfig.validate: only the int8 wires
            # consult the rounding mode, and a report header claiming
            # "rounding=stochastic" over a wire that never rounds would
            # poison the trajectory attribution this harness exists for.
            parser.error("--quant_rounding stochastic only applies to "
                         "--grad_comm_dtype int8/int8_ring")
        cd = None if ns.grad_comm_dtype == "f32" else ns.grad_comm_dtype
        r = traj_run(steps=ns.traj_steps,
                     batch=16 if ns.batch is None else ns.batch,
                     seq=64 if ns.seq is None else ns.seq,
                     seed=ns.seed, grad_sync=ns.grad_sync,
                     grad_comm_dtype=cd, matmul_dtype=ns.matmul_dtype,
                     quant_rounding=ns.quant_rounding)
        if ns.json:
            print(json.dumps(r, indent=1, sort_keys=True))
            return 0
        print(f"LM loss-trajectory A/B ({r['workload']}, {r['steps']} "
              f"steps, data axis {r['data_axis']}): "
              f"wire={r['grad_comm_dtype'] or 'f32'} "
              f"matmul={r['matmul_dtype']} "
              f"rounding={r['quant_rounding']}")
        for i, (b, q) in enumerate(zip(r["loss_fp32"], r["loss_quant"])):
            print(f"  step {i:>3}  fp32 {b:.6f}  quant {q:.6f}  "
                  f"rel dev {abs(q - b) / max(abs(b), 1e-9):.2e}")
        print(f"max rel dev {r['max_rel_dev']:.4%} "
              f"(envelope {r['envelope']['max_rel_dev']:.2%}); "
              f"final {r['final_rel_dev']:.4%} "
              f"(envelope {r['envelope']['final_rel_dev']:.2%})"
              + (f"; wire quant error rms "
                 f"{r['quant_error_rms']:.2e}"
                 if r["quant_error_rms"] is not None else ""))
        print("within envelope: " + ("YES" if r["within_envelope"]
                                     else "NO"))
        if "warning" in r:
            print(f"WARNING: {r['warning']}")
        return 0
    batch = 8 if ns.batch is None else ns.batch
    seq = 512 if ns.seq is None else ns.seq
    r = run(ns.preset, batch, seq, ns.gen, ns.seed, ckpt=ns.ckpt)
    print(f"weights: {r['weights']}"
          + (f" step {r['ckpt_step']}" if r['ckpt_step'] is not None else ""))
    print(f"tokens scored: {r['tokens_scored']}")
    print(f"fp loss {r['loss_fp']:.6f}   int8 loss {r['loss_int8']:.6f}")
    print(f"perplexity ratio {r['ppl_ratio']:.6f} "
          f"({(r['ppl_ratio'] - 1) * 100:+.4f}%)")
    print(f"greedy agreement over {r['gen_tokens']}: "
          f"{r['greedy_agreement']:.4f} "
          f"(first divergence at {r['first_divergence']})")
    print(f"per-channel scale dispersion (max/median per matrix): "
          f"worst {r['max_scale_ratio']:.2f}, "
          f"median {r['median_scale_ratio']:.2f}, by family "
          + ", ".join(f"{k}={v:.2f}"
                      for k, v in r['per_family_max'].items()))
    if ns.kv:
        kr = kv_run(ns.preset, batch, seq, ns.seed, ckpt=ns.ckpt)
        print(f"KV-cache int8 (teacher-forced fused decode, "
              f"{kr['tokens_scored']} tokens): ppl ratio "
              f"{kr['kv_ppl_ratio']:.6f} "
              f"({(kr['kv_ppl_ratio'] - 1) * 100:+.4f}%); harness "
              f"self-check fp-decode vs parallel delta "
              f"{kr['fp_vs_parallel_delta']:+.5f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
