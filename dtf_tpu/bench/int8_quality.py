"""fp-vs-int8 decode-quality measurement (BASELINE.md round 3).

Applies the decode path's per-output-channel int8 quantization
(`ops.decode_kernel.quantize_cols`, the one definition shared by fused and
unfused ``--decode_int8``) to a dequantized copy of the GPT weights, then
reports the teacher-forced perplexity ratio and the greedy-decode
agreement against the fp weights.  The quantization-noise numbers are
device-independent — the same dequantized weights produce the same
logits — so this runs anywhere; the throughput rows in BASELINE.md are
what need the chip.

This harness is a conservative UPPER BOUND on the deployed path's
damage, for two documented reasons: (a) the q·scale product is re-rounded
to the param dtype (one extra bf16 rounding the deployed
``(x @ w8)·fp32_scale`` form avoids), and (b) quantizing the tied token
table also perturbs the input-embedding lookup, which the deployed path
keeps in fp (only the head-side copy is quantized in ``_decode_pack``).
Both effects ADD noise here, so a near-1.0 perplexity ratio from this
harness implies at-least-as-good deployed quality.

    python -m dtf_tpu.bench.int8_quality [--preset gpt2_small]
        [--batch 8] [--seq 512] [--gen 256] [--ckpt DIR]

``--ckpt`` scores TRAINED weights (a checkpoint directory written by the
trainer's CheckpointManager) instead of random init.  This matters
because random-init weights have benign per-channel dynamic range;
training grows outlier channels — the case per-channel int8 quantization
exists for — so the random-init ratio likely overstates the deployed
quality margin (r3 VERDICT weak #4).  ``scale_stats`` quantifies exactly
that: the per-matrix max/median ratio of the per-output-channel scales
(1.0 = perfectly uniform channels; large = outliers dominate).
"""

from __future__ import annotations

import argparse


def dequantized_params(params):
    """params with every decode-quantized operand replaced by its
    dequantize(quantize(w)) round trip: qkv / o / fc1 / fc2(, gate) and
    the tied vocab head, per ``GPT._decode_pack``'s contract (see the
    module docstring for the two upper-bound caveats)."""
    import jax.numpy as jnp

    from dtf_tpu.ops.decode_kernel import quantize_cols

    def dq(w):
        q, s = quantize_cols(w)
        return (q.astype(jnp.float32) * s).astype(w.dtype)

    lay = dict(params["layers"])
    attn = dict(lay["attn"])
    for k in ("q", "k", "v"):
        e = dict(attn[k])
        n_l, d = e["w"].shape[0], e["w"].shape[1]
        e["w"] = dq(e["w"].reshape(n_l, d, -1)).reshape(e["w"].shape)
        attn[k] = e
    e = dict(attn["o"])
    n_l, d = e["w"].shape[0], e["w"].shape[-1]
    e["w"] = dq(e["w"].reshape(n_l, -1, d)).reshape(e["w"].shape)
    attn["o"] = e
    lay["attn"] = attn
    for k in ("fc1", "fc2", "fc_gate"):
        if k in lay:
            e = dict(lay[k])
            e["w"] = dq(e["w"])
            lay[k] = e
    out = dict(params)
    out["layers"] = lay
    tok = dict(out["tok"])
    tok["table"] = dq(tok["table"].T).T
    out["tok"] = tok
    return out


def scale_stats(params, cfg) -> dict:
    """Per-output-channel scale dispersion of every decode-quantized
    matrix: ratio = max(scale)/median(scale) per matrix (per layer for
    stacked weights).  Near 1.0 means channels are uniform (int8 is
    easy); large ratios mean outlier channels emerged — the regime
    per-channel quantization exists for.  The scales are read off
    ``fused_decode_pack(int8=True)`` (plus ``_decode_pack``'s head
    quantization), i.e. the DEPLOYED layouts, so the stat cannot drift
    from what the kernel actually quantizes.  Returns the worst and
    median ratio over all matrices plus a per-family breakdown."""
    import jax
    import numpy as np

    from dtf_tpu.ops.decode_kernel import fused_decode_pack, quantize_cols

    def ratios(sc):
        s = np.asarray(sc, np.float64)
        s = s.reshape(-1, s.shape[-1])          # (L|1, N)
        med = np.median(s, axis=-1)
        return (s.max(axis=-1) / np.maximum(med, 1e-30)).tolist()

    # jit: at GPT-2-small scale an eager op-by-op quantization of ~124M
    # params is seconds of host time.
    pack = jax.jit(lambda p: fused_decode_pack(p, cfg, int8=True))(params)
    fams = {key[2:]: ratios(pack[key + "_sc"])
            for key in ("w_qkv", "w_o", "w_fc1", "w_fc2", "w_gate")
            if key + "_sc" in pack}
    head_sc = jax.jit(
        lambda t: quantize_cols(t.T)[1])(params["tok"]["table"])  # as _decode_pack
    fams["head"] = ratios(head_sc)
    allr = [r for v in fams.values() for r in v]
    return {
        "max_scale_ratio": float(np.max(allr)),
        "median_scale_ratio": float(np.median(allr)),
        "per_family_max": {k: float(np.max(v)) for k, v in fams.items()},
    }


def load_checkpoint_params(ckpt_dir: str):
    """Load the params subtree from a trainer CheckpointManager directory
    (no state template needed: orbax restores with saved metadata).
    Deliberate tradeoff: the whole TrainState (params + optimizer
    moments, ~3x the params bytes) is materialized and the rest dropped —
    a params-only orbax partial restore needs a state template this
    harness by design does not have.  ~1 GB transient host memory at
    GPT-2-small scale; acceptable for an offline quality harness."""
    import orbax.checkpoint as ocp

    import contextlib

    with contextlib.closing(ocp.CheckpointManager(ckpt_dir)) as mgr:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps under {ckpt_dir}")
        state = mgr.restore(step)
    return state["params"], step


def run(preset: str = "gpt2_small", batch: int = 8, seq: int = 512,
        gen: int = 256, seed: int = 0, ckpt: str | None = None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.from_preset(preset, dtype=jnp.bfloat16,
                                max_len=max(seq, gen + 8))
    model = GPT(cfg)
    ckpt_step = None
    if ckpt is not None:
        params, ckpt_step = load_checkpoint_params(ckpt)
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if "pos" in params:
            # Positions beyond the trained table would be a SILENT
            # out-of-bounds gather (JAX clamps) — garbage numbers that
            # look like a valid measurement.
            avail = params["pos"]["table"].shape[0]
            if cfg.max_len > avail:
                raise ValueError(
                    f"checkpoint position table covers {avail} positions "
                    f"but --seq/--gen need {cfg.max_len}; rerun with "
                    f"--seq/--gen within the trained max_len ({avail})")
    else:
        params = model.init(jax.random.key(seed))
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16),
                                    params)
    p8 = jax.jit(dequantized_params)(params)

    toks = jnp.asarray(synthetic_text(batch, seq, cfg.vocab_size,
                                      seed=seed + 9))
    loss_fn = jax.jit(lambda p, t: model.loss(p, {"tokens": t})[0])
    l_fp = float(loss_fn(params, toks))
    l_i8 = float(loss_fn(p8, toks))

    prompt = toks[:1, :8]
    g = jax.jit(lambda p, pr: model.generate(p, pr, gen, temperature=0.0))
    a = np.asarray(g(params, prompt))
    b = np.asarray(g(p8, prompt))
    agree = float((a[0, 8:] == b[0, 8:]).mean())
    div = int(np.argmax(a[0, 8:] != b[0, 8:])) if agree < 1.0 else gen
    out = {
        "tokens_scored": batch * (seq - 1),
        "loss_fp": l_fp, "loss_int8": l_i8,
        "ppl_ratio": float(np.exp(l_i8 - l_fp)),
        "greedy_agreement": agree,
        "first_divergence": div,
        "gen_tokens": gen,
        "weights": "random-init" if ckpt is None else f"trained ({ckpt})",
        "ckpt_step": ckpt_step,
    }
    out.update(scale_stats(params, cfg))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--preset", default="gpt2_small",
                        choices=["gpt2_small", "llama", "tiny"])
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=512)
    parser.add_argument("--gen", type=int, default=256)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ckpt", default=None, metavar="DIR",
                        help="score TRAINED weights from this trainer "
                             "checkpoint directory (must match --preset); "
                             "default: random init")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (reliable even when "
                             "a TPU plugin is registered: jax.config "
                             "beats the env var — see "
                             ".claude/skills/verify)")
    ns = parser.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    r = run(ns.preset, ns.batch, ns.seq, ns.gen, ns.seed, ckpt=ns.ckpt)
    print(f"weights: {r['weights']}"
          + (f" step {r['ckpt_step']}" if r['ckpt_step'] is not None else ""))
    print(f"tokens scored: {r['tokens_scored']}")
    print(f"fp loss {r['loss_fp']:.6f}   int8 loss {r['loss_int8']:.6f}")
    print(f"perplexity ratio {r['ppl_ratio']:.6f} "
          f"({(r['ppl_ratio'] - 1) * 100:+.4f}%)")
    print(f"greedy agreement over {r['gen_tokens']}: "
          f"{r['greedy_agreement']:.4f} "
          f"(first divergence at {r['first_divergence']})")
    print(f"per-channel scale dispersion (max/median per matrix): "
          f"worst {r['max_scale_ratio']:.2f}, "
          f"median {r['median_scale_ratio']:.2f}, by family "
          + ", ".join(f"{k}={v:.2f}"
                      for k, v in r['per_family_max'].items()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
