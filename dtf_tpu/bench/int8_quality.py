"""fp-vs-int8 decode-quality measurement (BASELINE.md round 3).

Applies the decode path's per-output-channel int8 quantization
(`ops.decode_kernel.quantize_cols`, the one definition shared by fused and
unfused ``--decode_int8``) to a dequantized copy of the GPT weights, then
reports the teacher-forced perplexity ratio and the greedy-decode
agreement against the fp weights.  The quantization-noise numbers are
device-independent — the same dequantized weights produce the same
logits — so this runs anywhere; the throughput rows in BASELINE.md are
what need the chip.

This harness is a conservative UPPER BOUND on the deployed path's
damage, for two documented reasons: (a) the q·scale product is re-rounded
to the param dtype (one extra bf16 rounding the deployed
``(x @ w8)·fp32_scale`` form avoids), and (b) quantizing the tied token
table also perturbs the input-embedding lookup, which the deployed path
keeps in fp (only the head-side copy is quantized in ``_decode_pack``).
Both effects ADD noise here, so a near-1.0 perplexity ratio from this
harness implies at-least-as-good deployed quality.

    python -m dtf_tpu.bench.int8_quality [--preset gpt2_small]
        [--batch 8] [--seq 512] [--gen 256]
"""

from __future__ import annotations

import argparse


def dequantized_params(params):
    """params with every decode-quantized operand replaced by its
    dequantize(quantize(w)) round trip: qkv / o / fc1 / fc2(, gate) and
    the tied vocab head, per ``GPT._decode_pack``'s contract (see the
    module docstring for the two upper-bound caveats)."""
    import jax.numpy as jnp

    from dtf_tpu.ops.decode_kernel import quantize_cols

    def dq(w):
        q, s = quantize_cols(w)
        return (q.astype(jnp.float32) * s).astype(w.dtype)

    lay = dict(params["layers"])
    attn = dict(lay["attn"])
    for k in ("q", "k", "v"):
        e = dict(attn[k])
        n_l, d = e["w"].shape[0], e["w"].shape[1]
        e["w"] = dq(e["w"].reshape(n_l, d, -1)).reshape(e["w"].shape)
        attn[k] = e
    e = dict(attn["o"])
    n_l, d = e["w"].shape[0], e["w"].shape[-1]
    e["w"] = dq(e["w"].reshape(n_l, -1, d)).reshape(e["w"].shape)
    attn["o"] = e
    lay["attn"] = attn
    for k in ("fc1", "fc2", "fc_gate"):
        if k in lay:
            e = dict(lay[k])
            e["w"] = dq(e["w"])
            lay[k] = e
    out = dict(params)
    out["layers"] = lay
    tok = dict(out["tok"])
    tok["table"] = dq(tok["table"].T).T
    out["tok"] = tok
    return out


def run(preset: str = "gpt2_small", batch: int = 8, seq: int = 512,
        gen: int = 256, seed: int = 0) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig

    cfg = {"gpt2_small": GPTConfig.gpt2_small,
           "llama": GPTConfig.llama_style,
           "tiny": GPTConfig.tiny}[preset](dtype=jnp.bfloat16,
                                           max_len=max(seq, gen + 8))
    model = GPT(cfg)
    params = model.init(jax.random.key(seed))
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16),
                                    params)
    p8 = jax.jit(dequantized_params)(params)

    toks = jnp.asarray(synthetic_text(batch, seq, cfg.vocab_size,
                                      seed=seed + 9))
    loss_fn = jax.jit(lambda p, t: model.loss(p, {"tokens": t})[0])
    l_fp = float(loss_fn(params, toks))
    l_i8 = float(loss_fn(p8, toks))

    prompt = toks[:1, :8]
    g = jax.jit(lambda p, pr: model.generate(p, pr, gen, temperature=0.0))
    a = np.asarray(g(params, prompt))
    b = np.asarray(g(p8, prompt))
    agree = float((a[0, 8:] == b[0, 8:]).mean())
    div = int(np.argmax(a[0, 8:] != b[0, 8:])) if agree < 1.0 else gen
    return {
        "tokens_scored": batch * (seq - 1),
        "loss_fp": l_fp, "loss_int8": l_i8,
        "ppl_ratio": float(np.exp(l_i8 - l_fp)),
        "greedy_agreement": agree,
        "first_divergence": div,
        "gen_tokens": gen,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--preset", default="gpt2_small",
                        choices=["gpt2_small", "llama", "tiny"])
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=512)
    parser.add_argument("--gen", type=int, default=256)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (reliable even when "
                             "a TPU plugin is registered: jax.config "
                             "beats the env var — see "
                             ".claude/skills/verify)")
    ns = parser.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    r = run(ns.preset, ns.batch, ns.seq, ns.gen, ns.seed)
    print(f"tokens scored: {r['tokens_scored']}")
    print(f"fp loss {r['loss_fp']:.6f}   int8 loss {r['loss_int8']:.6f}")
    print(f"perplexity ratio {r['ppl_ratio']:.6f} "
          f"({(r['ppl_ratio'] - 1) * 100:+.4f}%)")
    print(f"greedy agreement over {r['gen_tokens']}: "
          f"{r['greedy_agreement']:.4f} "
          f"(first divergence at {r['first_divergence']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
