"""Sharded matmul benchmark — the reference's headline metric, done right.

The reference *intended* a distributed 1000x1000 matmul benchmark
(``A,B = random_normal([1000,1000])`` on the PS, ``C = tf.matmul(A,B)``,
tf_distributed_1000Matrix.py:42-48) but its driver loop crashes with a
NameError before ever executing ``C`` (tf_distributed_1000Matrix.py:74; see
SURVEY.md §2.9).  Per BASELINE.json the metric is GFLOPs/chip + step-time
with a >=90%-of-roofline north star on the matmul.

TPU-native design decisions:

* operands live on device, sharded over the mesh with ``NamedSharding``
  (A row-sharded over ``data``, B column-sharded over ``tensor`` when those
  axes exist) — no parameter server, no per-step operand transfer (the
  reference would have pulled 2x4MB over gRPC per step);
* a *step* is a chain of ``iters_per_step`` dependent matmuls inside one
  compiled program (``A_{k+1} = A_k @ B``): dependent so XLA cannot CSE or
  hoist the loop body, chained inside ``lax.fori_loop`` so dispatch overhead
  is amortised — at N=1000 a single matmul is ~microseconds on one chip and
  dispatch-bound (SURVEY.md §6.1);
* bf16 by default (MXU-native), fp32 supported for parity with the
  reference's fp32 variables; operands are scaled ~N(0, 1/sqrt(N)) so the
  chain stays numerically bounded;
* timing via ``block_until_ready`` (utils.timing), never raw ``time.time()``
  around an async dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu.parallel.mesh import local_mesh
from dtf_tpu.utils.timing import time_linfit

# Peak dense-matmul FLOP/s per chip, by (device kind substring, dtype).
# Public figures: v4 275 Tbf16 / 137.5 Tfp32-ish via bf16x3; v5e 197 Tbf16,
# v5p 459 Tbf16, v6e 918 Tbf16.  fp32 on MXU runs ~1/4-1/2 of bf16 depending
# on generation; we use bf16 numbers for the roofline target and report the
# dtype used.
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,   # aka v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
    "v6p": 4614e12 / 2,  # placeholder; updated when public
}


def peak_flops_per_chip(device: Optional[jax.Device] = None,
                        dtype: str = "bfloat16") -> Optional[float]:
    """Best-known peak FLOP/s for the device, or None if unknown (e.g. CPU)."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            if dtype in ("float32", "fp32"):
                return peak / 2
            return peak
    return None


@dataclasses.dataclass
class MatmulBenchConfig:
    n: int = 1000                 # reference shape, tf_distributed_1000Matrix.py:42-44
    dtype: str = "bfloat16"
    # Marginal timing: per-matmul device time = least-squares slope of
    # chain-length -> wall time over a geometric ladder.  The longest chain
    # is sized so its device time is about ``target_long_s`` (assuming ~50%
    # of roofline), keeping the ~tens-of-ms relay jitter small relative to
    # the fit range; fixed iteration counts would drown µs-scale matmuls
    # (N=1000 is ~20 µs/matmul) in that jitter.
    target_long_s: float = 1.2
    ladder_points: int = 4        # chain lengths: L, L/2, L/4, ...
    max_iters: int = 200_000
    reps: int = 5                 # timed repetitions of each chain length
    # Relay jitter is one-sided (only ever adds time), so best-of-reps is the
    # right estimator and more reps monotonically improves it.
    seed: int = 1                 # reference seed, tf_distributed.py:49
    mesh: Optional[Mesh] = None   # default: all local devices on a data axis


def _operand_shardings(mesh: Mesh) -> tuple[NamedSharding, NamedSharding]:
    """A row-sharded over data-like axes; B column-sharded over tensor."""
    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names) or None
    tensor = "tensor" if "tensor" in mesh.axis_names else None
    return (NamedSharding(mesh, P(data_axes, None)),
            NamedSharding(mesh, P(None, tensor)))


def build_step(mesh: Mesh, n: int, dtype: str, iters: int):
    """Compile one benchmark step: ``iters`` chained matmuls on the mesh."""
    a_sh, b_sh = _operand_shardings(mesh)

    @functools.partial(jax.jit, out_shardings=a_sh)
    def step(a, b):
        def body(_, acc):
            return acc @ b
        return lax.fori_loop(0, iters, body, a)

    return step, a_sh, b_sh


def make_operands(mesh: Mesh, n: int, dtype: str, seed: int):
    a_sh, b_sh = _operand_shardings(mesh)
    ka, kb = jax.random.split(jax.random.key(seed))
    scale = 1.0 / (n ** 0.5)  # keep the chained product bounded
    a = jax.device_put(jax.random.normal(ka, (n, n), jnp.dtype(dtype)) * scale, a_sh)
    b = jax.device_put(jax.random.normal(kb, (n, n), jnp.dtype(dtype)) * scale, b_sh)
    return a, b


def run_matmul_bench(cfg: MatmulBenchConfig) -> dict:
    """Run the benchmark; returns a flat dict of results (JSON-friendly)."""
    from dtf_tpu.telemetry import costobs

    mesh = cfg.mesh if cfg.mesh is not None else local_mesh("data=-1")
    a, b = make_operands(mesh, cfg.n, cfg.dtype, cfg.seed)

    flop = 2.0 * cfg.n ** 3
    peak = peak_flops_per_chip(mesh.devices.flat[0], cfg.dtype)
    peak_guess = peak or 100e9
    longest = int(cfg.target_long_s * 0.5 * peak_guess * mesh.size / flop)
    longest = max(16, min(longest, cfg.max_iters))
    ladder = sorted({max(2, longest >> i) for i in range(cfg.ladder_points)})

    # Cost observatory: every ladder point is its own compile — the
    # wrapper captures each as a bench/matmul CostCard at compile time
    # (the first call per point, which paid the compile anyway), so the
    # timed region is untouched.
    obs = costobs.get_observatory()
    compiles0 = obs.total_compiles()
    steps = {k: costobs.instrument(build_step(mesh, cfg.n, cfg.dtype, k)[0],
                                   "bench/matmul", (cfg.n, cfg.dtype, k))
             for k in ladder}

    # Vary the operand each call: the axon relay MEMOIZES repeat
    # executions with bitwise-identical inputs (returns ~instantly,
    # discovered round 3 — BASELINE.md "timing methodology correction"),
    # which would corrupt best-of-reps timing.  The factor must be
    # EXACTLY representable in the operand dtype or the cast makes it a
    # bitwise no-op (bf16 rounds 1 + k·1e-7 back to 1.0): 1 + k/64 is
    # exact in bf16/fp32 and distinct for 63 consecutive calls.  The
    # scale is a separate eagerly-dispatched op whose constant cost the
    # linfit intercept absorbs.
    counter = [0]

    def call(k):
        counter[0] += 1
        return steps[k](a * (1.0 + (counter[0] % 63) * 2.0 ** -6), b)

    fit = time_linfit(lambda k: (lambda: call(k)), ladder, reps=cfg.reps)

    n_chips = mesh.size
    flops_per_chip = flop / fit.per_iter_s / n_chips
    # Ledger columns (scripts/bench_ledger.py): the round's compile
    # count and the largest per-executable HBM claim, so --check-ledger
    # can name the regressed QUANTITY, not just the regressed rig.
    # Scoped to THIS ladder's geometry keys — the observatory is
    # process-wide, and an earlier arm's cards must not leak into this
    # run's row.
    obs.update_live_memory()
    mm_keys = {("bench/matmul", (cfg.n, cfg.dtype, k)) for k in ladder}
    mm_cards = [c for c in obs.cards() if c.key() in mm_keys]
    peak_hbm = max((c.peak_hbm_bytes for c in mm_cards
                    if c.peak_hbm_bytes is not None), default=None)
    return {
        "n_compiles": obs.total_compiles() - compiles0,
        "peak_hbm_bytes": peak_hbm,
        "n": cfg.n,
        "dtype": cfg.dtype,
        "n_chips": n_chips,
        "device_kind": getattr(mesh.devices.flat[0], "device_kind", "cpu"),
        "matmul_time_us": fit.per_iter_s * 1e6,
        "fit_overhead_ms": fit.overhead_s * 1e3,
        "ladder": [[k, round(t * 1e3, 2)] for k, t in fit.points],
        "tflops_per_chip": flops_per_chip / 1e12,
        "peak_tflops_per_chip": (peak / 1e12) if peak else None,
        "roofline_fraction": (flops_per_chip / peak) if peak else None,
    }


def sweep(ns=(1000, 1024, 2048, 4096, 8192), dtype: str = "bfloat16",
          mesh: Optional[Mesh] = None, reps: int = 5) -> list[dict]:
    """N-sweep to find where roofline is reachable (SURVEY.md §6.1: N=1000 is
    dispatch/HBM-bound; honesty requires showing the curve).  1024 is the
    128-lane-aligned neighbour of the reference's 1000 — the delta between
    them is pure padding waste (1000 pads to 1024 on the MXU, a
    (1000/1024)^3 = 93% intrinsic ceiling)."""
    out = []
    for n in ns:
        cfg = MatmulBenchConfig(n=n, dtype=dtype, mesh=mesh, reps=reps)
        out.append(run_matmul_bench(cfg))
    return out


def verify_correctness(mesh: Optional[Mesh] = None, n: int = 256,
                       dtype: str = "float32", seed: int = 1) -> float:
    """C == A@B check for the sharded matmul (SURVEY.md §4 integration test:
    'matmul benchmark correctness (C == A@B)').  Returns max abs error vs
    the unsharded host reference."""
    import numpy as np

    mesh = mesh if mesh is not None else local_mesh("data=-1")
    a, b = make_operands(mesh, n, dtype, seed)
    a_sh, b_sh = _operand_shardings(mesh)
    c = jax.jit(jnp.matmul, out_shardings=a_sh)(a, b)
    ref = np.asarray(a, dtype=np.float64) @ np.asarray(b, dtype=np.float64)
    return float(np.max(np.abs(np.asarray(c, dtype=np.float64) - ref)))
