"""Reproducible decode-throughput ladder — the honest decode number.

The only trustworthy per-token time through the axon relay is the
marginal one: the least-squares slope of ``max_new_tokens -> wall time``
over a ladder of generation lengths (``utils.timing.time_linfit``).
Spot timings carry ~50-250 ms of fixed relay cost per synced call, and
the relay *memoizes* bitwise-identical executions (BASELINE.md round-3
"timing methodology correction"), so this harness also perturbs the
prompt between repetitions — every timed call is a genuinely new
execution.

One command per BASELINE.md decode row::

    python -m dtf_tpu.bench.decode_ladder --preset gpt2_small \
        --mode fused --streams 32            # tiled fused kernel
    python -m dtf_tpu.bench.decode_ladder --preset llama \
        --mode fused --streams 1 --int8      # int8 weights in-kernel
    python -m dtf_tpu.bench.decode_ladder --preset gpt2_small \
        --mode fused --beam 4                # beam through the kernel

Serving-engine rungs (ISSUE 14) ride the SAME linfit methodology so
the unfused/fused/paged/speculative numbers are directly comparable::

    python -m dtf_tpu.bench.decode_ladder --preset tiny --mode paged \
        --streams 3                          # narrowed paged data path
    python -m dtf_tpu.bench.decode_ladder --preset tiny --mode paged \
        --no_narrow --pool_blocks 200        # baseline whole-pool arm
    python -m dtf_tpu.bench.decode_ladder --preset tiny --mode spec \
        --spec_k 4 --trace_vocab 12          # speculative decoding

``--json`` writes a ladder doc ``scripts/bench_ledger.py`` folds into
LEDGER.jsonl as a ``decode`` rig row (gated by
``python bench.py --check-ledger``); the decode-fast full-suite lane
A/Bs the paged arm against the baseline on tight AND oversized pools —
marginal ms/token must drop, and must be pool-size invariant only for
the narrowed arm.

The reference has no decode path at all (TF1 parameter-server MNIST
demo); these rows are framework-beyond-parity serving numbers.
"""

from __future__ import annotations

import argparse
import json


def _hbm_sampler(obs):
    """Per-invocation live-HBM watermark: ``sample()`` at ladder-point
    boundaries (OUTSIDE the timed closures) and once after the fit; the
    max of the samples is this run's ``peak_hbm_bytes`` ledger column —
    per-run by construction, so a previous arm in the same process
    cannot leak into this row.  ONE definition for both ladder entry
    points so the column's meaning cannot drift between them."""
    seen = [0.0]

    def sample():
        live = obs.update_live_memory()
        if live:
            seen[0] = max(seen[0], live)

    return seen, sample


def _finish_fit(out: dict, fit, streams: int) -> dict:
    """Shared fit -> report fields: the no-signal check and the
    tokens/s conversions (one definition for the generate-path and
    engine-path rungs)."""
    per_token_s = fit.per_iter_s
    out["ladder"] = [[k, round(t * 1e3, 2)] for k, t in fit.points]
    out["per_token_us"] = per_token_s * 1e6
    out["fit_overhead_ms"] = fit.overhead_s * 1e3
    times = [t for _, t in fit.points]
    if times[-1] <= times[0] or per_token_s <= 1e-9:
        out["tok_s_per_stream"] = out["tok_s_aggregate"] = None
        out["warning"] = ("non-positive slope — ladder is "
                          "noise-dominated; lengthen --ladder or raise "
                          "--reps")
    else:
        out["tok_s_per_stream"] = 1.0 / per_token_s
        out["tok_s_aggregate"] = streams / per_token_s
    return out


def run_engine(preset: str = "tiny", mode: str = "paged",
               streams: int = 3, ladder=(8, 16, 32), reps: int = 2,
               prompt_len: int = 8, seed: int = 0, block_size: int = 4,
               pool_blocks=None, narrow: bool = True, spec_k: int = 4,
               trace_vocab=None) -> dict:
    """Serving-engine ladder rung: drive a fresh ``ServingEngine`` on
    the wall clock for each (ladder point, rep) — ``streams`` requests,
    each generating ``max_new`` tokens — and linfit wall time against
    ``max_new``.  The marginal slope is the engine's whole per-token
    cost (dispatch, gather/scatter, host bookkeeping), which is exactly
    the quantity the narrowed data path and speculation attack.

    ``mode="paged"`` runs the plain decode path (``--no_narrow`` is the
    whole-pool/full-window baseline arm); ``mode="spec"`` arms the
    n-gram drafter.  ``pool_blocks`` oversizes the pool to probe
    pool-size (in)variance.
    """
    import jax
    import numpy as np

    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.serve import ServingEngine, WallClock, blocks_for
    from dtf_tpu.telemetry import costobs
    from dtf_tpu.utils.timing import time_linfit

    ladder = tuple(sorted(set(ladder)))
    if len(ladder) < 2:
        raise ValueError(f"ladder needs >=2 distinct lengths, got {ladder}")
    max_new = max(ladder)
    window = prompt_len + max_new + block_size
    cfg = GPTConfig.from_preset(preset, max_len=max(window, 64))
    model = GPT(cfg)
    params = model.init(jax.random.key(seed))
    blocks_per_slot = blocks_for(window, block_size)
    tight = 1 + streams * blocks_per_slot
    num_blocks = pool_blocks or tight
    if num_blocks < tight:
        raise ValueError(f"--pool_blocks {num_blocks} < tight pool "
                         f"{tight} for {streams} stream(s)")
    rng = np.random.default_rng(seed + 1)
    vocab = min(cfg.vocab_size, trace_vocab) if trace_vocab \
        else cfg.vocab_size
    base_prompts = rng.integers(0, vocab, (streams, prompt_len))
    counter = [0]
    last_engine = [None]
    # ONE pool shared across every timed engine: per-call zeros/concat
    # churn for an oversized pool is tens of MB and would otherwise
    # dominate the fit's noise floor (stale finite rows are harmless —
    # prefill rewrites each block before an unmasked read)
    from dtf_tpu.serve import KVPool
    shared_pool = KVPool.create(cfg, num_blocks, block_size)

    obs = costobs.get_observatory()
    hbm_seen, sample_hbm = _hbm_sampler(obs)

    def closure_of(n_new):
        sample_hbm()

        def call():
            counter[0] += 1
            eng = ServingEngine(
                model, params, num_slots=streams, block_size=block_size,
                blocks_per_slot=blocks_per_slot, num_blocks=num_blocks,
                clock=WallClock(), seed=seed,
                narrow_decode=narrow, pool=shared_pool,
                spec_k=(spec_k if mode == "spec" else 0))
            prompts = (base_prompts + counter[0]) % vocab
            trace = [(0.0, dict(rid=i,
                                prompt=prompts[i].astype(np.int32),
                                max_new_tokens=n_new))
                     for i in range(streams)]
            eng.run(trace)
            last_engine[0] = eng
            return eng
        return call

    compiles0 = obs.total_compiles()
    fit = time_linfit(closure_of, ladder, reps=reps)
    # Ledger columns: the run's compile count (the engine's serve/*
    # builds are observatory-instrumented, delta'd against this
    # invocation's start) and the sampled live-HBM watermark above.
    sample_hbm()
    n_compiles = obs.total_compiles() - compiles0
    # The rig id carries the FULL arm geometry: ledger rounds gate
    # newest-green vs best-prior PER RIG, and a baseline (--no_narrow)
    # or oversized-pool arm is deliberately slower — aliased onto the
    # narrowed rig it would read as a spurious regression.
    rig = f"decode_{preset}_{mode}_s{streams}_bs{block_size}"
    if mode == "spec":
        rig += f"_k{spec_k}"
    if not narrow:
        rig += "_nonarrow"
    if pool_blocks:
        rig += f"_pool{num_blocks}"
    out = {
        "preset": preset, "mode": mode, "streams": streams,
        "block_size": block_size, "pool_blocks": num_blocks,
        "tight_pool_blocks": tight, "narrow": bool(narrow),
        "spec_k": spec_k if mode == "spec" else 0,
        "prompt_len": prompt_len,
        "rig": rig,
        "device": str(jax.devices()[0]),
        "n_compiles": n_compiles,
        "peak_hbm_bytes": hbm_seen[0] or None,
    }
    eng = last_engine[0]
    if mode == "spec" and eng is not None:
        out["spec_proposed"] = eng.spec_proposed
        out["spec_accepted"] = eng.spec_accepted
        out["spec_acceptance"] = (eng.spec_accepted / eng.spec_proposed
                                  if eng.spec_proposed else None)
    return _finish_fit(out, fit, streams)


def run(preset: str = "gpt2_small", mode: str = "fused", streams: int = 1,
        int8: bool = False, beam: int = 0, ladder=(32, 64, 128),
        reps: int = 3, prompt_len: int = 8, seed: int = 0,
        kv_int8: bool = False, cache_chunk=None) -> dict:
    import jax
    import jax.numpy as jnp

    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.telemetry import costobs
    from dtf_tpu.utils.timing import time_linfit

    fused = mode == "fused"
    # Increasing, deduped ladder with >=2 points: the fit needs a real
    # slope, and the no-signal check reads the shortest-vs-longest run.
    ladder = tuple(sorted(set(ladder)))
    if len(ladder) < 2:
        raise ValueError(f"ladder needs >=2 distinct lengths, got {ladder}")
    max_new = max(ladder)
    cfg = GPTConfig.from_preset(
        preset, dtype=jnp.bfloat16,
        max_len=max(prompt_len + max_new + 1, 128))
    model = GPT(cfg)
    params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), model.init(jax.random.key(seed)))

    base_prompt = jax.random.randint(
        jax.random.key(seed + 1), (streams, prompt_len), 0, cfg.vocab_size)

    def gen_fn(k):
        geometry = (preset, mode, streams, int8, kv_int8, beam, k)
        if beam > 0:
            jfn = jax.jit(lambda p, pr: model.beam_search(
                p, pr, k, beam_size=beam, int8_weights=int8,
                fused=fused, kv_int8=kv_int8, cache_chunk=cache_chunk)[0])
        else:
            jfn = jax.jit(lambda p, pr: model.generate(
                p, pr, k, temperature=0.0, int8_weights=int8, fused=fused,
                kv_int8=kv_int8, cache_chunk=cache_chunk))
        return costobs.instrument(jfn, "bench/decode_ladder", geometry)

    # Perturb the prompt each call: the relay memoizes bitwise-identical
    # executions.  A deterministic token shift keeps runs reproducible
    # while making every execution distinct.
    counter = [0]
    obs = costobs.get_observatory()
    hbm_seen, sample_hbm = _hbm_sampler(obs)

    def closure_of(k):
        sample_hbm()
        g = gen_fn(k)

        def call():
            counter[0] += 1
            pr = (base_prompt + counter[0]) % cfg.vocab_size
            return g(params, pr)
        return call

    compiles0 = obs.total_compiles()
    fit = time_linfit(closure_of, ladder, reps=reps)
    sample_hbm()
    rig = (f"decode_{preset}_{mode}_s{streams}"
           + ("_int8" if int8 else "") + ("_kvint8" if kv_int8 else "")
           + (f"_beam{beam}" if beam else ""))
    out = {
        "preset": preset, "mode": mode, "streams": streams,
        "int8": int8, "kv_int8": kv_int8, "beam": beam,
        "rig": rig,
        "device": str(jax.devices()[0]),
        "n_compiles": obs.total_compiles() - compiles0,
        "peak_hbm_bytes": hbm_seen[0] or None,
    }
    # time_linfit clamps the slope to >= 1e-12, so "no signal" must be
    # detected directly (_finish_fit): the longest chain must actually
    # take longer than the shortest, and the per-token time must be
    # physically plausible (>1 ns).
    return _finish_fit(out, fit, streams)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--preset", default="gpt2_small",
                        choices=["gpt2_small", "llama", "tiny"])
    parser.add_argument("--mode",
                        choices=["fused", "unfused", "paged", "spec"],
                        default="fused",
                        help="fused/unfused = GPT.generate kernels; "
                             "paged = the serving engine's narrowed "
                             "block-indexed data path (--no_narrow = "
                             "whole-pool baseline arm); spec = "
                             "speculative decoding through the engine")
    parser.add_argument("--streams", type=int, default=1)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument("--kv_int8", action="store_true",
                        help="int8 KV-cache rows (fused only)")
    parser.add_argument("--cache_chunk", type=int, default=None,
                        help="walk the KV cache in chunks of this many "
                             "rows (fused long-context; default: whole "
                             "cache when it fits the VMEM budget)")
    parser.add_argument("--beam", type=int, default=0,
                        help=">0: beam search of this width (tokens "
                             "counted per batch row, beams are search "
                             "overhead)")
    parser.add_argument("--ladder", default="32,64,128",
                        help="comma-separated max_new_tokens ladder")
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--prompt_len", type=int, default=8,
                        help="prompt length (long-context rows: a long "
                             "prompt makes the cache long from step one)")
    parser.add_argument("--block_size", type=int, default=4,
                        help="paged/spec: KV block size")
    parser.add_argument("--pool_blocks", type=int, default=None,
                        help="paged/spec: total pool blocks (oversize "
                             "to probe pool-size invariance; default "
                             "tight = 1 + streams x window)")
    parser.add_argument("--no_narrow", action="store_true",
                        help="paged/spec: full-window whole-pool "
                             "baseline geometry (the A/B foil)")
    parser.add_argument("--spec_k", type=int, default=4,
                        help="spec: drafts per iteration")
    parser.add_argument("--trace_vocab", type=int, default=None,
                        help="paged/spec: cap the prompt token alphabet "
                             "(small alphabets give the n-gram drafter "
                             "material)")
    parser.add_argument("--json", default=None,
                        help="write the ladder doc here (bench_ledger "
                             "folds it as a decode rig row)")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (reliable even when "
                             "a TPU plugin is registered)")
    ns = parser.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    ladder = tuple(int(k) for k in ns.ladder.split(","))
    if ns.mode in ("paged", "spec"):
        # fail loud, not silently-fp: the engine rungs don't take the
        # generate-path quantization/beam knobs (yet — ROADMAP lists
        # int8 verify composition as the open item)
        for flag, val in (("--int8", ns.int8), ("--kv_int8", ns.kv_int8),
                          ("--beam", ns.beam),
                          ("--cache_chunk", ns.cache_chunk)):
            if val:
                parser.error(f"{flag} applies to the fused/unfused "
                             f"generate-path modes, not --mode {ns.mode}")
        r = run_engine(ns.preset, ns.mode, ns.streams, ladder, ns.reps,
                       prompt_len=ns.prompt_len, block_size=ns.block_size,
                       pool_blocks=ns.pool_blocks,
                       narrow=not ns.no_narrow, spec_k=ns.spec_k,
                       trace_vocab=ns.trace_vocab)
        tag = (" narrow" if r["narrow"] else " baseline") + (
            f" k={r['spec_k']}" if r["mode"] == "spec" else "")
        print(f"{r['preset']} {r['mode']}{tag} x{r['streams']} streams "
              f"pool={r['pool_blocks']} blocks on {r['device']}")
    else:
        r = run(ns.preset, ns.mode, ns.streams, ns.int8, ns.beam, ladder,
                ns.reps, prompt_len=ns.prompt_len, kv_int8=ns.kv_int8,
                cache_chunk=ns.cache_chunk)
        beam_tag = f" beam={r['beam']}" if r["beam"] else ""
        int8_tag = (" int8" if r["int8"] else "") + (
            " kv-int8" if r.get("kv_int8") else "")
        print(f"{r['preset']} {r['mode']}{int8_tag}{beam_tag} "
              f"x{r['streams']} streams on {r['device']}")
    print(f"ladder (max_new_tokens, best ms): {r['ladder']}")
    if ns.json:
        with open(ns.json, "w") as f:
            json.dump(r, f, indent=1, sort_keys=True)
        print(f"wrote {ns.json}")
    if r.get("warning"):
        print(f"NO RESULT: {r['warning']}")
        return 1
    acc = r.get("spec_acceptance")
    acc_tag = f", acceptance {acc:.2f}" if acc is not None else ""
    print(f"per-token {r['per_token_us']:.1f} us  ->  "
          f"{r['tok_s_per_stream']:.1f} tok/s/stream, "
          f"{r['tok_s_aggregate']:.1f} tok/s aggregate "
          f"(fixed overhead {r['fit_overhead_ms']:.0f} ms absorbed"
          f"{acc_tag})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
