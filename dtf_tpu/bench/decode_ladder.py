"""Reproducible decode-throughput ladder — the honest decode number.

The only trustworthy per-token time through the axon relay is the
marginal one: the least-squares slope of ``max_new_tokens -> wall time``
over a ladder of generation lengths (``utils.timing.time_linfit``).
Spot timings carry ~50-250 ms of fixed relay cost per synced call, and
the relay *memoizes* bitwise-identical executions (BASELINE.md round-3
"timing methodology correction"), so this harness also perturbs the
prompt between repetitions — every timed call is a genuinely new
execution.

One command per BASELINE.md decode row::

    python -m dtf_tpu.bench.decode_ladder --preset gpt2_small \
        --mode fused --streams 32            # tiled fused kernel
    python -m dtf_tpu.bench.decode_ladder --preset llama \
        --mode fused --streams 1 --int8      # int8 weights in-kernel
    python -m dtf_tpu.bench.decode_ladder --preset gpt2_small \
        --mode fused --beam 4                # beam through the kernel

The reference has no decode path at all (TF1 parameter-server MNIST
demo); these rows are framework-beyond-parity serving numbers.
"""

from __future__ import annotations

import argparse


def run(preset: str = "gpt2_small", mode: str = "fused", streams: int = 1,
        int8: bool = False, beam: int = 0, ladder=(32, 64, 128),
        reps: int = 3, prompt_len: int = 8, seed: int = 0,
        kv_int8: bool = False, cache_chunk=None) -> dict:
    import jax
    import jax.numpy as jnp

    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.utils.timing import time_linfit

    fused = mode == "fused"
    # Increasing, deduped ladder with >=2 points: the fit needs a real
    # slope, and the no-signal check reads the shortest-vs-longest run.
    ladder = tuple(sorted(set(ladder)))
    if len(ladder) < 2:
        raise ValueError(f"ladder needs >=2 distinct lengths, got {ladder}")
    max_new = max(ladder)
    cfg = GPTConfig.from_preset(
        preset, dtype=jnp.bfloat16,
        max_len=max(prompt_len + max_new + 1, 128))
    model = GPT(cfg)
    params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), model.init(jax.random.key(seed)))

    base_prompt = jax.random.randint(
        jax.random.key(seed + 1), (streams, prompt_len), 0, cfg.vocab_size)

    def gen_fn(k):
        if beam > 0:
            return jax.jit(lambda p, pr: model.beam_search(
                p, pr, k, beam_size=beam, int8_weights=int8,
                fused=fused, kv_int8=kv_int8, cache_chunk=cache_chunk)[0])
        return jax.jit(lambda p, pr: model.generate(
            p, pr, k, temperature=0.0, int8_weights=int8, fused=fused,
            kv_int8=kv_int8, cache_chunk=cache_chunk))

    # Perturb the prompt each call: the relay memoizes bitwise-identical
    # executions.  A deterministic token shift keeps runs reproducible
    # while making every execution distinct.
    counter = [0]

    def closure_of(k):
        g = gen_fn(k)

        def call():
            counter[0] += 1
            pr = (base_prompt + counter[0]) % cfg.vocab_size
            return g(params, pr)
        return call

    fit = time_linfit(closure_of, ladder, reps=reps)
    per_token_s = fit.per_iter_s
    out = {
        "preset": preset, "mode": mode, "streams": streams,
        "int8": int8, "kv_int8": kv_int8, "beam": beam,
        "ladder": [[k, round(t * 1e3, 2)] for k, t in fit.points],
        "per_token_us": per_token_s * 1e6,
        "fit_overhead_ms": fit.overhead_s * 1e3,
        "device": str(jax.devices()[0]),
    }
    # time_linfit clamps the slope to >= 1e-12, so "no signal" must be
    # detected directly: the longest chain must actually take longer
    # than the shortest (ladder passed in increasing order), and the
    # per-token time must be physically plausible (>1 ns).
    times = [t for _, t in fit.points]
    if times[-1] <= times[0] or per_token_s <= 1e-9:
        out["tok_s_per_stream"] = out["tok_s_aggregate"] = None
        out["warning"] = ("non-positive slope — ladder is "
                          "noise-dominated; lengthen --ladder or raise "
                          "--reps")
    else:
        out["tok_s_per_stream"] = 1.0 / per_token_s
        out["tok_s_aggregate"] = streams / per_token_s
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--preset", default="gpt2_small",
                        choices=["gpt2_small", "llama", "tiny"])
    parser.add_argument("--mode", choices=["fused", "unfused"],
                        default="fused")
    parser.add_argument("--streams", type=int, default=1)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument("--kv_int8", action="store_true",
                        help="int8 KV-cache rows (fused only)")
    parser.add_argument("--cache_chunk", type=int, default=None,
                        help="walk the KV cache in chunks of this many "
                             "rows (fused long-context; default: whole "
                             "cache when it fits the VMEM budget)")
    parser.add_argument("--beam", type=int, default=0,
                        help=">0: beam search of this width (tokens "
                             "counted per batch row, beams are search "
                             "overhead)")
    parser.add_argument("--ladder", default="32,64,128",
                        help="comma-separated max_new_tokens ladder")
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--prompt_len", type=int, default=8,
                        help="prompt length (long-context rows: a long "
                             "prompt makes the cache long from step one)")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (reliable even when "
                             "a TPU plugin is registered)")
    ns = parser.parse_args(argv)
    if ns.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    ladder = tuple(int(k) for k in ns.ladder.split(","))
    r = run(ns.preset, ns.mode, ns.streams, ns.int8, ns.beam, ladder,
            ns.reps, prompt_len=ns.prompt_len, kv_int8=ns.kv_int8,
            cache_chunk=ns.cache_chunk)
    beam_tag = f" beam={r['beam']}" if r["beam"] else ""
    int8_tag = (" int8" if r["int8"] else "") + (
        " kv-int8" if r.get("kv_int8") else "")
    print(f"{r['preset']} {r['mode']}{int8_tag}{beam_tag} "
          f"x{r['streams']} streams on {r['device']}")
    print(f"ladder (max_new_tokens, best ms): {r['ladder']}")
    if r.get("warning"):
        print(f"NO RESULT: {r['warning']}")
        return 1
    print(f"per-token {r['per_token_us']:.1f} us  ->  "
          f"{r['tok_s_per_stream']:.1f} tok/s/stream, "
          f"{r['tok_s_aggregate']:.1f} tok/s aggregate "
          f"(fixed overhead {r['fit_overhead_ms']:.0f} ms absorbed)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
