"""Named device meshes.

The reference's topology was a static ClusterSpec of hardcoded host:port
strings (tf_distributed.py:9-11) with roles split between a parameter server
and workers.  The TPU-native topology is a single logical device mesh with
named axes; every parallelism strategy is an axis:

* ``data``   — data parallelism (the reference's only strategy, §2.14);
* ``fsdp``   — sharded parameter/optimizer state (ZeRO-style weight-update
  sharding; generalizes the reference's PS-side variable placement);
* ``tensor`` — tensor (intra-op) model parallelism;
* ``seq``    — sequence/context parallelism (ring attention);
* ``expert`` — expert parallelism for MoE layers;
* ``pipe``   — pipeline parallelism.

A mesh is requested as a spec string, e.g. ``"data=-1"`` or
``"data=4,tensor=2"``; ``-1`` means "infer from device count".
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

DATA = "data"
FSDP = "fsdp"
TENSOR = "tensor"
SEQ = "seq"
EXPERT = "expert"
PIPE = "pipe"
AXES = (DATA, FSDP, TENSOR, SEQ, EXPERT, PIPE)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """An ordered request for mesh axes.  At most one size may be -1."""

    names: tuple[str, ...]
    sizes: tuple[int, ...]

    @classmethod
    def parse(cls, spec: str) -> "MeshSpec":
        """Parse ``"data=4,tensor=2"`` (or ``"data=-1"``)."""
        names, sizes = [], []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, size = part.partition("=")
            name = name.strip()
            if name not in AXES:
                raise ValueError(f"unknown mesh axis {name!r}; known: {AXES}")
            names.append(name)
            sizes.append(int(size) if size else -1)
        if not names:
            raise ValueError(f"empty mesh spec {spec!r}")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate axis in mesh spec {spec!r}")
        if sum(s == -1 for s in sizes) > 1:
            raise ValueError(f"at most one axis may be -1 in {spec!r}")
        if any(s == 0 or s < -1 for s in sizes):
            raise ValueError(f"axis sizes must be positive (or -1 to infer) in {spec!r}")
        return cls(tuple(names), tuple(sizes))

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in a -1 axis so the product equals ``n_devices``."""
        sizes = list(self.sizes)
        fixed = math.prod(s for s in sizes if s != -1)
        if -1 in sizes:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes of {self}")
            sizes[sizes.index(-1)] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"{self} needs {fixed} devices, have {n_devices}")
        return MeshSpec(self.names, tuple(sizes))


def shrink_to_devices(spec: "MeshSpec | str", n_devices: int) -> MeshSpec:
    """Elastic restart: re-fit a mesh request onto a changed device count
    by re-sizing the ``data`` axis, keeping every model axis (fsdp/tensor/
    seq/expert/pipe) fixed.

    Data parallelism is the one axis whose size is a pure throughput
    knob — model math is invariant to it — so it absorbs lost (or
    regained) hardware: a relaunch on N-1 hosts shrinks ``data`` and the
    checkpoint reshards onto the smaller mesh through the restore
    template.  A spec with a ``-1`` axis is already elastic and returns
    unchanged (``resolve`` re-infers it).  Model axes that no longer
    divide the device count are a real topology loss (e.g. a pipeline
    stage's hosts died) — that raises; no silent degradation of the
    parallelism strategy."""
    if isinstance(spec, str):
        spec = MeshSpec.parse(spec)
    if -1 in spec.sizes:
        return spec
    if DATA not in spec.names:
        raise ValueError(
            f"cannot shrink {spec} onto {n_devices} device(s): no data "
            f"axis to resize (model axes are fixed topology)")
    other = math.prod(s for n, s in zip(spec.names, spec.sizes)
                      if n != DATA)
    if n_devices % other or n_devices < other:
        raise ValueError(
            f"cannot shrink {spec} onto {n_devices} device(s): model axes "
            f"need a multiple of {other}")
    sizes = tuple(n_devices // other if n == DATA else s
                  for n, s in zip(spec.names, spec.sizes))
    return MeshSpec(spec.names, sizes)


def make_mesh(spec: "MeshSpec | str",
              devices: Optional[Sequence[jax.Device]] = None,
              explicit: bool = False) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` from a spec.

    Axis order in the spec is the physical device-grid order; put axes with
    the heaviest collectives (``tensor``, ``seq``) innermost (last) so their
    collectives ride ICI neighbours.

    Axis types default to ``Auto`` (GSPMD decides intermediate shardings from
    in/out annotations — the framework's normal mode).  JAX 0.9's
    ``jax.make_mesh`` defaults to ``Explicit``, which rejects ops like
    ``x @ x.T`` on a data-sharded batch unless every intermediate sharding is
    spelled out; pass ``explicit=True`` to opt into that stricter mode.
    """
    if isinstance(spec, str):
        spec = MeshSpec.parse(spec)
    devices = list(devices) if devices is not None else jax.devices()
    spec = spec.resolve(len(devices))
    # Older jax (< 0.5) has no AxisType: every axis is implicitly Auto
    # (GSPMD mode — the framework default), so the annotation is simply
    # omitted there; only an Explicit request has no equivalent.
    axis_type_cls = getattr(jax.sharding, "AxisType", None)
    if axis_type_cls is None:
        if explicit:
            raise NotImplementedError(
                f"explicit axis types need jax.sharding.AxisType "
                f"(jax >= 0.5); this is jax {jax.__version__}")
        kwargs = {}
    else:
        axis_type = axis_type_cls.Explicit if explicit else axis_type_cls.Auto
        kwargs = {"axis_types": (axis_type,) * len(spec.names)}
    if devices == list(jax.devices()):
        return jax.make_mesh(spec.sizes, spec.names, **kwargs)
    import numpy as np
    dev_grid = np.asarray(devices).reshape(spec.sizes)
    return Mesh(dev_grid, spec.names, **kwargs)


def local_mesh(spec: "MeshSpec | str" = "data=-1") -> Mesh:
    """Single-process mesh over all local devices (the zero-flag mode the
    reference lacked — its hardcoded IPs made it unrunnable standalone,
    tf_distributed.py:9-10)."""
    return make_mesh(spec, jax.local_devices())
