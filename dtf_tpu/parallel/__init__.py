"""Parallelism layer: device meshes, sharding rules, explicit collectives.

TPU-native replacement for the reference's placement/replication machinery
(``tf.train.replica_device_setter``, tf_distributed.py:34-36, which pinned
variables to the PS job and compute to each worker).  Here placement is
declarative: a named :class:`jax.sharding.Mesh` plus ``NamedSharding`` rules;
XLA's GSPMD partitioner inserts the collectives the TF runtime used to route
through gRPC Send/Recv pairs.
"""

from dtf_tpu.parallel.mesh import (
    AXES, DATA, FSDP, TENSOR, SEQ, EXPERT, PIPE,
    MeshSpec, make_mesh, local_mesh,
)
from dtf_tpu.parallel.sharding import (
    named_sharding, replicate, shard_batch, batch_spec, logical_to_spec,
    apply_rules,
)
from dtf_tpu.parallel.grad_sync import GradSyncEngine, STRATEGIES

__all__ = [
    "AXES", "DATA", "FSDP", "TENSOR", "SEQ", "EXPERT", "PIPE",
    "MeshSpec", "make_mesh", "local_mesh",
    "named_sharding", "replicate", "shard_batch", "batch_spec",
    "logical_to_spec", "apply_rules",
    "GradSyncEngine", "STRATEGIES",
]
