"""Block-scaled int8 wire format for gradient collectives (EQuARX-style).

PR 5's ``--grad_comm_dtype bf16`` halved the gradient wire; this module
takes the next rung (ROADMAP "Quantized collectives and low-precision
compute paths", cf. PAPERS.md "Efficient Quantized AllReduce in XLA",
arxiv 2506.17615): an **int8 payload plus one f32 scale per QBLOCK
values**, ~3.94x less wire than f32 (~1.97x less than bf16) at ~1.6%
scale overhead.

Design points, each load-bearing:

* **per-block scales** (:data:`QBLOCK` = 256): a single outlier inflates
  only its own block's step size instead of the whole bucket — an order
  of magnitude less error on heavy-tailed gradient distributions than
  one scale per tensor.
* **mean-preserving pre-scaling**: callers ship ``g/N`` (the engine and
  the dense helper pre-scale), so the summed wire value IS the mean —
  exactly one quantization per contribution and no post-hoc divide to
  round again.
* **reduce-scatter as all-to-all + local sum**: int8 payloads with
  different scales cannot be summed on the wire (and would overflow
  int8), so each device sends the j-th chunk of its local vector to
  device j (``lax.all_to_all`` on the int8 payload + scales) and the
  receiver dequantizes and sums in f32.  Same tiled semantics as
  :func:`dtf_tpu.parallel.collectives.reduce_scatter`, one rounding per
  value, int8 bytes on the wire.
* **rounding modes**: ``nearest`` (deterministic) or ``stochastic``
  (``floor(v/s + u)``, u ~ U[0,1) from a caller-provided key — unbiased,
  E[decode] == v, and reproducible because the key derives from the step
  rng).
* **non-finite safety**: a NaN/inf anywhere in a block makes that
  block's scale non-finite, so decode yields NaN — quantization can
  NEVER launder a non-finite gradient into finite garbage.  The
  trainer's guard additionally checks isfinite BEFORE the sync (see
  make_train_step), so a poisoned step is skipped either way.

Shard-map contract: every function taking ``axis`` is per-device code —
call inside ``shard_map`` with the vector replicated or locally distinct
per device as documented.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.parallel import collectives as col

#: Values per f32 scale.  256 keeps the scale overhead at 4/256 ~ 1.6%
#: of the payload while bounding each outlier's blast radius; it also
#: matches the decode kernel's serving-side block size so the two wire
#: formats tell one story (ops/decode_kernel.py).
QBLOCK = 256

#: The rounding-mode spellings ``--quant_rounding`` accepts.
ROUNDINGS: Tuple[str, ...] = ("nearest", "stochastic")

#: Effective wire bytes per f32 gradient element, by wire format: int8
#: pays 1 payload byte + 4/QBLOCK scale bytes.  ``int8_ring`` ships the
#: same block format, but fewer ELEMENTS cross the wire (each of the
#: n-1 hops carries one chunk instead of the all-to-all's n chunks —
#: see :func:`ring_wire_elems`), so its per-element cost is identical
#: here and the saving shows up in the element count.  The telemetry
#: gauges and the bench A/B both read from here so the accounting
#: cannot drift.
WIRE_BYTES_PER_ELEM = {"f32": 4.0, "bf16": 2.0,
                       "int8": 1.0 + 4.0 / QBLOCK,
                       "int8_ring": 1.0 + 4.0 / QBLOCK}

_TINY = 1e-30   # scale floor: all-zero blocks decode to exact zeros


def check_rounding(rounding: str) -> str:
    if rounding not in ROUNDINGS:
        raise ValueError(f"--quant_rounding must be one of {ROUNDINGS}, "
                         f"got {rounding!r}")
    return rounding


def pad_to_blocks(v: jax.Array) -> jax.Array:
    """Zero-pad a flat vector up to a whole number of QBLOCK blocks (a
    no-op when already aligned).  Zero padding is inert: an all-zero
    tail quantizes to q=0 against its block's scale and decodes to exact
    zeros, and receivers slice it off."""
    m = v.shape[-1]
    pad = -(-m // QBLOCK) * QBLOCK - m
    return jnp.pad(v, (0, pad)) if pad else v


def encode(v: jax.Array, rounding: str = "nearest",
           rng: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Flat f32 ``(m,)`` with ``m % QBLOCK == 0`` -> ``(q int8 (nb,
    QBLOCK), scale f32 (nb, 1))``, symmetric per-block quantization.

    ``stochastic`` needs ``rng`` and draws one uniform per value; the
    expectation of ``decode(encode(v))`` is exactly ``v`` (within the
    clip range, which the per-block max scale guarantees)."""
    if v.shape[-1] % QBLOCK:
        raise ValueError(
            f"encode: vector length {v.shape[-1]} is not a multiple of "
            f"QBLOCK={QBLOCK}; use pad_to_blocks first (the collective "
            f"wrappers below do)")
    vb = v.reshape(-1, QBLOCK)
    scale = jnp.max(jnp.abs(vb), axis=1, keepdims=True) / 127.0
    t = vb / jnp.maximum(scale, _TINY)
    if rounding == "stochastic":
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key "
                             "(seed it from the step rng)")
        t = jnp.floor(t + jax.random.uniform(rng, t.shape))
    else:
        check_rounding(rounding)
        t = jnp.round(t)
    q = jnp.clip(t, -127, 127).astype(jnp.int8)
    return q, scale


def decode(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`encode` -> flat f32.  A non-finite scale (the
    block held a NaN/inf) propagates as NaN, never finite garbage."""
    return (q.astype(jnp.float32) * scale).reshape(-1)


def encode_error(v: jax.Array, rounding: str = "nearest",
                 rng: Optional[jax.Array] = None) -> jax.Array:
    """``(sum((decode(encode(v)) - v)^2), sum(v^2))`` as a ``(2,)`` f32
    vector — the quantization-error accumulator behind the
    ``comm/quant_error`` gauge.  Pairs sum across buckets/microbatches/
    devices; the final gauge is ``sqrt(num/den)`` (relative RMS)."""
    err = decode(*encode(v, rounding, rng)) - v
    return jnp.stack([jnp.sum(err * err), jnp.sum(v * v)])


def error_ratio(pair: jax.Array) -> jax.Array:
    """(num, den) accumulator -> relative RMS error scalar."""
    return jnp.sqrt(pair[0] / jnp.maximum(pair[1], _TINY))


def wire_elems(length: int, n_shards: int) -> int:
    """Elements actually shipped when reduce-scattering a ``(length,)``
    vector over ``n_shards``: each of the n per-device chunks rounds up
    to whole QBLOCK blocks (at most QBLOCK-1 slack elements per chunk).
    The bucket layout itself is UNCHANGED by the int8 wire — the
    alignment lives inside the collective — so wire dtypes compare at an
    equal bucket layout and checkpoint shapes never depend on the wire
    format.  comm_stats and the bench A/B compute bytes from here."""
    chunk = length // n_shards
    return n_shards * (-(-chunk // QBLOCK) * QBLOCK)


def ring_wire_elems(length: int, n_shards: int) -> int:
    """Elements shipped by :func:`ring_reduce_scatter_quantized` for the
    same ``(length,)`` vector: ``n_shards - 1`` hops, each carrying ONE
    block-padded chunk — ``(n-1)/n`` of :func:`wire_elems`.  This is the
    multi-hop win the ``comm/wire_bytes`` gauge must show: the all-to-all
    wire ships every chunk once per device, the ring ships one chunk per
    hop and the partial sums stay int8 the whole way (EQuARX)."""
    chunk = length // n_shards
    return (n_shards - 1) * (-(-chunk // QBLOCK) * QBLOCK)


def reduce_scatter_quantized(v: jax.Array, axis: str, *,
                             rounding: str = "nearest",
                             rng: Optional[jax.Array] = None,
                             return_error: bool = False):
    """Block-quantized sum-reduce-scatter of a flat vector.

    Per-device code: ``v (P,)`` is this device's local contribution with
    ``P % axis_size == 0`` (the ordinary reduce-scatter divisibility —
    the bucket layout's lcm padding already guarantees it); rank k
    returns the f32 SUM of all ranks' ``[k*P/n : (k+1)*P/n]`` chunk —
    the tiled semantics of :func:`collectives.reduce_scatter`, with
    int8+scales on the wire instead of f32.  Each chunk is zero-padded
    to whole QBLOCK blocks inside (see :func:`wire_elems`), so the
    bucket layout is wire-format-independent.  Callers pre-scale by 1/N
    for a mean.

    ``return_error=True`` additionally returns this device's encode
    error pair (see :func:`encode_error`) measured on the ACTUAL encoded
    payload — free of a second encode pass.  (Padding contributes zero
    to both components.)"""
    n = col.axis_size(axis)
    p = v.shape[0]
    if p % n:
        raise ValueError(
            f"reduce_scatter_quantized: length {p} is not divisible by "
            f"mesh axis {axis!r} (size {n}); pad the vector upstream "
            f"(grad_sync's bucket layout does this)")
    if n == 1:
        return (v, jnp.zeros((2,), jnp.float32)) if return_error else v
    chunk = p // n
    padded = -(-chunk // QBLOCK) * QBLOCK
    vc = v.reshape(n, chunk)
    if padded != chunk:
        vc = jnp.pad(vc, ((0, 0), (0, padded - chunk)))
    q, s = encode(vc.reshape(-1), rounding, rng)
    err = None
    if return_error:
        e = decode(q, s) - vc.reshape(-1)
        err = jnp.stack([jnp.sum(e * e), jnp.sum(v * v)])
    # chunk j of the block grid goes to device j: blocks never straddle
    # chunk boundaries (padded is a QBLOCK multiple), so a reshape
    # routes whole (q, scale) blocks.
    nb = q.shape[0]
    q = col.all_to_all(q.reshape(n, nb // n, QBLOCK), axis,
                       split_axis=0, concat_axis=0)
    s = col.all_to_all(s.reshape(n, nb // n, 1), axis,
                       split_axis=0, concat_axis=0)
    out = (q.astype(jnp.float32) * s).reshape(n, -1).sum(axis=0)
    out = out[:chunk]
    return (out, err) if return_error else out


def ring_reduce_scatter_quantized(v: jax.Array, axis: str, *,
                                  rounding: str = "nearest",
                                  rng: Optional[jax.Array] = None,
                                  return_error: bool = False):
    """Segmented-ring sum-reduce-scatter with **per-hop requantization**
    (EQuARX, arxiv 2506.17615) — the ``--grad_comm_dtype int8_ring``
    wire.

    Same contract as :func:`reduce_scatter_quantized` (per-device code;
    ``v (P,)`` with ``P % axis_size == 0``; rank k returns the f32 SUM
    of all ranks' chunk k; callers pre-scale by 1/N for a mean), but a
    different schedule: instead of one all-to-all that ships every chunk
    once per device (n block-padded chunks on the wire), each rank walks
    ``n-1`` ``ppermute`` hops around the ring, and EVERY hop re-encodes
    the running **partial sum** into the block-scaled int8 format before
    it travels — int8 payload + f32 block scales on every link, never an
    f32 partial sum.  Total wire: ``(n-1)`` chunks instead of ``n``
    (:func:`ring_wire_elems`), the multi-hop win on meshes where the
    reduction actually spans several links.

    The price is ``n-1`` roundings per value instead of one; the error
    pair (``return_error=True``) accumulates every hop's encode error
    against that hop's payload energy, so ``comm/quant_error`` reports
    the TRUE per-hop requantization ladder, not just the first rung.
    ``stochastic`` rounding folds the hop index into ``rng`` — draws
    never repeat across hops (or across buckets: the engine already
    folds the bucket index in), so trajectories stay bitwise
    reproducible from the step rng."""
    n = col.axis_size(axis)
    p = v.shape[0]
    if p % n:
        raise ValueError(
            f"ring_reduce_scatter_quantized: length {p} is not divisible "
            f"by mesh axis {axis!r} (size {n}); pad the vector upstream "
            f"(grad_sync's bucket layout does this)")
    if n == 1:
        return (v, jnp.zeros((2,), jnp.float32)) if return_error else v
    chunk = p // n
    padded = -(-chunk // QBLOCK) * QBLOCK
    buf = v.reshape(n, chunk)
    if padded != chunk:
        buf = jnp.pad(buf, ((0, 0), (0, padded - chunk)))
    me = lax.axis_index(axis)
    fwd = col.ring_neighbors(n)
    err = jnp.zeros((2,), jnp.float32)
    # hop s: rank me ships its partial sum of chunk (me-1-s) mod n to
    # rank me+1 and folds in the payload arriving from rank me-1.  After
    # n-1 hops rank me holds the full sum of chunk me — the tiled
    # reduce-scatter ownership, so the all-gather leg needs no reindex.
    for s in range(n - 1):
        send_idx = (me - 1 - s) % n
        recv_idx = (me - 2 - s) % n     # = sender (me-1)'s send_idx
        payload = jnp.take(buf, send_idx, axis=0)
        hop_rng = (jax.random.fold_in(rng, s) if rng is not None
                   and rounding == "stochastic" else None)
        q, scale = encode(payload, rounding, hop_rng)
        if return_error:
            e = decode(q, scale) - payload
            err = err + jnp.stack([jnp.sum(e * e),
                                   jnp.sum(payload * payload)])
        q = lax.ppermute(q, axis, fwd)
        scale = lax.ppermute(scale, axis, fwd)
        buf = buf.at[recv_idx].add(decode(q, scale))
    out = jnp.take(buf, me, axis=0)[:chunk]
    return (out, err) if return_error else out


def all_gather_quantized(shard: jax.Array, axis: str) -> jax.Array:
    """Block-quantized all-gather of an f32 shard ``(m,)`` -> full
    ``(n*m,)`` f32 in mesh-axis order (any ``m``; the shard pads to
    whole blocks inside and receivers slice the padding off).

    Each rank encodes its own shard exactly once (nearest rounding: the
    gather leg must be deterministic) and every rank decodes the same
    gathered payload, so the result is replica-identical by
    construction."""
    if col.axis_size(axis) == 1:
        # Identity on a 1-device axis (mirrors reduce_scatter_quantized):
        # no wire, so no reason to pay the encode/decode round-trip.
        return shard
    m = shard.shape[0]
    q, s = encode(pad_to_blocks(shard))
    full = decode(col.all_gather(q, axis), col.all_gather(s, axis))
    pm = q.shape[0] * QBLOCK            # padded shard length
    if pm == m:
        return full
    return full.reshape(-1, pm)[:, :m].reshape(-1)


def _flatten_tree(tree: Any, quantum: int):
    """Pytree -> (padded flat f32 vector, unflatten) for the dense-path
    all-reduce (the zero1 engine has its own BucketLayout)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1) for l in leaves])
    pad = -(-flat.size // quantum) * quantum - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))

    def unflatten(vec):
        out, off = [], 0
        for l, n in zip(leaves, sizes):
            out.append(vec[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    return flat, unflatten


def all_reduce_mean_quantized(tree: Any, axis: str, *,
                              rounding: str = "nearest",
                              rng: Optional[jax.Array] = None,
                              ring: bool = False):
    """Mean-all-reduce of a gradient pytree with the block-scaled int8
    wire — the DENSE strategy's ``--grad_comm_dtype int8`` path
    (``ring=True``: the ``int8_ring`` path, per-hop requantizing
    reduce-scatter instead of the one-shot all-to-all).

    Per-device code: flatten -> pre-scale by 1/N (mean-preserving) ->
    quantized reduce-scatter -> quantized all-gather -> unflatten.  Two
    roundings per value total on the all-to-all wire (one per wire leg;
    the ring pays one per hop on the scatter leg instead — see
    :func:`ring_reduce_scatter_quantized`); the gather leg is
    deterministic so all replicas hold bitwise-identical means.  Returns
    ``(mean_tree, error_pair)`` — the error pair is the local scatter-leg
    encode error (psum it across the axis before reporting)."""
    n = col.axis_size(axis)
    flat, unflatten = _flatten_tree(tree, n)
    rs = ring_reduce_scatter_quantized if ring else reduce_scatter_quantized
    shard, err = rs(flat * (1.0 / n), axis, rounding=rounding, rng=rng,
                    return_error=True)
    return unflatten(all_gather_quantized(shard, axis)), err
