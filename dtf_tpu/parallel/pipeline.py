"""Pipeline parallelism over a ``pipe`` mesh axis (GPipe schedule).

Not in the reference (its only parallelism is async-PS data parallelism,
SURVEY.md §2.14); built because the framework treats pipeline sharding as a
first-class mesh axis alongside data/fsdp/tensor/seq.

TPU-native design: SPMD, not per-stage processes.  Stage parameters carry a
leading ``stage`` logical axis sharded over ``pipe`` (rule table
``("stage", "pipe")``, parallel/sharding.py); execution runs under
``jax.shard_map`` where each device holds exactly one stage's weights and
activations hop stage→stage via ``lax.ppermute`` over ICI.  The schedule is
a ``lax.scan`` over M + S - 1 ticks (M microbatches, S stages, bubble
fraction (S-1)/(M+S-1)); reverse-mode AD through the scan+ppermute gives the
backward pipeline automatically, so the same code trains under jit.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, *, num_microbatches: int, axis: str = "pipe",
                   batch_axes: Optional[tuple] = None) -> jax.Array:
    """Run ``x`` through S pipeline stages.

    ``stage_fn(params_one_stage, x_mb) -> y_mb`` must preserve the
    activation shape (e.g. a block of transformer layers).  ``stage_params``
    is a pytree whose every leaf has leading dim S (the stage axis, sharded
    over ``axis``).  ``x``: (B, ...) global batch; B must be divisible by
    ``num_microbatches`` (× the data-axis size, if present).  Returns the
    last stage's output, (B, ...).
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    s = mesh.shape[axis]
    m = num_microbatches
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"num_microbatches={m}")
    leaves = jax.tree_util.tree_leaves(stage_params)
    if leaves and leaves[0].shape[0] != s:
        raise ValueError(f"stage_params leading dim {leaves[0].shape[0]} "
                         f"!= {axis} axis size {s}")
    if batch_axes is None:
        from dtf_tpu.parallel.sharding import data_axes as _data_axes
        batch_axes = _data_axes(mesh)

    mb = x.shape[0] // m
    data_size = 1
    for a in batch_axes:
        data_size *= mesh.shape[a]
    if mb % data_size:
        raise ValueError(f"microbatch size {mb} (batch {x.shape[0]} / "
                         f"{m} microbatches) not divisible by data-axis "
                         f"size {data_size}")
    xs = x.reshape(m, mb, *x.shape[1:])

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    # microbatch dim replicated over pipe; batch dim sharded over data axes
    x_spec = P(None, batch_axes or None, *([None] * (x.ndim - 1)))

    body = functools.partial(_per_device_pipeline, stage_fn, s=s, m=m,
                             axis=axis)
    mapped = jax.shard_map(body, mesh=mesh, in_specs=(param_spec, x_spec),
                           out_specs=x_spec, check_vma=False)
    ys = mapped(stage_params, xs)
    return ys.reshape(x.shape[0], *x.shape[1:])


def _per_device_pipeline(stage_fn, stage_params, xs, *, s: int, m: int,
                         axis: str):
    """Per-device GPipe loop.  stage_params leaves: (1, ...) — this stage;
    xs: (M, mb_local, ...) microbatches (same on every pipe rank)."""
    idx = lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    is_first = idx == 0
    is_last = idx == s - 1
    fwd_perm = [(i, i + 1) for i in range(s - 1)]

    def tick(carry, t):
        buf, ys = carry
        # stage 0 injects microbatch t (clamped; ticks >= M are drain-only)
        x_in = lax.dynamic_index_in_dim(xs, jnp.minimum(t, m - 1), axis=0,
                                        keepdims=False)
        inp = jnp.where(is_first, x_in, buf)
        y = stage_fn(params, inp)
        # collect finished microbatches; warm-up ticks (t < s-1) all write
        # slot 0 and are overwritten by the first valid write at t = s-1.
        # Non-last stages accumulate garbage here — masked out by the psum
        # below, and the where() there also zeroes their cotangents in AD.
        slot = jnp.maximum(t - (s - 1), 0)
        ys = lax.dynamic_update_index_in_dim(ys, y, slot, axis=0)
        buf_next = lax.ppermute(y, axis, fwd_perm)
        return (buf_next, ys), None

    buf0 = jnp.zeros(xs.shape[1:], xs.dtype)
    ys0 = jnp.zeros_like(xs)
    (_, ys), _ = lax.scan(tick, (buf0, ys0), jnp.arange(m + s - 1))
    # only the last stage holds real outputs; broadcast over the pipe axis
    ys = lax.psum(jnp.where(is_last, ys, jnp.zeros_like(ys)), axis)
    return ys
