"""Pipeline parallelism over a ``pipe`` mesh axis (GPipe + 1F1B schedules).

Not in the reference (its only parallelism is async-PS data parallelism,
SURVEY.md §2.14); built because the framework treats pipeline sharding as a
first-class mesh axis alongside data/fsdp/tensor/seq.

TPU-native design: SPMD, not per-stage processes.  Stage parameters carry a
leading ``stage`` logical axis sharded over ``pipe`` (rule table
``("stage", "pipe")``, parallel/sharding.py); execution runs under
``jax.shard_map`` where each device holds exactly one stage's weights and
activations hop stage→stage via ``lax.ppermute`` over ICI.

Two schedules:

* :func:`pipeline_apply` — GPipe: a ``lax.scan`` over M + S - 1 forward
  ticks; reverse-mode AD through the scan+ppermute gives the backward
  pipeline automatically.  Simple and composes with any outer loss, but AD
  stores ALL M microbatch activations per stage.
* :func:`pipeline_train_1f1b` — PipeDream-flush (1F1B): forward and
  backward microbatches interleave on one global tick clock, so a stage
  holds at most S in-flight activations instead of M — the schedule that
  lets M grow (and the bubble fraction (S-1)/(M+S-1) shrink) without the
  GPipe activation blow-up.  The loss runs INSIDE the last stage (that is
  what makes interleaving possible), so this primitive returns gradients
  directly rather than composing with an outer ``jax.grad``.  Stage inputs
  are re-materialized from the stashed stage INPUT during each backward
  tick (remat-style), which is what bounds the stash at S small input
  buffers.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.parallel.collectives import axis_size, shard_map_fn


def _validate(mesh, axis, stage_params, x, m, batch_axes):
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    s = mesh.shape[axis]
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"num_microbatches={m}")
    leaves = jax.tree_util.tree_leaves(stage_params)
    if leaves and leaves[0].shape[0] != s:
        raise ValueError(f"stage_params leading dim {leaves[0].shape[0]} "
                         f"!= {axis} axis size {s}")
    if batch_axes is None:
        from dtf_tpu.parallel.sharding import data_axes as _data_axes
        batch_axes = _data_axes(mesh)
    mb = x.shape[0] // m
    data_size = 1
    for a in batch_axes:
        data_size *= mesh.shape[a]
    if mb % data_size:
        raise ValueError(f"microbatch size {mb} (batch {x.shape[0]} / "
                         f"{m} microbatches) not divisible by data-axis "
                         f"size {data_size}")
    return s, mb, tuple(batch_axes)


def _mb_spec(batch_axes, ndim):
    """Spec for an (M, mb, ...) microbatched array: M replicated, batch dim
    sharded over the data axes."""
    return P(None, batch_axes or None, *([None] * (ndim - 2)))


def _ctx_at(ctx, k):
    return jax.tree_util.tree_map(
        lambda c: lax.dynamic_index_in_dim(c, k, axis=0, keepdims=False),
        ctx)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, *, num_microbatches: int, axis: str = "pipe",
                   batch_axes: Optional[tuple] = None,
                   ctx: Any = None) -> tuple:
    """Run ``x`` through S pipeline stages (GPipe schedule).

    ``stage_fn(params_one_stage, x_mb, ctx_mb) -> (y_mb, aux_scalar)`` must
    preserve the activation shape (e.g. a block of transformer layers);
    ``aux_scalar`` carries differentiable per-stage side losses (MoE router
    aux; return 0.0 when unused).  ``stage_params`` is a pytree whose every
    leaf has leading dim S (the stage axis, sharded over ``axis``).
    ``x``: (B, ...) global batch; B must be divisible by
    ``num_microbatches`` (× the data-axis size, if present).  ``ctx``: an
    optional pytree of per-example side inputs with leading dim B (padding
    masks etc.), microbatched alongside ``x`` and fed to every stage.
    Returns ``(y, aux_sum)`` — the last stage's output (B, ...) and the sum
    of every stage's aux over all microbatches.
    """
    m = num_microbatches
    s, mb, batch_axes = _validate(mesh, axis, stage_params, x, m, batch_axes)
    xs = x.reshape(m, mb, *x.shape[1:])
    ctx = jax.tree_util.tree_map(
        lambda c: c.reshape(m, mb, *c.shape[1:]), ctx)

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    x_spec = _mb_spec(batch_axes, xs.ndim)
    ctx_spec = jax.tree_util.tree_map(lambda c: _mb_spec(batch_axes, c.ndim),
                                      ctx)

    body = functools.partial(_per_device_pipeline, stage_fn, s=s, m=m,
                             axis=axis, data_axes=batch_axes)
    mapped = shard_map_fn(
        body, mesh=mesh, in_specs=(param_spec, x_spec, ctx_spec),
        out_specs=(x_spec, P()))
    ys, aux = mapped(stage_params, xs, ctx)
    return ys.reshape(x.shape[0], *x.shape[1:]), aux


def _per_device_pipeline(stage_fn, stage_params, xs, ctx, *, s: int, m: int,
                         axis: str, data_axes: tuple):
    """Per-device GPipe loop.  stage_params leaves: (1, ...) — this stage;
    xs: (M, mb_local, ...) microbatches (same on every pipe rank)."""
    idx = lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    is_first = idx == 0
    is_last = idx == s - 1
    fwd_perm = [(i, i + 1) for i in range(s - 1)]

    def tick(carry, t):
        buf, ys, aux_sum = carry
        # stage i processes microbatch t - i; clamp covers warmup/drain
        k = jnp.clip(t - idx, 0, m - 1)
        x_in = lax.dynamic_index_in_dim(xs, jnp.minimum(t, m - 1), axis=0,
                                        keepdims=False)
        inp = jnp.where(is_first, x_in, buf)
        y, aux = stage_fn(params, inp, _ctx_at(ctx, k))
        # collect finished microbatches; warm-up ticks (t < s-1) all write
        # slot 0 and are overwritten by the first valid write at t = s-1.
        # Non-last stages accumulate garbage here — masked out by the psum
        # below, and the where() there also zeroes their cotangents in AD.
        slot = jnp.maximum(t - (s - 1), 0)
        ys = lax.dynamic_update_index_in_dim(ys, y, slot, axis=0)
        # aux is garbage outside this stage's active window — mask it
        valid = (t >= idx) & (t - idx < m)
        aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        buf_next = lax.ppermute(y, axis, fwd_perm)
        return (buf_next, ys, aux_sum), None

    buf0 = jnp.zeros(xs.shape[1:], xs.dtype)
    ys0 = jnp.zeros_like(xs)
    (_, ys, aux_sum), _ = lax.scan(
        tick, (buf0, ys0, jnp.zeros((), jnp.float32)),
        jnp.arange(m + s - 1))
    # only the last stage holds real outputs; broadcast over the pipe axis
    ys = lax.psum(jnp.where(is_last, ys, jnp.zeros_like(ys)), axis)
    # per-stage aux: sum over pipe ranks; mean over data ranks (aux is a
    # per-token mean within each shard's rows)
    aux_sum = lax.psum(aux_sum, axis)
    if data_axes:
        aux_sum = lax.pmean(aux_sum, data_axes)
    return ys, aux_sum


# --------------------------------------------------------------------------
# 1F1B (PipeDream-flush)
# --------------------------------------------------------------------------

def pipeline_train_1f1b(stage_fn: Callable, loss_fn: Callable,
                        stage_params: Any, head_params: Any, x: jax.Array,
                        ctx: Any, mesh: Mesh, *, num_microbatches: int,
                        axis: str = "pipe", aux_weight: float = 0.0,
                        batch_axes: Optional[tuple] = None,
                        diff_ctx: Optional[dict] = None) -> tuple:
    """One pipelined forward+backward pass under the 1F1B schedule.

    Schedule (global tick clock, S stages, M microbatches): stage ``i``
    runs the forward of microbatch ``k`` at tick ``2k + i`` and its
    backward at tick ``2k + 2S - 1 - i`` — forwards and backwards
    interleave, so at most ``S - i`` microbatches are ever in flight at
    stage ``i`` (vs all M under GPipe-by-AD).  Total ticks 2(M + S - 1);
    bubble fraction (S-1)/(M+S-1), same per-M as GPipe — the win is that
    the O(S) activation footprint lets M grow until the bubble is
    negligible.  Each backward tick re-materializes the stage forward from
    the stashed stage INPUT (remat-style; the stash holds S small input
    buffers, not full per-layer activations).

    Contracts:

    * ``stage_fn(params_one_stage, x_mb, ctx_mb) -> (y_mb, aux_scalar)``
      — shape-preserving; ``aux_scalar`` differentiable (MoE router loss);
    * ``loss_fn(head_params, y_mb, ctx_mb) -> scalar`` — the LAST stage
      maps its output straight to the training loss (mean over the
      microbatch rows); running the loss inside the pipeline is what makes
      fwd/bwd interleaving possible;
    * ``ctx``: pytree of per-example side inputs, leading dim B (labels,
      masks); not differentiated;
    * ``diff_ctx``: optional dict of per-example side inputs that ARE
      differentiated — e.g. the encoder output every T5 decoder stage
      cross-attends to.  Stage/loss fns see them merged into their ctx
      dict; each stage's backward contributes that microbatch's cotangent
      and the contributions are summed over the pipe axis.

    Total objective: ``mean_k loss_k + aux_weight * sum_{stage,k} aux / M``.

    Returns ``(loss_mean, stage_grads, head_grads, dx)`` — grads for the
    S-stacked stage params, the head/loss params, and the cotangent of
    ``x`` (flows back into pre-pipeline embedding layers; differentiate
    those with an outer ``jax.vjp`` around the embedding computation).
    With ``diff_ctx``, a fifth element ``d_diff_ctx`` (same structure /
    batch shape as ``diff_ctx``) is appended.  Grads are already pmean'd
    over the data axes.
    """
    m = num_microbatches
    s, mb, batch_axes = _validate(mesh, axis, stage_params, x, m, batch_axes)
    xs = x.reshape(m, mb, *x.shape[1:])
    ctx = jax.tree_util.tree_map(
        lambda c: c.reshape(m, mb, *c.shape[1:]), ctx)
    dctx_in = diff_ctx
    if dctx_in is not None:
        dctx_in = jax.tree_util.tree_map(
            lambda c: c.reshape(m, mb, *c.shape[1:]), dctx_in)

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    head_spec = jax.tree_util.tree_map(lambda _: P(), head_params)
    x_spec = _mb_spec(batch_axes, xs.ndim)
    ctx_spec = jax.tree_util.tree_map(lambda c: _mb_spec(batch_axes, c.ndim),
                                      ctx)
    dctx_spec = jax.tree_util.tree_map(
        lambda c: _mb_spec(batch_axes, c.ndim), dctx_in)

    body = functools.partial(_per_device_1f1b, stage_fn, loss_fn, s=s, m=m,
                             axis=axis, aux_weight=aux_weight,
                             data_axes=batch_axes,
                             has_dctx=dctx_in is not None)
    mapped = shard_map_fn(
        body, mesh=mesh,
        in_specs=(param_spec, head_spec, x_spec, ctx_spec, dctx_spec),
        out_specs=(P(), param_spec, head_spec, x_spec, dctx_spec))
    loss, sgrads, hgrads, dxs, ddctx = mapped(stage_params, head_params,
                                              xs, ctx, dctx_in)
    if dctx_in is None:
        return loss, sgrads, hgrads, dxs.reshape(x.shape)
    ddctx = jax.tree_util.tree_map(
        lambda g, c: g.reshape(c.shape), ddctx, diff_ctx)
    return loss, sgrads, hgrads, dxs.reshape(x.shape), ddctx


def _per_device_1f1b(stage_fn, loss_fn, stage_params, head_params, xs, ctx,
                     dctx, *, s: int, m: int, axis: str, aux_weight: float,
                     data_axes: tuple, has_dctx: bool):
    """Per-device 1F1B loop (see pipeline_train_1f1b for the schedule)."""
    idx = lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    is_first = idx == 0
    is_last = idx == s - 1
    fwd_perm = [(i, i + 1) for i in range(s - 1)]
    bwd_perm = [(i, i - 1) for i in range(1, s)]
    f32 = functools.partial(jax.tree_util.tree_map,
                            lambda p: jnp.zeros(p.shape, jnp.float32))

    act_shape = xs.shape[1:]
    if not has_dctx:
        dctx = {}
    dc0 = _ctx_at(dctx, 0)        # zero-cotangent template per microbatch

    def merged(ctx_k, dc_k):
        return {**ctx_k, **dc_k} if has_dctx else ctx_k

    def fwd_compute(x_in, ctx_k, dc_k):
        y, aux = stage_fn(params, x_in, merged(ctx_k, dc_k))
        return y, jnp.asarray(aux, jnp.float32)

    def bwd_last(x_res, ctx_k, dc_k, _dy):
        def f(p, hp, xx, dc):
            c = merged(ctx_k, dc)
            y, aux = stage_fn(p, xx, c)
            l = loss_fn(hp, y, c)
            # differentiate the total; report the pure loss (aux is a
            # regularizer, not the training metric)
            return l + aux_weight * jnp.asarray(aux, jnp.float32), l
        _, vjp, l_pure = jax.vjp(f, params, head_params, x_res, dc_k,
                                 has_aux=True)
        dp, dhp, dx, ddc = vjp(jnp.asarray(1.0 / m, jnp.float32))
        return dp, dhp, dx, ddc, l_pure

    def bwd_mid(x_res, ctx_k, dc_k, dy):
        def f(p, xx, dc):
            return stage_fn(p, xx, merged(ctx_k, dc))
        _, vjp = jax.vjp(f, params, x_res, dc_k)
        dp, dx, ddc = vjp((dy, jnp.asarray(aux_weight / m, jnp.float32)))
        return dp, jax.tree_util.tree_map(jnp.zeros_like, head_params), \
            dx, ddc, jnp.zeros((), jnp.float32)

    def tick(carry, t):
        buf_f, buf_b, stash, gsum, hsum, dxs, dcs, loss_sum = carry

        # ---- forward slot: stage i, microbatch kf at tick 2*kf + i
        kf = (t - idx) // 2
        do_f = ((t - idx) % 2 == 0) & (kf >= 0) & (kf < m)
        kfc = jnp.clip(kf, 0, m - 1)
        x_in = jnp.where(
            is_first,
            lax.dynamic_index_in_dim(xs, kfc, axis=0, keepdims=False),
            buf_f)
        y_send = lax.cond(
            do_f, lambda: fwd_compute(x_in, _ctx_at(ctx, kfc),
                                      _ctx_at(dctx, kfc))[0],
            lambda: jnp.zeros(act_shape, xs.dtype))
        stash = lax.cond(
            do_f,
            lambda: lax.dynamic_update_index_in_dim(stash, x_in, kfc % s,
                                                    axis=0),
            lambda: stash)

        # ---- backward slot: stage i, microbatch kb at tick 2*kb + 2S-1-i
        tb = t - (2 * s - 1 - idx)
        kb = tb // 2
        do_b = (tb % 2 == 0) & (kb >= 0) & (kb < m)
        kbc = jnp.clip(kb, 0, m - 1)
        x_res = lax.dynamic_index_in_dim(stash, kbc % s, axis=0,
                                         keepdims=False)

        def run_bwd():
            dp, dhp, dx, ddc, l = lax.cond(
                is_last,
                lambda: bwd_last(x_res, _ctx_at(ctx, kbc),
                                 _ctx_at(dctx, kbc), buf_b),
                lambda: bwd_mid(x_res, _ctx_at(ctx, kbc),
                                _ctx_at(dctx, kbc), buf_b))
            return dp, dhp, dx, ddc, l

        def skip_bwd():
            return (jax.tree_util.tree_map(jnp.zeros_like, params),
                    jax.tree_util.tree_map(jnp.zeros_like, head_params),
                    jnp.zeros(act_shape, xs.dtype),
                    jax.tree_util.tree_map(jnp.zeros_like, dc0),
                    jnp.zeros((), jnp.float32))

        dp, dhp, dx_send, ddc, l = lax.cond(do_b, run_bwd, skip_bwd)
        gsum = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), gsum, dp)
        hsum = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), hsum, dhp)
        loss_sum = loss_sum + l
        dxs = lax.cond(
            do_b & is_first,
            lambda: lax.dynamic_update_index_in_dim(dxs, dx_send, kbc,
                                                    axis=0),
            lambda: dxs)
        # every stage contributes its cross-attention cotangent for kb;
        # each device hits each microbatch once, so a read-add-update is
        # an exact accumulate (summed over stages by the psum below)
        dcs = lax.cond(
            do_b,
            lambda: jax.tree_util.tree_map(
                lambda acc, g: lax.dynamic_update_index_in_dim(
                    acc,
                    lax.dynamic_index_in_dim(acc, kbc, axis=0,
                                             keepdims=False)
                    + g.astype(jnp.float32), kbc, axis=0),
                dcs, ddc),
            lambda: dcs)

        # unconditional collectives: every device participates every tick
        buf_f = lax.ppermute(y_send, axis, fwd_perm)
        buf_b = lax.ppermute(dx_send, axis, bwd_perm)
        return (buf_f, buf_b, stash, gsum, hsum, dxs, dcs, loss_sum), None

    carry0 = (jnp.zeros(act_shape, xs.dtype),
              jnp.zeros(act_shape, xs.dtype),
              jnp.zeros((s, *act_shape), xs.dtype),
              f32(params), f32(head_params),
              jnp.zeros_like(xs), f32(dctx),
              jnp.zeros((), jnp.float32))
    (_, _, _, gsum, hsum, dxs, dcs, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(2 * (m + s - 1)))

    # head grads / loss live on the last stage, dxs on the first: share
    hsum = jax.tree_util.tree_map(lambda g: lax.psum(g, axis), hsum)
    loss_mean = lax.psum(loss_sum, axis) / m
    dxs = lax.psum(jnp.where(is_first, dxs, jnp.zeros_like(dxs)), axis)
    dcs = jax.tree_util.tree_map(lambda g: lax.psum(g, axis), dcs)
    if data_axes:
        pm = lambda g: lax.pmean(g, data_axes)
        gsum = jax.tree_util.tree_map(pm, gsum)
        hsum = jax.tree_util.tree_map(pm, hsum)
        loss_mean = pm(loss_mean)
        # loss_fn averaged over the LOCAL shard's rows; per-row input
        # cotangents must reflect the GLOBAL per-microbatch mean (grads
        # handle this via the pmean above — dxs rows are per-shard)
        dsize = 1
        for a in data_axes:
            dsize *= axis_size(a)
        dxs = dxs / dsize
        dcs = jax.tree_util.tree_map(lambda g: g / dsize, dcs)
    # re-add the stacked stage dim so out_specs P(axis) reassembles (S, ...)
    gsum = jax.tree_util.tree_map(lambda g: g[None], gsum)
    dcs_out = (jax.tree_util.tree_map(
        lambda g, c: g.astype(c.dtype), dcs, dctx) if has_dctx else None)
    return loss_mean, gsum, hsum, dxs, dcs_out


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Idle fraction of the pipeline: (S-1)/(M+S-1) for both schedules —
    1F1B's O(S) activation memory is what lets M grow to shrink this."""
    s, m = num_stages, num_microbatches
    return (s - 1) / (m + s - 1)
