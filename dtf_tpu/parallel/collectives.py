"""Explicit collectives for shard_map-style SPMD code.

The reference's data plane was implicit gRPC Send/Recv traffic inserted by
the TF graph partitioner at the PS<->worker cut (SURVEY.md §5.8): every step,
each worker pulled all parameters and pushed all gradients asynchronously.
The TPU-native data plane is XLA collectives over ICI, used two ways:

1. implicitly — GSPMD inserts them from sharding annotations (preferred);
2. explicitly — inside ``jax.shard_map`` per-device code, via these wrappers.

These are thin, named wrappers so framework code reads at the level of the
design ("all-reduce the gradients over the data axis") and so tests can
exercise each primitive on a CPU-simulated mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def all_reduce_mean(tree: Any, axis: "str | Sequence[str]") -> Any:
    """Mean-all-reduce a pytree over mesh axis/axes (gradient sync).

    Replaces the reference's asynchronous per-worker ``apply_gradients`` on
    the PS (tf_distributed.py:75-76) with a synchronous psum/mean.
    """
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis), tree)


def all_reduce_sum(tree: Any, axis: "str | Sequence[str]") -> Any:
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis), tree)


def all_gather(x: jax.Array, axis: str, *, tiled_axis: int = 0) -> jax.Array:
    """Gather shards along a mesh axis, concatenating on ``tiled_axis``.

    ``tiled=True`` semantics (pinned by tests/test_mesh.py): the output's
    ``tiled_axis`` dim is ``axis_size * x.shape[tiled_axis]``, shards
    concatenated in mesh-axis-index order — rank k's block sits at
    ``[k*n : (k+1)*n]``.
    """
    return lax.all_gather(x, axis, axis=tiled_axis, tiled=True)


def reduce_scatter(x: jax.Array, axis: str, *, scatter_axis: int = 0) -> jax.Array:
    """Sum-reduce over the mesh axis, leaving each device its shard.

    ``tiled=True`` semantics (pinned by tests/test_mesh.py): the input's
    ``scatter_axis`` dim splits evenly over the axis; rank k keeps the
    summed ``[k*m/n : (k+1)*m/n]`` block.  An indivisible dim is a layout
    bug upstream (grad_sync's bucket layout pads for exactly this), so it
    fails here with the shape arithmetic spelled out instead of deep in
    XLA.
    """
    n = axis_size(axis)
    dim = x.shape[scatter_axis]
    if dim % n:
        raise ValueError(
            f"reduce_scatter: dim {dim} of axis {scatter_axis} is not "
            f"divisible by mesh axis {axis!r} (size {n}); pad the scatter "
            f"dim to a multiple of {n} (grad_sync's bucket layout does "
            f"this for gradient vectors)")
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ring_neighbors(n: int, *, shift: int = 1) -> list:
    """The ``ppermute`` permutation for one hop around an ``n``-device
    ring — THE forward schedule shared by every hand-scheduled ring here
    and in parallel/quantize.py (one definition, so the legacy ring and
    the per-hop-requantizing grad-sync ring can never disagree on
    direction)."""
    return [(i, (i + shift) % n) for i in range(n)]


def ring_permute(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Send to the next device along a mesh axis ring (ppermute).

    Building block for ring attention / pipeline schedules.
    """
    return lax.ppermute(x, axis, ring_neighbors(axis_size(axis),
                                                shift=shift))


def all_to_all(x: jax.Array, axis: str, *, split_axis: int, concat_axis: int) -> jax.Array:
    """All-to-all over a mesh axis (Ulysses-style sequence<->head reshard,
    MoE token dispatch)."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis,
                          tiled=True)


# Quantization granularity: one f32 scale per this many values.  A single
# outlier then only inflates the step size of its own block instead of the
# whole chunk (~an order of magnitude less error on heavy-tailed gradient
# distributions), for 4 bytes of scale overhead per 256 int8 payload bytes
# (~1.6% extra wire traffic).  THE block format lives in
# parallel/quantize.py (the grad_sync wire shares it); the ring below
# delegates so there is exactly one quantizer definition.
_QBLOCK = 256


def _quantize_int8(v: jax.Array) -> tuple:
    """Symmetric per-block int8 quantization of a flat (m,) chunk whose m
    is a _QBLOCK multiple: (q int8 (nb, B), scales f32 (nb, 1)).
    Delegates to quantize.encode (nearest rounding — the ring
    re-quantizes per hop and must stay deterministic)."""
    from dtf_tpu.parallel import quantize as qz
    assert qz.QBLOCK == _QBLOCK
    return qz.encode(v)


def _dequantize_int8(q: jax.Array, scale: jax.Array) -> jax.Array:
    from dtf_tpu.parallel import quantize as qz
    return qz.decode(q, scale)


def quantized_ring_all_reduce_mean(x: jax.Array, axis: str) -> jax.Array:
    """Mean-all-reduce with an int8 wire format (EQuARX-style, cf.
    PAPERS.md "Efficient Quantized AllReduce in XLA"): a hand-scheduled
    ring — reduce-scatter then all-gather over ``ppermute`` — where every
    hop ships int8 payloads + per-block f32 scales (one per _QBLOCK
    values) instead of f32 tensors, ~4x less ICI traffic for
    bandwidth-bound gradient syncs.

    Per-device code (call inside ``shard_map``).  Deterministic and
    identical on every device (the gather phase distributes each reduced
    chunk through the same quantize/dequantize path to all ranks, so no
    rank-dependent rounding survives).  Quantization noise: one
    round-to-nearest per reduce hop (n-1 of them) plus one on the gather,
    each bounded by its block's own max — relative error ~1e-3 on typical
    gradients (see tests/test_quantized_allreduce.py's measured bound and
    convergence A/B); use exact ``pmean`` when that matters more than
    bandwidth.

    The grad-sync engine's ``--grad_comm_dtype int8_ring`` wire is the
    productionized sibling (quantize.ring_reduce_scatter_quantized):
    same per-hop requantizing RS schedule, plus stochastic rounding,
    per-hop error accounting, and the bucket-layout contract.  This
    whole-tensor helper stays as the legacy ``--grad_compression int8``
    path and the minimal reference the parity tests pin.
    """
    n = axis_size(axis)
    if n == 1:
        return x
    me = lax.axis_index(axis)
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    m = -(-flat.size // n)
    m = -(-m // _QBLOCK) * _QBLOCK          # per-block scales need full blocks
    buf = jnp.pad(flat, (0, n * m - flat.size)).reshape(n, m)

    fwd = ring_neighbors(n)

    # reduce-scatter: after n-1 hops, rank i owns the full sum of chunk
    # (i+1) mod n.  Each hop ships the partial sum quantized.
    for s in range(n - 1):
        send_idx = (me - s) % n
        recv_idx = (me - s - 1) % n
        q, scale = _quantize_int8(jnp.take(buf, send_idx, axis=0))
        q = lax.ppermute(q, axis, fwd)
        scale = lax.ppermute(scale, axis, fwd)
        buf = buf.at[recv_idx].add(_dequantize_int8(q, scale))

    # broadcast each finished chunk through ONE shared quantization so all
    # ranks (including the owner) hold bitwise-identical values.
    own_idx = (me + 1) % n
    q, scale = _quantize_int8(jnp.take(buf, own_idx, axis=0))
    buf = buf.at[own_idx].set(_dequantize_int8(q, scale))

    # all-gather: circulate the quantized chunks n-1 hops — each rank just
    # forwards the (q, scale) it received last hop, nothing is re-read
    # from buf on the send side.
    for s in range(n - 1):
        recv_idx = (me - s) % n
        q = lax.ppermute(q, axis, fwd)
        scale = lax.ppermute(scale, axis, fwd)
        buf = buf.at[recv_idx].set(_dequantize_int8(q, scale))

    out = buf.reshape(-1)[: flat.size].reshape(shape) / n
    return out.astype(dtype)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Size of a mapped mesh axis from inside shard_map'd code.  Newer jax
    spells this ``lax.axis_size``; older releases constant-fold the classic
    ``psum(1, axis)`` idiom to the same static int."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def shard_map_fn(fn, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Wrap ``shard_map`` with the framework's mesh conventions.

    THE shard_map entry point for the whole framework (trainer, pipeline
    schedules, ring/ulysses attention route through here): newer jax exposes
    ``jax.shard_map(..., check_vma=)``, older releases only
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` — same
    semantics, renamed flag."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)
