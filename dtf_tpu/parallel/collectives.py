"""Explicit collectives for shard_map-style SPMD code.

The reference's data plane was implicit gRPC Send/Recv traffic inserted by
the TF graph partitioner at the PS<->worker cut (SURVEY.md §5.8): every step,
each worker pulled all parameters and pushed all gradients asynchronously.
The TPU-native data plane is XLA collectives over ICI, used two ways:

1. implicitly — GSPMD inserts them from sharding annotations (preferred);
2. explicitly — inside ``jax.shard_map`` per-device code, via these wrappers.

These are thin, named wrappers so framework code reads at the level of the
design ("all-reduce the gradients over the data axis") and so tests can
exercise each primitive on a CPU-simulated mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def all_reduce_mean(tree: Any, axis: "str | Sequence[str]") -> Any:
    """Mean-all-reduce a pytree over mesh axis/axes (gradient sync).

    Replaces the reference's asynchronous per-worker ``apply_gradients`` on
    the PS (tf_distributed.py:75-76) with a synchronous psum/mean.
    """
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis), tree)


def all_reduce_sum(tree: Any, axis: "str | Sequence[str]") -> Any:
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis), tree)


def all_gather(x: jax.Array, axis: str, *, tiled_axis: int = 0) -> jax.Array:
    """Gather shards along a mesh axis, concatenating on ``tiled_axis``."""
    return lax.all_gather(x, axis, axis=tiled_axis, tiled=True)


def reduce_scatter(x: jax.Array, axis: str, *, scatter_axis: int = 0) -> jax.Array:
    """Sum-reduce over the mesh axis, leaving each device its shard."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ring_permute(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Send to the next device along a mesh axis ring (ppermute).

    Building block for ring attention / pipeline schedules.
    """
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x: jax.Array, axis: str, *, split_axis: int, concat_axis: int) -> jax.Array:
    """All-to-all over a mesh axis (Ulysses-style sequence<->head reshard,
    MoE token dispatch)."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis,
                          tiled=True)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def shard_map_fn(fn, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Wrap ``jax.shard_map`` with the framework's mesh conventions."""
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=check_vma)
