"""Sharding rules: the declarative replacement for ``replica_device_setter``.

The reference placed every ``tf.Variable`` on the PS job and every compute op
on the local worker (tf_distributed.py:34-36); the partition was implicit in
device strings and the TF graph partitioner inserted gRPC Send/Recv at the
cut.  Here placement is explicit data: each parameter carries *logical* axis
names (e.g. ``("vocab", "embed")``) and a rule table maps logical names to
mesh axes (or ``None`` = replicated).  GSPMD then inserts the collectives.

This is the same logical-axis-rules idea flax/t5x popularised, implemented
standalone so the framework owns its placement policy end to end.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rule table: logical axis name -> mesh axis (None = replicate).
# Covers the built-in model families (MLP, ResNet, BERT/MoE).
DEFAULT_RULES: tuple[tuple[str, Optional[str]], ...] = (
    ("batch", "data"),
    ("vocab", "tensor"),
    ("embed", None),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("joined_kv", "tensor"),
    ("seq", "seq"),
    ("expert", "expert"),
    ("conv_in", None),
    ("conv_out", None),
    ("stage", "pipe"),
)


def fsdp_rules(rules: Sequence[tuple[str, Optional[str]]] = DEFAULT_RULES
               ) -> tuple[tuple[str, Optional[str]], ...]:
    """Rule table for FSDP/ZeRO-style weight sharding: the ``embed`` logical
    axis (present in every large weight) shards over the ``fsdp`` mesh axis,
    so parameters and optimizer state are partitioned there and GSPMD
    all-gathers weights on use / reduce-scatters grads (weight-update
    sharding, cf. PAPERS.md).  Composes with tensor rules: e.g. an MLP
    weight ("embed", "mlp") becomes P("fsdp", "tensor")."""
    table = dict(rules)
    table["embed"] = "fsdp"
    return tuple(table.items())


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules: Sequence[tuple[str, Optional[str]]] = DEFAULT_RULES,
                    mesh: Optional[Mesh] = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    Logical names absent from the rule table (or mapped to a mesh axis the
    mesh doesn't have) become ``None`` (replicated) — so one model definition
    runs unchanged on any mesh shape.  A mesh axis is used at most once per
    spec (first dim wins): e.g. a square weight ("embed", "embed") under
    FSDP rules shards dim 0 only, since PartitionSpec forbids duplicates.
    """
    table = dict(rules)
    out = []
    used = set()
    for name in logical_axes:
        mesh_axis = table.get(name) if name is not None else None
        if mesh is not None and mesh_axis is not None and mesh_axis not in mesh.axis_names:
            mesh_axis = None
        if mesh_axis in used:
            mesh_axis = None
        if mesh_axis is not None:
            used.add(mesh_axis)
        out.append(mesh_axis)
    return P(*out)


def named_sharding(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def replicate(mesh: Mesh, tree: Any = None) -> Any:
    """Fully-replicated sharding (or device_put a tree replicated)."""
    s = NamedSharding(mesh, P())
    if tree is None:
        return s
    return jax.device_put(tree, s)


def data_axes(mesh: Mesh) -> tuple:
    """The mesh axes a batch dim shards over (``data`` and ``fsdp``)."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def data_axis_size(mesh: Mesh) -> int:
    """Total number of batch shards (product of the data-like axis sizes)."""
    n = 1
    for a in data_axes(mesh):
        n *= mesh.shape[a]
    return n


def data_axis_tiles_processes(mesh: Mesh) -> bool:
    """True iff process k's addressable devices hold exactly the k-th
    contiguous 1/nproc block of linear data-axis indices — the layout
    ``put_process_batch`` assumes.  Holds for a leading ``data`` axis;
    fails e.g. for ``pipe=2,data=4`` over 2 processes, where every process
    spans the whole data axis (each host must then feed the full global
    batch)."""
    import numpy as np

    names = mesh.axis_names
    axes = data_axes(mesh)
    if not axes:
        return False
    per: dict = {}
    for idx in np.ndindex(*mesh.devices.shape):
        dlin = 0
        for a in axes:
            dlin = dlin * mesh.shape[a] + idx[names.index(a)]
        per.setdefault(mesh.devices[idx].process_index, set()).add(dlin)
    total = data_axis_size(mesh)
    nproc = len(per)
    if total % nproc:
        return False
    share = total // nproc
    return all(s == set(range(k * share, (k + 1) * share))
               for k, s in sorted(per.items()))


def batch_spec(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over every data-like axis present.

    The reference fed each worker an independent batch via feed_dict
    (tf_distributed.py:108,111); here one global batch is sharded over the
    ``data`` (and ``fsdp``, if present) axes.
    """
    axes = data_axes(mesh)
    return NamedSharding(mesh, P(axes or None, *([None] * (ndim - 1))))


def shard_batch(mesh: Mesh, tree: Any) -> Any:
    """device_put a pytree of arrays with their leading dim sharded over data
    axes; rank-0 leaves (scalars) are replicated."""
    import numpy as np

    def put(x):
        ndim = np.ndim(x)
        sharding = batch_spec(mesh, ndim) if ndim > 0 else replicate(mesh)
        return jax.device_put(x, sharding)
    return jax.tree_util.tree_map(put, tree)


def apply_rules(logical_tree: Any,
                mesh: Mesh,
                rules: Sequence[tuple[str, Optional[str]]] = DEFAULT_RULES) -> Any:
    """Convert a pytree of logical-axis tuples into NamedShardings.

    ``logical_tree`` mirrors a parameter pytree; each leaf is a tuple of
    logical axis names (from the model's ``param_axes``).
    """
    def convert(axes):
        return NamedSharding(mesh, logical_to_spec(axes, rules, mesh))
    return jax.tree_util.tree_map(
        convert, logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))
