"""Measurement-driven sharding planner (``--plan auto``).

The reference assigned placement by hand: every variable pinned to the PS
job, every op to the local worker, and the operator re-tuned batch size /
tower count whenever the model changed (tf_distributed.py:34-36).  The
grown framework kept that manual flavor — ``--grad_sync``, ``--grad_comm_
dtype``, ``--grad_bucket_mb``, model-level ``remat`` are all hand-pinned
flags.  This module closes the loop: given a model template, the mesh, and
a per-device HBM budget, it derives ONE consistent :class:`ShardingPlan`
(parameter placement rules, gradient-sync strategy + bucket size, wire
dtype for the gradient allreduce, activation sharding + remat policy) and
predicts the per-device HBM footprint and step time that plan implies.

Two prediction sources (``PLAN_SOURCES``):

* ``"analytic"`` — closed-form bytes/flops accounting from the model
  template's shapes (``jax.eval_shape`` of ``model.init``) plus a
  transformer activation model.  Always available; used to rank the
  candidate ladder.
* ``"costcards"`` — when a cost-card library captured by the device cost
  observatory (telemetry/costobs.py) exists for this geometry, the
  measured compile-time ``peak_hbm_bytes`` / flops / bytes replace the
  analytic estimate for the *selected* plan, and step time comes from the
  chip roofline (utils/profiling.py).  Measurement beats modeling.

Infeasible (model, budget) pairs are rejected LOUDLY: the raised
:class:`PlanInfeasibleError` names the overflowing component (``"optimizer
state"``, ``"activations"``, ...) and the budget, so the failure reads as
a capacity diagnosis rather than a downstream OOM.  Predictions are
recorded to ``<logdir>/plan.json`` so ``report --explain`` can audit
predicted-vs-measured after the run.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Mapping, Optional

import numpy as np

from dtf_tpu.parallel import sharding as sh

# Literal mirror order: plan/source_idx gauge indexes into this tuple.
PLAN_SOURCES = ("analytic", "costcards")

# File the plan document is recorded to inside the run's logdir (read back
# by ``report --explain`` for the predicted-vs-measured audit).
PLAN_FILENAME = "plan.json"

# Candidate ladders, least intrusive first: the planner walks DOWN the
# mesh's ladder and stops at the first feasible rung, each further rung
# trading compute (remat) or schedule complexity for HBM headroom.
# On a >= _ZERO1_MIN_AXIS-way data axis ZeRO-1 IS the least intrusive
# rung: optimizer state drops to 1/N AND the sharded update was measured
# faster than dense's full-tree quantized allreduce (bench.breakdown
# --plan_ab); dense leads only on narrow meshes where the bucket
# machinery's overhead buys little.
#   (grad_sync, remat, remat_policy)
_ZERO1_MIN_AXIS = 4
_LADDER_NARROW = (
    ("dense", False, "full"),
    ("zero1", False, "full"),
    ("zero1", True, "dots"),
    ("zero1_overlap", True, "full"),
)
_LADDER_WIDE = (
    ("zero1", False, "full"),
    ("zero1", True, "dots"),
    ("zero1_overlap", True, "full"),
)

# Collective scratch: quantized allreduce stages ~2 bucket-sized buffers
# (send + recv) regardless of strategy.
_SCRATCH_BUCKETS = 2.0


class PlanInfeasibleError(ValueError):
    """No rung of the candidate ladder fits the HBM budget.

    The message names the largest component of the *most aggressive*
    candidate (the best the planner could do), so the operator learns
    WHAT overflows, not just that something did.
    """

    def __init__(self, component: str, component_bytes: float,
                 total_bytes: float, budget_bytes: float):
        self.component = component
        self.component_bytes = float(component_bytes)
        self.total_bytes = float(total_bytes)
        self.budget_bytes = float(budget_bytes)
        super().__init__(
            f"no feasible sharding plan: predicted per-device HBM "
            f"{total_bytes / 2**30:.2f} GiB exceeds the "
            f"{budget_bytes / 2**30:.2f} GiB budget even at the most "
            f"aggressive rung (zero1_overlap + full remat); largest "
            f"component is {component!r} at "
            f"{component_bytes / 2**30:.2f} GiB — shrink the model, "
            f"raise --plan_hbm_gb, or add devices to the data/fsdp axes")


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """One consistent answer to "how does this model run on this mesh".

    Everything the trainer needs to configure the gradient path plus the
    predictions that justify it; JSON round-trips via to_doc/from_doc so
    checkpoints can carry the plan and restores can detect plan changes.
    """
    mesh_axes: tuple            # ((name, size), ...) — the planned mesh
    hbm_budget_bytes: float
    source: str                 # one of PLAN_SOURCES
    grad_sync: str              # grad_sync.STRATEGIES member
    grad_bucket_mb: float
    grad_comm_dtype: Optional[str]
    quant_rounding: str
    remat: bool
    remat_policy: str
    predicted_hbm_bytes: float
    predicted_step_ms: float    # 0.0 = no roofline/card basis to predict
    components: tuple           # ((name, bytes), ...) analytic breakdown

    def to_doc(self) -> dict:
        d = dataclasses.asdict(self)
        d["mesh_axes"] = [list(p) for p in self.mesh_axes]
        d["components"] = [list(p) for p in self.components]
        return d

    @classmethod
    def from_doc(cls, doc: Mapping[str, Any]) -> "ShardingPlan":
        d = dict(doc)
        d["mesh_axes"] = tuple((str(n), int(s)) for n, s in d["mesh_axes"])
        d["components"] = tuple((str(n), float(b))
                                for n, b in d["components"])
        return cls(**d)

    def activation_sharding(self, mesh) -> Any:
        """NamedSharding for rank-3 (B, T, D) activations: batch dim over
        the data-like axes and the hidden dim over ``tensor`` when the
        mesh has one — the layout the partitioner's own preferred
        transition points agree with, which is what suppresses the
        "involuntary full rematerialization" warnings (measured 8 -> 0 on
        the data=2,fsdp=2,tensor=2 dryrun mesh; batch-only still left 4,
        since the embedding gather and attention want D over tensor)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        axes = sh.data_axes(mesh)
        tensor = "tensor" if "tensor" in mesh.axis_names else None
        return NamedSharding(mesh, P(axes or None, None, tensor))

    def summary(self) -> str:
        wire = self.grad_comm_dtype or "f32"
        return (f"plan[{self.source}]: {self.grad_sync}/{wire} "
                f"bucket={self.grad_bucket_mb:g}MB "
                f"remat={'on(' + self.remat_policy + ')' if self.remat else 'off'} "
                f"hbm={self.predicted_hbm_bytes / 2**30:.2f}GiB"
                f"/{self.hbm_budget_bytes / 2**30:.2f}GiB")


# ---------------------------------------------------------------------------
# Analytic component accounting
# ---------------------------------------------------------------------------

def _leaf_bytes(leaf) -> float:
    return float(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize


def _sharded_param_bytes(model, mesh, shapes) -> float:
    """Per-device parameter bytes under the implicit-mode rule table
    (fsdp rules when the mesh has an fsdp axis, defaults otherwise)."""
    import jax

    leaves = jax.tree_util.tree_leaves(shapes)
    axes_fn = getattr(model, "param_axes", None)
    if axes_fn is None:
        return sum(_leaf_bytes(l) for l in leaves)
    rules = sh.fsdp_rules() if "fsdp" in mesh.axis_names else sh.DEFAULT_RULES
    shardings = sh.apply_rules(axes_fn(), mesh, rules)
    total = 0.0
    for leaf, s in zip(leaves, jax.tree_util.tree_leaves(shardings)):
        local = s.shard_shape(leaf.shape)
        total += float(np.prod(local)) * np.dtype(leaf.dtype).itemsize
    return total


def _param_shapes(model):
    import jax
    return jax.eval_shape(model.init, jax.random.PRNGKey(0))


def _opt_state_bytes(optimizer, shapes) -> float:
    """Full (unsharded) optimizer-state bytes for the param template."""
    import jax

    if optimizer is None:
        return 0.0
    try:
        st = jax.eval_shape(optimizer.init, shapes)
    except Exception:
        # optimizers whose init can't be shape-traced: assume adam-like 2x
        return 2.0 * sum(_leaf_bytes(l)
                         for l in jax.tree_util.tree_leaves(shapes))
    return sum(_leaf_bytes(l) for l in jax.tree_util.tree_leaves(st))


def _activation_bytes(model, local_batch: int, remat: bool,
                      remat_policy: str) -> float:
    """Saved-for-backward activation bytes under the given remat policy.

    Transformer coefficient model when the template exposes the BERT-ish
    config attrs (dim / num_layers / mlp_dim / max_len); a generic
    hidden-width fallback otherwise (MLPs).  Coefficients count the f32
    tensors autodiff keeps live: ~10 D-wide + 2 F-wide residuals per
    layer without remat, ~4 D-wide (dot outputs) under "dots", layer
    boundaries only (1 D-wide) under "full".
    """
    cfg = getattr(model, "cfg", None)
    dim = getattr(cfg, "dim", None)
    if cfg is not None and dim is not None:
        n_layers = int(getattr(cfg, "num_layers", 1))
        mlp_dim = int(getattr(cfg, "mlp_dim", 4 * dim))
        seq = int(getattr(cfg, "max_len", 128))
        if remat and remat_policy == "full":
            per_layer = 1.0 * dim
        elif remat:                       # "dots": keep matmul outputs
            per_layer = 4.0 * dim
        else:
            per_layer = 10.0 * dim + 2.0 * mlp_dim
        return float(local_batch) * seq * per_layer * n_layers * 4.0
    hidden = float(getattr(model, "hidden", 0) or
                   getattr(model, "in_dim", 0) or 1024)
    return float(local_batch) * hidden * 4.0 * 4.0


def _logits_bytes(model, local_batch: int) -> float:
    cfg = getattr(model, "cfg", None)
    vocab = getattr(cfg, "vocab_size", None)
    if cfg is not None and vocab is not None:
        k = int(getattr(cfg, "mlm_predictions", 0) or
                getattr(cfg, "max_len", 128))
        return float(local_batch) * k * vocab * 4.0
    classes = float(getattr(model, "num_classes", 10))
    return float(local_batch) * classes * 4.0


def _components(model, mesh, *, batch_size: int, grad_sync: str,
                grad_bucket_mb: float, remat: bool,
                remat_policy: str, optimizer=None) -> tuple:
    """Analytic per-device HBM breakdown for one candidate, as
    ((name, bytes), ...) sorted largest-first."""
    import jax

    shapes = _param_shapes(model)
    n = max(1, sh.data_axis_size(mesh))
    local_batch = max(1, batch_size // n)

    param_b = _sharded_param_bytes(model, mesh, shapes)
    full_param_b = sum(_leaf_bytes(l)
                       for l in jax.tree_util.tree_leaves(shapes))
    opt_b = _opt_state_bytes(optimizer, shapes)

    # Gradients: a full f32 copy of the params lives across the sync;
    # zero1_overlap accumulates into the 1/N owned shard instead.
    grad_b = full_param_b / n if grad_sync == "zero1_overlap" else full_param_b
    # ZeRO-1: optimizer state is partitioned over the sync shards.
    if grad_sync in ("zero1", "zero1_overlap"):
        opt_b = opt_b / n

    comps = (
        ("params", param_b),
        ("gradients", grad_b),
        ("optimizer state", opt_b),
        ("activations", _activation_bytes(model, local_batch, remat,
                                          remat_policy)),
        ("logits", _logits_bytes(model, local_batch)),
        ("collective scratch",
         _SCRATCH_BUCKETS * grad_bucket_mb * 2.0**20),
    )
    return tuple(sorted(comps, key=lambda kv: -kv[1]))


# ---------------------------------------------------------------------------
# Cost-card / roofline measurement basis
# ---------------------------------------------------------------------------

def _find_step_card(logdir: Optional[str], batch_size: int):
    """The train/step cost card matching this geometry, if captured."""
    if not logdir:
        return None
    from dtf_tpu.telemetry import costobs
    try:
        cards = costobs.read_costcards(logdir)
    except FileNotFoundError:
        return None
    want = ["aot", batch_size]
    best = None
    for c in cards:
        if c.site != "train/step":
            continue
        if list(c.geometry) == want or best is None:
            best = c
            if list(c.geometry) == want:
                break
    return best


def _roofline_step_ms(card, mesh) -> float:
    from dtf_tpu.utils import profiling
    dev = np.asarray(mesh.devices).flat[0]
    roof = profiling.chip_roofline(dev)
    if roof is None or card is None:
        return 0.0
    flops = float(card.flops or card.flops_total or 0.0)
    byts = float(card.bytes_accessed or card.bytes_total or 0.0)
    if flops <= 0.0 and byts <= 0.0:
        return 0.0
    return max(flops / roof.peak_flops, byts / roof.hbm_bytes_per_s) * 1e3


def default_hbm_budget(mesh) -> float:
    """Detected per-device HBM capacity (chip roofline table); the
    pinned 4 GiB CPU-sim entry keeps tests deterministic off-TPU."""
    from dtf_tpu.utils import profiling
    dev = np.asarray(mesh.devices).flat[0]
    roof = profiling.chip_roofline(dev)
    if roof is None:
        return float(profiling.CPU_SIM_ROOFLINE.hbm_capacity_bytes)
    return float(roof.hbm_capacity_bytes)


# ---------------------------------------------------------------------------
# The planner
# ---------------------------------------------------------------------------

def _wire_dtype(n_shards: int, pinned: Mapping[str, Any]) -> Optional[str]:
    if "grad_comm_dtype" in pinned:
        return pinned["grad_comm_dtype"]
    # Ring reduce-scatter ships (n-1)/n of the one-shot exchange per
    # direction; the win over all-to-all int8 only materializes with
    # enough hops (parallel/quantize.py:ring_wire_elems).
    if n_shards >= 4:
        return "int8_ring"
    if n_shards >= 2:
        return "int8"
    return None


def make_plan(model, mesh, *, batch_size: int,
              hbm_budget_bytes: Optional[float] = None,
              optimizer=None, logdir: Optional[str] = None,
              pinned: Optional[Mapping[str, Any]] = None) -> ShardingPlan:
    """Derive the least-intrusive feasible plan for (model, mesh, budget).

    ``pinned`` maps knob name -> user-pinned value (flags the operator
    set away from their defaults); the planner never overrides a pinned
    knob — it filters the candidate ladder down to matching rungs and
    only auto-tunes what was left free.  Raises
    :class:`PlanInfeasibleError` when nothing fits.
    """
    pinned = dict(pinned or {})
    budget = float(hbm_budget_bytes if hbm_budget_bytes
                   else default_hbm_budget(mesh))
    base = (_LADDER_WIDE if sh.data_axis_size(mesh) >= _ZERO1_MIN_AXIS
            else _LADDER_NARROW)
    ladder = [c for c in base
              if pinned.get("grad_sync", c[0]) == c[0]
              and pinned.get("remat", c[1]) == c[1]
              and pinned.get("remat_policy", c[2]) == c[2]]
    if not ladder:
        # pinned combination not on the ladder: honor it as the only rung
        ladder = [(pinned.get("grad_sync", "dense"),
                   bool(pinned.get("remat", False)),
                   str(pinned.get("remat_policy", "full")))]

    bucket_mb = float(pinned.get("grad_bucket_mb", 4.0))
    rounding = str(pinned.get("quant_rounding", "nearest"))
    n = max(1, sh.data_axis_size(mesh))

    chosen = None
    comps = None
    for cand in ladder:
        strat, remat, policy = cand
        comps = _components(model, mesh, batch_size=batch_size,
                            grad_sync=strat, grad_bucket_mb=bucket_mb,
                            remat=remat, remat_policy=policy,
                            optimizer=optimizer)
        if sum(b for _, b in comps) <= budget:
            chosen = cand
            break
    if chosen is None:
        name, biggest = comps[0]
        raise PlanInfeasibleError(name, biggest,
                                  sum(b for _, b in comps), budget)

    strat, remat, policy = chosen
    predicted_hbm = sum(b for _, b in comps)
    source = "analytic"
    card = _find_step_card(logdir, batch_size)
    step_ms = 0.0
    if card is not None and card.peak_hbm_bytes:
        # measurement basis: the compile-time memory analysis of the
        # actual train step beats the closed-form model
        predicted_hbm = float(card.peak_hbm_bytes)
        step_ms = _roofline_step_ms(card, mesh)
        source = "costcards"
        if predicted_hbm > budget:
            raise PlanInfeasibleError(comps[0][0], comps[0][1],
                                      predicted_hbm, budget)

    return ShardingPlan(
        mesh_axes=tuple((str(a), int(mesh.shape[a]))
                        for a in mesh.axis_names),
        hbm_budget_bytes=budget,
        source=source,
        grad_sync=strat,
        grad_bucket_mb=bucket_mb,
        grad_comm_dtype=_wire_dtype(n, pinned),
        quant_rounding=rounding,
        remat=remat,
        remat_policy=policy,
        predicted_hbm_bytes=predicted_hbm,
        predicted_step_ms=step_ms,
        components=comps,
    )


# ---------------------------------------------------------------------------
# Plan recording + audit (report --explain)
# ---------------------------------------------------------------------------

def write_plan(logdir: str, plan: ShardingPlan) -> str:
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, PLAN_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(plan.to_doc(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def read_plan(logdir: str) -> Optional[ShardingPlan]:
    path = os.path.join(logdir, PLAN_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return ShardingPlan.from_doc(json.load(f))


def audit_lines(logdir: str) -> list:
    """Predicted-vs-measured audit for ``report --explain``: compares the
    recorded plan's HBM prediction against the peak the cost observatory
    measured at compile time.  Empty when the run carried no plan."""
    plan = read_plan(logdir)
    if plan is None:
        return []
    from dtf_tpu.telemetry import costobs
    measured = 0.0
    try:
        for c in costobs.read_costcards(logdir):
            if c.site == "train/step" and c.peak_hbm_bytes:
                measured = max(measured, float(c.peak_hbm_bytes))
    except FileNotFoundError:
        pass
    lines = [f"Plan audit ({logdir})", f"  {plan.summary()}"]
    lines.append(f"  {'predicted peak HBM':<28} "
                 f"{plan.predicted_hbm_bytes / 2**20:12.2f} MiB "
                 f"[{plan.source}]")
    if measured > 0.0:
        rel = abs(plan.predicted_hbm_bytes - measured) / measured
        lines.append(f"  {'measured peak HBM':<28} "
                     f"{measured / 2**20:12.2f} MiB "
                     f"(rel err {rel:.1%})")
    else:
        lines.append(f"  {'measured peak HBM':<28} "
                     f"{'(no train/step cost card)':>12}")
    return lines
