"""Gradient-sync + weight-update engine: dense, ZeRO-1, and overlapped ZeRO-1.

The reference's data plane pulled every parameter and pushed every gradient
through the PS each step (SURVEY.md §5.8).  The framework's first
replacement — ``all_reduce_mean`` over the full gradient tree followed by a
fully REPLICATED optimizer update — fixed the topology but kept two costs
the TPU does not have to pay:

* **memory**: Adam moments (2x params in f32) live on every data-parallel
  replica, so an N-way data axis spends N× the HBM a single copy needs;
* **time**: the all-reduce moves 2·(N-1)/N of the gradient bytes and then
  every device redundantly computes the SAME full update.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336, PAPERS.md) is the TPU-native fix, implemented
here as three selectable strategies (``--grad_sync``):

``dense``
    today's pmean path, kept as the default and the correctness oracle.
``zero1``
    ZeRO-1 / weight-update sharding inside the explicit ``shard_map`` step:
    gradients are flattened into fixed **buckets** (padded so every bucket
    divides the data axis), each bucket ``reduce_scatter``'d so device k
    owns the k-th shard of the *mean* gradient; the optimizer update runs
    on that shard only — against optimizer state that was **initialized
    sharded** (:func:`dtf_tpu.optim.init_partitioned`), so the moments
    cost 1/N per device — and the updated parameter shards are
    ``all_gather``'d back into full replicated params for the next forward.
``zero1_overlap``
    the same math, scheduled inside the grad-accumulation skeleton: each
    microbatch's bucket gradients are reduce-scatter'd IMMEDIATELY and the
    accumulator holds 1/N-size shards, so bucket *i*'s collective overlaps
    microbatch *i+1*'s backward (and accumulator memory drops N×).  On
    real hardware pair it with ``--xla_overlap`` (latency-hiding-scheduler
    preset, applied at backend init by :func:`dtf_tpu.cluster.bootstrap`)
    so XLA actually interleaves the comm with the compute.

A reduced-precision collective knob (``--grad_comm_dtype``,
EQuARX-motivated — arxiv 2506.17615) composes with every strategy: the
wire payload is the 1/N **mean-preserving pre-scaled** gradient, so the
summed wire value is the final mean and there is exactly ONE rounding
per hop with no post-hoc divide to round again.  ``bf16`` casts the
pre-scaled payload; ``int8`` ships the block-scaled format from
:mod:`dtf_tpu.parallel.quantize` (int8 payload + one f32 scale per
QBLOCK values, ~2x less wire than bf16, ~4x less than f32) with
``--quant_rounding nearest|stochastic`` (stochastic draws are seeded
from the step rng, so trajectories stay reproducible); ``int8_ring``
keeps the same block format but schedules the reduce-scatter as a
segmented ring that **requantizes the partial sum on every hop**
(EQuARX proper) — ``(n-1)/n`` of the int8 wire bytes, at ``n-1``
roundings per value, with the per-hop error ladder measured into
``comm/quant_error`` and the hop count into ``comm/hops``.

Sharding the update requires the update rule to commute with partitioning
the flattened parameter vector — true for ELEMENTWISE optimizers
(sgd/momentum/adam/adamw, tagged ``Optimizer.elementwise``), and for LAMB
via a shard-aware rebuild: its per-tensor trust-ratio norms are plain
sums of squares, so each shard segment-sums its contribution per tensor
and one psum over the data axis recovers the global norms (the
large-batch path for zero1 scenario cells; see ``_build_sharded_lamb``).
adafactor's factored row/col moments need whole-tensor geometry the flat
bucket layout destroys and stay rejected up front.
``clip_by_global_norm`` wrappers are re-derived with the data axis so the
clip scale psums local squared norms back into the true global norm
(bit-for-bit the same policy as dense clipping).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu import optim as optim_lib
from dtf_tpu.parallel import collectives as col
from dtf_tpu.parallel import quantize as qz
from dtf_tpu.parallel import sharding as sh

#: The canonical strategy order.  telemetry gauges encode a strategy as its
#: index here (``comm/strategy_idx``) and the report CLI maps it back — a
#: pinned test (tests/test_grad_sync.py) keeps the report's literal in sync.
STRATEGIES: Tuple[str, ...] = ("dense", "zero1", "zero1_overlap")

#: Bucket sizes are padded to a multiple of lcm(data_axis, _PAD_QUANTUM).
#: 128 keeps shards lane-aligned AND — because every power-of-two axis size
#: up to 128 divides it — makes the padded (global) bucket shapes identical
#: across those axis sizes, so an elastic 4->2 relaunch restores the SAME
#: checkpoint arrays and only the NamedSharding in the template changes.
_PAD_QUANTUM = 128

_COMM_DTYPES = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                "f32": jnp.float32, "float32": jnp.float32,
                "int8": "int8", "int8_ring": "int8_ring"}

#: Canonical wire-format order for the ``comm/wire_dtype_idx`` gauge; the
#: report CLI carries a literal mirror (pinned by tests/test_grad_sync.py).
WIRE_DTYPES: Tuple[str, ...] = ("f32", "bf16", "int8", "int8_ring")

#: The wire formats that ship the block-scaled int8 payload (the ring
#: variant re-encodes it per hop — see quantize.ring_reduce_scatter_
#: quantized); both route scatter through parallel/quantize.py and carry
#: the ``qerr`` accumulator.
QUANTIZED_WIRES: Tuple[str, ...] = ("int8", "int8_ring")


def comm_dtype_of(name: Optional[str]):
    """Resolve a ``--grad_comm_dtype`` flag value to a wire format: None
    (exact f32 wire), ``jnp.bfloat16``, or the strings ``"int8"`` /
    ``"int8_ring"`` (the block-scaled format from parallel/quantize.py —
    not a plain cast, so no jnp dtype; the ring spelling additionally
    requantizes every reduce-scatter hop).  Raises with the valid
    spellings."""
    if name is None:
        return None
    try:
        dt = _COMM_DTYPES[name]
    except KeyError:
        raise ValueError(
            f"--grad_comm_dtype must be one of {sorted(_COMM_DTYPES)}, "
            f"got {name!r}") from None
    return None if dt == jnp.float32 else dt


def wire_dtype_name(resolved) -> str:
    """Inverse of :func:`comm_dtype_of` onto :data:`WIRE_DTYPES`."""
    if resolved is None:
        return "f32"
    return resolved if resolved in QUANTIZED_WIRES else "bf16"


def wire_bytes_per_elem(resolved) -> float:
    """Wire bytes per f32 gradient element for a resolved comm dtype
    (int8 includes its per-block scale overhead)."""
    return qz.WIRE_BYTES_PER_ELEM[wire_dtype_name(resolved)]


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Static bookkeeping for flattening a pytree into padded buckets.

    Leaves are raveled in ``tree_flatten`` order and concatenated greedily
    into buckets of ~``bucket_bytes`` (f32) each; every bucket is padded to
    a multiple of ``quantum`` so ``reduce_scatter`` divides evenly and
    shard shapes stay aligned (see ``_PAD_QUANTUM``).  The padding region
    is mathematically inert: zero grads against zero params produce zero
    updates under every elementwise rule, so it stays zero forever and the
    unflatten simply trims it.
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    bucket_leaves: Tuple[Tuple[int, ...], ...]   # leaf indices per bucket
    padded: Tuple[int, ...]                      # padded elems per bucket
    n_shards: int

    @classmethod
    def build(cls, tree: Any, n_shards: int,
              bucket_bytes: float) -> "BucketLayout":
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            raise ValueError("grad_sync: empty parameter tree")
        shapes = tuple(tuple(l.shape) for l in leaves)
        dtypes = tuple(jnp.dtype(l.dtype) for l in leaves)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        buckets: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        for i, n in enumerate(sizes):
            cur.append(i)
            cur_bytes += n * 4                  # buckets carry f32
            if cur_bytes >= bucket_bytes:
                buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            buckets.append(cur)
        quantum = math.lcm(n_shards, _PAD_QUANTUM)
        padded = tuple(
            -(-sum(sizes[i] for i in b) // quantum) * quantum
            for b in buckets)
        return cls(treedef=treedef, shapes=shapes, dtypes=dtypes,
                   sizes=sizes,
                   bucket_leaves=tuple(tuple(b) for b in buckets),
                   padded=padded, n_shards=n_shards)

    @property
    def keys(self) -> Tuple[str, ...]:
        return tuple(f"b{i}" for i in range(len(self.bucket_leaves)))

    def shard_len(self, key: str) -> int:
        return self.padded[int(key[1:])] // self.n_shards

    def flatten(self, tree: Any) -> Dict[str, jax.Array]:
        """Pytree -> {bucket key: padded f32 vector}."""
        leaves = jax.tree_util.tree_flatten(tree)[0]
        out = {}
        for k, idxs, pad in zip(self.keys, self.bucket_leaves, self.padded):
            parts = [leaves[i].astype(jnp.float32).reshape(-1) for i in idxs]
            fill = pad - sum(self.sizes[i] for i in idxs)
            if fill:
                parts.append(jnp.zeros((fill,), jnp.float32))
            out[k] = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        return out

    def unflatten(self, vecs: Dict[str, jax.Array],
                  cast: bool = True) -> Any:
        """{bucket key: padded vector} -> pytree (padding trimmed).
        ``cast=False`` keeps leaves in the vectors' dtype (f32) — the
        optimizer-state conversion path, where f32 IS the native storage
        regardless of param dtype."""
        leaves: List[Any] = [None] * len(self.shapes)
        for k, idxs in zip(self.keys, self.bucket_leaves):
            v, off = vecs[k], 0
            for i in idxs:
                chunk = v[off:off + self.sizes[i]].reshape(self.shapes[i])
                leaves[i] = chunk.astype(self.dtypes[i]) if cast else chunk
                off += self.sizes[i]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class GradSyncEngine:
    """One per Trainer: owns the bucket layout, the sharded optimizer-state
    lifecycle, and the per-device sync+update code the explicit train step
    splices in.

    Construction order: ``GradSyncEngine(...)`` validates the strategy /
    optimizer / mesh pairing, then :meth:`prepare` (with the model's
    eval_shape'd params) freezes the bucket layout and the optimizer-state
    sharding specs.  Everything after that is either host-side state
    management (:meth:`init_opt_state`, the dense<->zero1 converters) or
    traced per-device code (:meth:`scatter`, :meth:`sync_and_update`).
    """

    def __init__(self, strategy: str, optimizer: optim_lib.Optimizer,
                 mesh: Mesh, *, bucket_mb: float = 4.0,
                 comm_dtype: Optional[str] = None,
                 quant_rounding: str = "nearest"):
        if strategy not in STRATEGIES:
            raise ValueError(f"--grad_sync must be one of {STRATEGIES}, "
                             f"got {strategy!r}")
        if strategy == "dense":
            raise ValueError("dense gradient sync needs no engine; the "
                             "trainer's pmean path is the dense strategy")
        axes = sh.data_axes(mesh)
        if len(axes) != 1:
            raise ValueError(
                f"--grad_sync {strategy} runs its reduce-scatter/all-gather "
                f"over a single data axis; mesh has data-like axes {axes}")
        if bucket_mb <= 0:
            raise ValueError(f"--grad_bucket_mb must be > 0, got {bucket_mb}")
        # A clip_by_global_norm wrapper computed on shards would clip each
        # shard by its LOCAL norm; unwrap it here and re-derive it
        # partition-aware (psum over the data axis) in prepare(), so zero1
        # clipping applies the same global scale as dense.
        self._clip_max_norm: Optional[float] = None
        inner = getattr(optimizer.update, "_clip_inner", None)
        if inner is not None:
            self._clip_max_norm = optimizer.update._clip_max_norm
            optimizer = inner
        # Non-elementwise updates don't commute with partitioning the
        # flattened parameter vector in general — but LAMB's only
        # cross-element structure is per-TENSOR norm pairs, and a norm is
        # a plain sum of squares: prepare() re-derives it shard-aware
        # (segment sums over the bucket layout + psum over the data axis,
        # the clip wrapper's trick — see _build_sharded_lamb).  adafactor
        # stays rejected: its factored row/col moments need whole-tensor
        # geometry the flat bucket layout destroys.
        self._lamb_args: Optional[dict] = None
        if not optimizer.elementwise:
            self._lamb_args = getattr(optimizer.update, "_lamb_args", None)
            if self._lamb_args is None:
                raise ValueError(
                    f"--grad_sync zero1 requires an optimizer whose update "
                    f"commutes with partitioning the flattened parameter "
                    f"vector: elementwise rules (sgd/momentum/adam/adamw), "
                    f"or lamb (its per-tensor trust-ratio norms are psum'd "
                    f"across shards).  adafactor's factored row/col "
                    f"moments need whole-tensor geometry the bucket layout "
                    f"destroys.  Fall back to `--grad_sync dense`: it "
                    f"supports every optimizer but REPLICATES the full "
                    f"optimizer state on all {int(mesh.shape[axes[0]])} "
                    f"devices of the '{axes[0]}' axis — N x the per-device "
                    f"state bytes zero1 would pay (DESIGN.md §4.1 "
                    f"quantifies the cost; comm/optimizer_state_bytes "
                    f"measures it)")
        self.strategy = strategy
        # The base (clip-unwrapped) optimizer; prepare() derives the
        # layout-aware self.opt from it, so prepare stays idempotent.
        self._opt_base = optimizer
        self.opt = optimizer
        self.mesh = mesh
        self.axis = axes[0]
        self.n_shards = int(mesh.shape[self.axis])
        self.bucket_bytes = bucket_mb * (1 << 20)
        self.comm_dtype = comm_dtype_of(comm_dtype)
        # "int8"/"int8_ring" are wire FORMATS (block-scaled payload +
        # scales, not a cast): the scatter routes through
        # parallel/quantize.py.  The bucket layout is wire-independent —
        # block alignment happens inside the collective — so checkpoints
        # reshard across wire dtypes without a layout conversion.
        self.quantized = self.comm_dtype in QUANTIZED_WIRES
        self.ring = self.comm_dtype == "int8_ring"
        self.quant_rounding = qz.check_rounding(quant_rounding)
        self.layout: Optional[BucketLayout] = None

    # -- host-side lifecycle ------------------------------------------------

    def prepare(self, params_shapes: Any) -> "GradSyncEngine":
        """Freeze the bucket layout + optimizer-state specs from the
        model's (eval_shape'd or real) parameter tree, and re-derive the
        partition-aware optimizer wrappers that need the layout (the
        sharded LAMB update, the psum'd clip wrapper)."""
        self.layout = BucketLayout.build(params_shapes, self.n_shards,
                                         self.bucket_bytes)
        opt = self._opt_base
        if self._lamb_args is not None:
            opt = self._build_sharded_lamb()
        if self._clip_max_norm is not None:
            opt = optim_lib.clip_by_global_norm(
                opt, self._clip_max_norm, axis=self.axis)
        self.opt = opt
        bucket_sds = {
            k: jax.ShapeDtypeStruct((pad,), jnp.float32)
            for k, pad in zip(self.layout.keys, self.layout.padded)}
        self._bucket_treedef = jax.tree_util.tree_structure(bucket_sds)
        self._params_treedef = self.layout.treedef
        state_sds = jax.eval_shape(self.opt.init, bucket_sds)
        padded_set = set(self.layout.padded)
        # Bucket-shaped state leaves (adam's m/v, momentum's m) shard over
        # the data axis; everything else (step counters) replicates.
        is_vec = lambda s: s.ndim == 1 and s.shape[0] in padded_set
        self.opt_state_spec = jax.tree_util.tree_map(
            lambda s: P(self.axis) if is_vec(s) else P(), state_sds)
        self._opt_state_shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec), self.opt_state_spec,
            is_leaf=lambda x: isinstance(x, P))
        self._vec_sharding = NamedSharding(self.mesh, P(self.axis))
        self._rep_sharding = NamedSharding(self.mesh, P())
        return self

    def _require_layout(self) -> BucketLayout:
        if self.layout is None:
            raise RuntimeError("GradSyncEngine.prepare() was never called")
        return self.layout

    def _build_sharded_lamb(self) -> optim_lib.Optimizer:
        """LAMB against the bucket layout: the trust ratio needs
        ``||p|| / ||u||`` per PARAMETER TENSOR, but each device holds a
        1/N slice of a flat bucket that concatenates many tensors.  Both
        norms are plain sums of squares, so they partition exactly like
        the global clip norm: a static segment-id array (leaf index per
        bucket element; padding gets its own segment) maps every shard
        element back to its tensor, ``segment_sum`` accumulates each
        shard's per-tensor contribution, and one ``psum`` over the data
        axis makes the sums global — every device then applies the SAME
        per-tensor trust ratios to its shard, so the sharded trajectory
        matches dense LAMB up to float reduction order.

        Adam moments stay elementwise (the inner direction), so the
        optimizer state keeps the ordinary sharded bucket shapes and the
        dense<->zero1 checkpoint reshard works unchanged."""
        layout = self._require_layout()
        args = self._lamb_args
        inner = optim_lib.adam(1.0, b1=args["b1"], b2=args["b2"],
                               eps=args["eps"])
        lr, wd, eps = args["lr"], args["weight_decay"], args["eps"]
        axis = self.axis
        n_leaves = len(layout.shapes)
        n_seg = n_leaves + 1            # +1: the padding segment
        seg_ids = {}
        for k, idxs, pad in zip(layout.keys, layout.bucket_leaves,
                                layout.padded):
            ids = np.full((pad,), n_leaves, np.int32)
            off = 0
            for i in idxs:
                ids[off:off + layout.sizes[i]] = i
                off += layout.sizes[i]
            seg_ids[k] = ids

        def shard_seg(k):
            # This device's slice of the bucket's segment ids — sliced in
            # the traced code (axis_index), same as the param shards.
            n = layout.shard_len(k)
            me = lax.axis_index(axis)
            return lax.dynamic_slice(jnp.asarray(seg_ids[k]), (me * n,),
                                     (n,))

        def update(grads, state, params):
            dirs, state = inner.update(grads, state, None)
            lr_t = lr(state["step"]) if callable(lr) else lr
            u_sh, p_sq, u_sq = {}, jnp.zeros((n_seg,), jnp.float32), \
                jnp.zeros((n_seg,), jnp.float32)
            for k in layout.keys:
                p = params[k].astype(jnp.float32)
                u = -dirs[k] + wd * p
                u_sh[k] = u
                seg = shard_seg(k)
                p_sq = p_sq + jax.ops.segment_sum(jnp.square(p), seg,
                                                  num_segments=n_seg)
                u_sq = u_sq + jax.ops.segment_sum(jnp.square(u), seg,
                                                  num_segments=n_seg)
            pn = jnp.sqrt(lax.psum(p_sq, axis))
            un = jnp.sqrt(lax.psum(u_sq, axis))
            trust = jnp.where((pn > 0) & (un > 0),
                              pn / jnp.maximum(un, eps), 1.0)
            updates = {k: -lr_t * trust[shard_seg(k)] * u_sh[k]
                       for k in layout.keys}
            return updates, state

        return optim_lib.Optimizer(inner.init, update)

    def init_opt_state(self, params: Any) -> Any:
        """Optimizer state born SHARDED: bucket the real params (weight
        decay and schedules may read them) onto the data axis, then
        materialize ``opt.init`` through the partition-aware path."""
        layout = self._require_layout()
        bucket_params = jax.jit(
            layout.flatten, out_shardings=self._vec_sharding)(params)
        return optim_lib.init_partitioned(self.opt, bucket_params,
                                          self._opt_state_shardings)

    def shard_opt_state(self, dense_state: Any) -> Any:
        """dense -> zero1 optimizer-state conversion (the restore path for
        a checkpoint saved under ``--grad_sync dense``): every top-level
        state entry congruent with the params tree is bucket-flattened
        onto the data axis; scalars and everything else pass through."""
        layout = self._require_layout()
        to_buckets = jax.jit(layout.flatten, out_shardings=self._vec_sharding)

        def conv(entry):
            if (jax.tree_util.tree_structure(entry) == self._params_treedef
                    and tuple(tuple(l.shape) for l in
                              jax.tree_util.tree_leaves(entry))
                    == layout.shapes):
                return to_buckets(entry)
            return entry
        if isinstance(dense_state, dict):
            return {k: conv(v) for k, v in dense_state.items()}
        return conv(dense_state)

    def unshard_opt_state(self, sharded_state: Any) -> Any:
        """zero1 -> dense conversion (restoring a zero1 checkpoint under
        ``--grad_sync dense``).  Leaves stay f32 (``cast=False``): f32 is
        the moments' native storage whatever the param dtype."""
        layout = self._require_layout()
        from_buckets = jax.jit(
            lambda vecs: layout.unflatten(vecs, cast=False),
            out_shardings=self._rep_sharding)

        def conv(entry):
            if (jax.tree_util.tree_structure(entry) == self._bucket_treedef
                    and tuple(l.shape[0] for l in
                              jax.tree_util.tree_leaves(entry))
                    == layout.padded):
                return from_buckets(entry)
            return entry
        if isinstance(sharded_state, dict):
            return {k: conv(v) for k, v in sharded_state.items()}
        return conv(sharded_state)

    # -- telemetry ----------------------------------------------------------

    def comm_stats(self, grad_accum: int = 1) -> dict:
        """Static per-step comm facts for the ``comm/*`` gauges:
        ``wire_bytes`` is the GRADIENT wire per device per step (the
        reduce-scatter payload in the comm format — int8 counts its
        per-block scales — times the microbatch count under
        ``zero1_overlap``, whose scatter runs once per microbatch);
        ``grad_sync_bytes`` adds the f32 param all-gather payload (kept
        exact: quantizing updated PARAMS would inject error straight into
        the weights rather than the gradients)."""
        layout = self._require_layout()
        total = sum(layout.padded)
        rs_rounds = (grad_accum if (self.strategy == "zero1_overlap"
                                    and grad_accum > 1) else 1)
        # Hops per reduce-scatter round: the all-to-all wires (f32/bf16/
        # int8) ship every chunk in one shot; the ring walks n-1 links,
        # each carrying one chunk — fewer total elements, more hops (the
        # comm/hops gauge, so the wire win is auditable per topology).
        hops = (self.n_shards - 1) if self.ring else 1
        if self.quantized:
            # Exact: per-chunk block round-up (quantize.wire_elems /
            # ring_wire_elems), int8 payload + f32 scale per QBLOCK.
            elems = (qz.ring_wire_elems if self.ring else qz.wire_elems)
            wire_total = sum(elems(p, self.n_shards)
                             for p in layout.padded)
            wire = float(wire_total
                         * qz.WIRE_BYTES_PER_ELEM["int8"] * rs_rounds)
        else:
            wire = float(total * wire_bytes_per_elem(self.comm_dtype)
                         * rs_rounds)
        return {"grad_sync_bytes": wire + float(total * 4),
                "wire_bytes": wire,
                "bucket_count": float(len(layout.padded)),
                "hops": float(hops)}

    # -- traced per-device code (inside shard_map) --------------------------

    def scatter(self, grads: Any,
                rng: Optional[jax.Array] = None) -> Dict[str, jax.Array]:
        """Bucket + mean-reduce-scatter the local gradient tree: returns
        {bucket: f32 MEAN-gradient shard}.  The 1/N pre-scaling makes the
        summed wire value the mean directly (mean-preserving: one rounding
        per value on a reduced wire, no second rounding from a
        post-divide).  Also the ``zero1_overlap`` per-microbatch stage —
        called once per microbatch inside the accumulation scan, so
        shard_map schedules bucket i's reduce-scatter concurrently with
        microbatch i+1's backward.

        On the int8 wire the dict carries an extra ``"qerr"`` entry — the
        local encode-error accumulator ((2,) vector, see
        quantize.encode_error) summed over buckets; it rides the same
        pytree so zero1_overlap's accumulation scan aggregates it across
        microbatches for free.  ``rng`` seeds stochastic rounding
        (derived from the step rng by the caller; each bucket folds in
        its index so draws never repeat across buckets)."""
        layout = self._require_layout()
        inv = 1.0 / self.n_shards
        out: Dict[str, jax.Array] = {}
        if self.quantized:
            qerr = jnp.zeros((2,), jnp.float32)
            if self.quant_rounding == "stochastic" and rng is None:
                raise ValueError("stochastic quant_rounding needs the step "
                                 "rng threaded into scatter()")
            rs = (qz.ring_reduce_scatter_quantized if self.ring
                  else qz.reduce_scatter_quantized)
            for i, (k, v) in enumerate(layout.flatten(grads).items()):
                bucket_rng = (jax.random.fold_in(rng, i)
                              if rng is not None else None)
                out[k], e = rs(
                    v * inv, self.axis, rounding=self.quant_rounding,
                    rng=bucket_rng, return_error=True)
                qerr = qerr + e
            out["qerr"] = qerr
            return out
        for k, v in layout.flatten(grads).items():
            w = v * inv
            if self.comm_dtype is not None:
                w = w.astype(self.comm_dtype)
            out[k] = col.reduce_scatter(w, self.axis).astype(jnp.float32)
        return out

    def sync_and_update(self, grads: Any, opt_state: Any, params: Any, *,
                        prescattered: bool = False,
                        rng: Optional[jax.Array] = None
                        ) -> Tuple[Any, Any, Optional[jax.Array]]:
        """The sharded weight update: (local grads | mean shards) + sharded
        opt state + full replicated params -> (full updated params, new
        sharded opt state, quant-error scalar or None).  Per-device code;
        call inside ``shard_map`` with ``opt_state`` mapped over the data
        axis (:attr:`opt_state_spec`) and everything else replicated.
        The error scalar (int8 wire only) is psum'd over the data axis so
        every replica reports the same global relative-RMS value."""
        layout = self._require_layout()
        g_sh = dict(grads) if prescattered else self.scatter(grads, rng)
        qerr = g_sh.pop("qerr", None)
        if qerr is not None:
            qerr = qz.error_ratio(lax.psum(qerr, self.axis))
        me = lax.axis_index(self.axis)
        p_sh = {}
        for k, v in layout.flatten(params).items():
            n = layout.shard_len(k)
            p_sh[k] = lax.dynamic_slice(v, (me * n,), (n,))
        updates, new_opt = self.opt.update(g_sh, opt_state, p_sh)
        new_vecs = {k: col.all_gather(p_sh[k] + updates[k], self.axis)
                    for k in layout.keys}
        return layout.unflatten(new_vecs), new_opt, qerr


def opt_state_bytes_per_device(opt_state: Any) -> float:
    """Per-device bytes of an optimizer-state pytree, honoring shardings:
    a replicated leaf costs its full nbytes on every device, a data-sharded
    leaf 1/N — the ``comm/optimizer_state_bytes`` gauge, so the zero1
    memory claim is measured off the real arrays, not the design doc."""
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += shards[0].data.nbytes
        else:
            total += np.asarray(leaf).nbytes
    return total
