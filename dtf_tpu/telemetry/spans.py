"""Host-side structured tracer: nested spans to JSON-lines, exportable to
Chrome-trace/Perfetto.

The XLA profiler (utils/profiling.py) answers "which device op is slow"
inside a narrow trace window; it says nothing about the host-side life of
a run — where the wall-clock went between checkpoint saves, rollback
restores, supervisor restarts, data fetches and eval passes.  This tracer
is that other half: every instrumented phase appends one JSON object per
completed span to ``<logdir>/spans.p<k>.jsonl`` (k = process index), and
:func:`export_chrome_trace` rewraps any set of those files as a Chrome
``traceEvents`` JSON so Perfetto/chrome://tracing overlays them — on the
same viewer the XLA profiler window loads into.

Span records are already Chrome-trace "X" (complete) events::

    {"name": "checkpoint/save", "ph": "X", "ts": <epoch µs>,
     "dur": <µs>, "pid": <process>, "tid": <thread>, "args": {...}}

``ts`` is epoch wall-clock (not a monotonic origin) so spans from
different hosts land on one shared time axis; ``dur`` is measured with
the monotonic clock so a clock step mid-span cannot produce negative
durations.  Instants (``ph: "i"``) mark point events — a chaos fault
firing, a health abort.

Thread-safe; nesting is tracked per-thread (``depth`` in args) purely
from the with-statement structure, no global state to corrupt.  A
disabled tracer (no path) costs one attribute check per span.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from dtf_tpu.telemetry.names import validate

_FLUSH_EVERY = 64          # buffered records between file flushes


class Tracer:
    """Span recorder bound to one JSONL file (or disabled when path=None)."""

    def __init__(self, path: Optional[str] = None, process: int = 0):
        self.path = path
        self.process = process
        self._f = None
        self._lock = threading.Lock()
        self._pending = 0
        self._local = threading.local()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, "a", buffering=1 << 16)

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def _depth(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _emit(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            self._pending += 1
            if self._pending >= _FLUSH_EVERY:
                self._f.flush()
                self._pending = 0

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        """Record ``name`` over the with-block.  Nesting is structural:
        a span opened inside another (same thread) records its depth and
        parent, so the export shows the call tree without any id
        plumbing."""
        if self._f is None:
            yield
            return
        validate(name)
        stack = self._depth()
        parent = stack[-1] if stack else None
        stack.append(name)
        wall0 = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur_us = (time.perf_counter() - t0) * 1e6
            stack.pop()
            args = dict(attrs)
            args["depth"] = len(stack)
            if parent:
                args["parent"] = parent
            self._emit({"name": name, "ph": "X",
                        "ts": wall0 * 1e6, "dur": dur_us,
                        "pid": self.process,
                        "tid": threading.get_ident() & 0xFFFF,
                        "args": args})

    def instant(self, name: str, **attrs: Any) -> None:
        """Point event (chaos fault fired, peer died, ...); flushed
        eagerly — instants mark exactly the moments a post-mortem needs,
        and the process may be about to die."""
        if self._f is None:
            return
        validate(name)
        self._emit({"name": name, "ph": "i", "ts": time.time() * 1e6,
                    "s": "p", "pid": self.process,
                    "tid": threading.get_ident() & 0xFFFF,
                    "args": dict(attrs)})
        self.flush()

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._pending = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- process-wide tracer ----------------------------------------------------

_NULL = Tracer(None)
_TRACER = _NULL


def configure(logdir: Optional[str], process: int = 0) -> Tracer:
    """Install the process-wide tracer writing to
    ``<logdir>/spans.p<process>.jsonl`` (telemetry CONVENTION: per-process
    files so multi-host runs on a shared logdir never interleave writes).
    ``logdir=None`` uninstalls (back to the no-op tracer)."""
    global _TRACER
    if _TRACER is not _NULL:
        _TRACER.close()
    _TRACER = (Tracer(os.path.join(logdir, f"spans.p{process}.jsonl"),
                      process=process) if logdir else _NULL)
    return _TRACER


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, **attrs: Any):
    """Module-level convenience: a span on the process-wide tracer."""
    return _TRACER.span(name, **attrs)


def instant(name: str, **attrs: Any) -> None:
    _TRACER.instant(name, **attrs)


# -- readers / export -------------------------------------------------------

def read_spans(path: str) -> List[dict]:
    """Parse one spans JSONL file; a torn final line (process killed
    mid-write) is dropped, like the TB reader's torn-tail rule."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue               # torn tail / partial write
    return out


def find_span_files(logdir: str) -> List[str]:
    import glob
    return sorted(glob.glob(os.path.join(logdir, "spans.p*.jsonl")))


def export_chrome_trace(logdir: str, out_path: str) -> int:
    """Merge every ``spans.p*.jsonl`` under ``logdir`` into one Chrome-
    trace JSON (load in Perfetto / chrome://tracing; overlays with the
    XLA profiler's trace since both use epoch-µs timestamps).  Returns
    the number of events written."""
    events: List[dict] = []
    for path in find_span_files(logdir):
        events.extend(read_spans(path))
    for k in {e.get("pid", 0) for e in events}:
        events.append({"ph": "M", "pid": k, "name": "process_name",
                       "args": {"name": f"dtf_tpu host p{k}"}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)
