"""Host-side structured tracer: nested spans to JSON-lines, exportable to
Chrome-trace/Perfetto.

The XLA profiler (utils/profiling.py) answers "which device op is slow"
inside a narrow trace window; it says nothing about the host-side life of
a run — where the wall-clock went between checkpoint saves, rollback
restores, supervisor restarts, data fetches and eval passes.  This tracer
is that other half: every instrumented phase appends one JSON object per
completed span to ``<logdir>/spans.p<k>.jsonl`` (k = process index), and
:func:`export_chrome_trace` rewraps any set of those files as a Chrome
``traceEvents`` JSON so Perfetto/chrome://tracing overlays them — on the
same viewer the XLA profiler window loads into.

Span records are already Chrome-trace "X" (complete) events::

    {"name": "checkpoint/save", "ph": "X", "ts": <epoch µs>,
     "dur": <µs>, "pid": <process>, "tid": <thread>, "args": {...}}

``ts`` is epoch wall-clock (not a monotonic origin) so spans from
different hosts land on one shared time axis; ``dur`` is measured with
the monotonic clock so a clock step mid-span cannot produce negative
durations.  Instants (``ph: "i"``) mark point events — a chaos fault
firing, a health abort.

Thread-safe; nesting is tracked per-thread (``depth`` in args) purely
from the with-statement structure, no global state to corrupt.  A
disabled tracer (no path) costs one attribute check per span.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from dtf_tpu.telemetry.names import validate

_FLUSH_EVERY = 64          # buffered records between file flushes

#: Size-based rotation defaults: the active ``spans.p<k>.jsonl`` rolls
#: to ``spans.p<k>.NNN.jsonl`` once it crosses ROTATE_MAX_BYTES, and only
#: the newest ROTATE_KEEP rotated files survive — a week-long serving run
#: cannot fill the disk with span history, and the flight recorder
#: (``/tracez``) covers the live tail anyway.
ROTATE_MAX_BYTES = 64 << 20
ROTATE_KEEP = 8


def _rotated_path(path: str, seq: int) -> str:
    """``spans.p0.jsonl`` + seq 3 -> ``spans.p0.003.jsonl``."""
    base, ext = os.path.splitext(path)
    return f"{base}.{seq:03d}{ext}"


def _rotated_seqs(path: str) -> List[int]:
    """Existing rotation sequence numbers for an active span path."""
    import glob as _glob
    base, ext = os.path.splitext(path)
    out = []
    for p in _glob.glob(f"{base}.*{ext}"):
        mid = p[len(base) + 1:-len(ext)] if ext else p[len(base) + 1:]
        if mid.isdigit():
            out.append(int(mid))
    return sorted(out)


class Tracer:
    """Span recorder bound to one JSONL file (or disabled when path=None).

    ``max_bytes``/``keep`` arm size-based rotation (None = unbounded, the
    scratch-Tracer default; :func:`configure` arms the module defaults
    for the process-wide tracer so long runs are bounded by default)."""

    def __init__(self, path: Optional[str] = None, process: int = 0,
                 max_bytes: Optional[int] = None, keep: int = ROTATE_KEEP):
        self.path = path
        self.process = process
        self.max_bytes = max_bytes
        self.keep = keep
        self._f = None
        self._lock = threading.Lock()
        self._pending = 0
        self._local = threading.local()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, "a", buffering=1 << 16)
            seqs = _rotated_seqs(path)
            self._rot_seq = (seqs[-1] + 1) if seqs else 0
            # size tracked incrementally: f.tell() on a buffered text
            # file FLUSHES first, which would defeat _FLUSH_EVERY
            # batching on every emit (records are ASCII JSON, so char
            # count == byte count)
            try:
                self._size = os.path.getsize(path)
            except OSError:
                self._size = 0

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def _depth(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _emit(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._size += len(line)
            self._pending += 1
            if self._pending >= _FLUSH_EVERY:
                self._f.flush()
                self._pending = 0
            if self.max_bytes and self._size >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Roll the active file to ``spans.p<k>.NNN.jsonl`` and prune
        rotations older than keep-last-M.  Caller holds the lock."""
        self._f.flush()
        self._f.close()
        os.replace(self.path, _rotated_path(self.path, self._rot_seq))
        self._rot_seq += 1
        for seq in _rotated_seqs(self.path):
            if seq <= self._rot_seq - 1 - self.keep:
                try:
                    os.remove(_rotated_path(self.path, seq))
                except OSError:
                    pass
        self._f = open(self.path, "a", buffering=1 << 16)
        self._pending = 0
        self._size = 0

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        """Record ``name`` over the with-block.  Nesting is structural:
        a span opened inside another (same thread) records its depth and
        parent, so the export shows the call tree without any id
        plumbing."""
        if self._f is None:
            yield
            return
        validate(name)
        stack = self._depth()
        parent = stack[-1] if stack else None
        stack.append(name)
        wall0 = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur_us = (time.perf_counter() - t0) * 1e6
            stack.pop()
            args = dict(attrs)
            args["depth"] = len(stack)
            if parent:
                args["parent"] = parent
            self._emit({"name": name, "ph": "X",
                        "ts": wall0 * 1e6, "dur": dur_us,
                        "pid": self.process,
                        "tid": threading.get_ident() & 0xFFFF,
                        "args": args})

    def emit_instant(self, name: str, args: Optional[Dict[str, Any]] = None,
                     *, ts_us: Optional[float] = None,
                     tid: Optional[int] = None, eager: bool = False) -> None:
        """Raw instant record with explicit args/timestamp/lane — the
        request tracer's high-rate path (NOT eagerly flushed by default,
        unlike :meth:`instant`: request lifecycle events are frequent and
        the flight-recorder ring covers the live tail)."""
        if self._f is None:
            return
        validate(name)
        self._emit({"name": name, "ph": "i",
                    "ts": time.time() * 1e6 if ts_us is None else ts_us,
                    "s": "p", "pid": self.process,
                    "tid": (threading.get_ident() & 0xFFFF
                            if tid is None else tid),
                    "args": dict(args or {})})
        if eager:
            self.flush()

    def emit_complete(self, name: str, ts_us: float, dur_us: float,
                      args: Optional[Dict[str, Any]] = None,
                      tid: Optional[int] = None) -> None:
        """Raw Chrome-trace "X" (complete) record with explicit window —
        for spans whose start was observed earlier than the emit (a
        request's lifetime, closed at its terminal event)."""
        if self._f is None:
            return
        validate(name)
        self._emit({"name": name, "ph": "X", "ts": ts_us,
                    "dur": max(dur_us, 0.0), "pid": self.process,
                    "tid": (threading.get_ident() & 0xFFFF
                            if tid is None else tid),
                    "args": dict(args or {})})

    def instant(self, name: str, **attrs: Any) -> None:
        """Point event (chaos fault fired, peer died, ...); flushed
        eagerly — instants mark exactly the moments a post-mortem needs,
        and the process may be about to die.  Instants also fan out to
        any registered taps (telemetry/diagnose.py's live event log)
        even when the tracer itself is disabled — live root-cause
        correlation must not depend on a logdir being armed."""
        validate(name)
        ts_us = time.time() * 1e6
        args = dict(attrs)
        for tap in _INSTANT_TAPS:
            try:
                tap(name, ts_us, args, self.process)
            except Exception:
                pass               # a broken tap must never break the emit
        if self._f is None:
            return
        self._emit({"name": name, "ph": "i", "ts": ts_us,
                    "s": "p", "pid": self.process,
                    "tid": threading.get_ident() & 0xFFFF,
                    "args": args})
        self.flush()

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._pending = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- instant taps -----------------------------------------------------------
# Callables invoked on EVERY Tracer.instant emit: fn(name, ts_us, args,
# process).  The incident plane (telemetry/diagnose.py) taps here so the
# live correlator sees exactly the records the post-hoc reader parses
# back from disk — one evidence stream, two consumers.

_INSTANT_TAPS: List[Any] = []


def add_instant_tap(fn) -> None:
    if fn not in _INSTANT_TAPS:
        _INSTANT_TAPS.append(fn)


def remove_instant_tap(fn) -> None:
    try:
        _INSTANT_TAPS.remove(fn)
    except ValueError:
        pass


# -- process-wide tracer ----------------------------------------------------

_NULL = Tracer(None)
_TRACER = _NULL


def configure(logdir: Optional[str], process: int = 0,
              max_bytes: Optional[int] = None,
              keep: Optional[int] = None) -> Tracer:
    """Install the process-wide tracer writing to
    ``<logdir>/spans.p<process>.jsonl`` (telemetry CONVENTION: per-process
    files so multi-host runs on a shared logdir never interleave writes).
    Rotation is armed by default (module defaults; override per call) so
    a long run's span history is bounded on disk.
    ``logdir=None`` uninstalls (back to the no-op tracer)."""
    global _TRACER
    if _TRACER is not _NULL:
        _TRACER.close()
    _TRACER = (Tracer(os.path.join(logdir, f"spans.p{process}.jsonl"),
                      process=process,
                      max_bytes=(ROTATE_MAX_BYTES if max_bytes is None
                                 else max_bytes),
                      keep=ROTATE_KEEP if keep is None else keep)
               if logdir else _NULL)
    return _TRACER


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, **attrs: Any):
    """Module-level convenience: a span on the process-wide tracer."""
    return _TRACER.span(name, **attrs)


def instant(name: str, **attrs: Any) -> None:
    _TRACER.instant(name, **attrs)


# -- readers / export -------------------------------------------------------

def read_spans(path: str) -> List[dict]:
    """Parse one spans JSONL file; a torn final line (process killed
    mid-write) is dropped, like the TB reader's torn-tail rule."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue               # torn tail / partial write
    return out


def find_span_files(logdir: str) -> List[str]:
    """Every span file under ``logdir`` — rotated generations
    (``spans.p<k>.NNN.jsonl``) AND the active tail — ordered oldest-first
    per process so readers see one chronological stream."""
    import glob
    import re
    pat = re.compile(r"spans\.p(\d+)(?:\.(\d+))?\.jsonl$")

    def key(path: str):
        m = pat.search(os.path.basename(path))
        if not m:
            return (1 << 30, 1 << 30, path)
        proc = int(m.group(1))
        # rotated generations sort before the active (unnumbered) file
        seq = int(m.group(2)) if m.group(2) is not None else 1 << 30
        return (proc, seq, path)

    return sorted(glob.glob(os.path.join(logdir, "spans.p*.jsonl")),
                  key=key)


def export_chrome_trace(logdir: str, out_path: str,
                        offsets_s: Optional[Dict[int, float]] = None
                        ) -> int:
    """Merge every ``spans.p*.jsonl`` under ``logdir`` into one Chrome-
    trace JSON (load in Perfetto / chrome://tracing; overlays with the
    XLA profiler's trace since both use epoch-µs timestamps).  Returns
    the number of events written.

    ``offsets_s`` (the fleet plane's estimated per-host clock offsets,
    :func:`dtf_tpu.telemetry.fleet.estimate_offsets`) re-bases each
    host's timestamps onto the reference host's clock before export, so
    a multi-host run reads as ONE timeline — each host stays its own
    named, sort-ordered Perfetto track-group."""
    offsets_s = offsets_s or {}
    events: List[dict] = []
    for path in find_span_files(logdir):
        events.extend(read_spans(path))
    for e in events:
        off = offsets_s.get(e.get("pid", 0))
        if off and "ts" in e:
            e["ts"] = e["ts"] - off * 1e6
    for k in sorted({e.get("pid", 0) for e in events}):
        off = offsets_s.get(k, 0.0)
        label = (f"dtf_tpu host p{k}" if not off
                 else f"dtf_tpu host p{k} (clock {off * 1e3:+.3f} ms)")
        events.append({"ph": "M", "pid": k, "name": "process_name",
                       "args": {"name": label}})
        events.append({"ph": "M", "pid": k, "name": "process_sort_index",
                       "args": {"sort_index": k}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)
