"""Goodput accounting: where did the wall-clock go?

Pod-scale TPU practice (MLPerf pods, pjit/TPUv4 LM runs) reports not just
step time but **goodput** — the fraction of wall-clock spent on
productive training versus everything self-healing costs: rollback
restores, supervisor restart downtime, chaos/straggler stalls,
checkpoint saves, compile time.  The resilience layer made those costs
survivable (DESIGN.md §5); this module makes them *visible*.

One process-wide :class:`GoodputTracker` that the trainer AND the
supervisor both feed:

* the trainer attributes every host-side phase of its loop
  (``measure("productive")`` around step dispatch + sync reads,
  ``"data"`` around fetch/put, ``"checkpoint"``, ``"rollback"``,
  ``"eval"``, ``"stall"`` around injected/chaos sleeps, first-step
  ``"compile"``);
* the supervisor marks the down window (:meth:`mark_down` at crash /
  preemption, closed by :meth:`mark_up` when the next attempt's trainer
  starts building) as ``"restart"``;
* a relaunched PROCESS (scheduler restart, elastic round) resumes the
  books via :meth:`load_previous`: the buckets come off the previous
  ``telemetry.json`` and the gap since its last write is accounted as
  restart downtime — so productive + overhead sums to wall-clock across
  the whole supervised run, not just one attempt.

Every bucket mirrors into the registry as ``goodput/<category>_s`` so
``telemetry.json`` and the report CLI need no side channel.  MFU /
tokens-per-sec helpers live here too: one formula, used by the trainer's
sync points and the benchmark driver alike.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

from dtf_tpu.telemetry import registry as _registry

# Accounting categories.  "productive" is time the step pipeline is doing
# model work (dispatch + the sync-point readback that blocks on it);
# everything else is overhead a perfect run would not pay.  "init" covers
# trainer construction (model init, sharding setup); "other" is the
# explicit remainder so the report can show what escaped attribution.
CATEGORIES = ("productive", "compile", "data", "checkpoint", "rollback",
              "restart", "stall", "eval", "init", "other")


class GoodputTracker:
    """Thread-safe (one reentrant lock): the trainer/engine thread feeds
    the buckets while the live ``/statz`` endpoint snapshots them from
    an admin handler thread — a scrape must see one consistent cut of
    the books, never a mid-update mix."""

    def __init__(self):
        self._lock = threading.RLock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.buckets: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
            # Lazy clock: wall-time starts at the FIRST accounted event
            # (the trainer's mark_up), not at module import — the books
            # describe the training run, not the Python process around
            # it.
            self._t0: Optional[float] = None
            self._base_wall = 0.0      # carried over from a previous process
            self._down_since: Optional[float] = None

    def _start_clock(self) -> None:
        if self._t0 is None:
            self._t0 = time.perf_counter()

    # -- feeding ------------------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        if category not in self.buckets:
            raise ValueError(f"unknown goodput category {category!r}; "
                             f"one of {CATEGORIES}")
        with self._lock:
            self._start_clock()
            self.buckets[category] += max(float(seconds), 0.0)

    @contextlib.contextmanager
    def measure(self, category: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(category, time.perf_counter() - t0)

    def mark_down(self) -> None:
        """Supervisor: an attempt just crashed / was preempted; downtime
        starts now.  Idempotent (the first mark wins — the failure point,
        not the last log line)."""
        with self._lock:
            self._start_clock()
            if self._down_since is None:
                self._down_since = time.perf_counter()

    def mark_up(self) -> None:
        """Trainer construction: if a down window is open, close it into
        the restart bucket."""
        with self._lock:
            self._start_clock()
            if self._down_since is not None:
                self.add("restart", time.perf_counter() - self._down_since)
                self._down_since = None

    def load_previous(self, telemetry_json: dict) -> None:
        """Resume the books from a previous process's ``telemetry.json``
        (scheduler-driven --resume, elastic relaunch): restore its goodput
        buckets and account the dead time since its last write as restart
        downtime."""
        prev = telemetry_json.get("goodput", {})
        with self._lock:
            for c in CATEGORIES:
                self.buckets[c] += float(prev.get(f"{c}_s", 0.0))
            self._base_wall = float(prev.get("wall_s", 0.0))
            written = telemetry_json.get("written_unix")
            if written is not None:
                down = time.time() - float(written)
                if 0 < down < 7 * 24 * 3600:  # a stale file isn't downtime
                    self.add("restart", down)
                    self._base_wall += down

    # -- reading ------------------------------------------------------------

    def wall_s(self) -> float:
        with self._lock:
            if self._t0 is None:
                return self._base_wall
            return self._base_wall + (time.perf_counter() - self._t0)

    def accounted_s(self) -> float:
        with self._lock:
            return sum(self.buckets.values())

    def goodput_fraction(self) -> float:
        """Productive share of wall-clock (0 when nothing ran)."""
        with self._lock:
            wall = self.wall_s()
            return self.buckets["productive"] / wall if wall > 0 else 0.0

    def snapshot(self) -> dict:
        """The ``goodput`` section of telemetry.json; also mirrors every
        bucket into the registry (``goodput/<cat>_s``) so the metric
        stream and the JSON cannot drift.  The lock is held across the
        whole read so a concurrent ``/statz`` scrape sees buckets,
        accounted_s and productive_fraction from ONE instant."""
        with self._lock:
            out = {f"{c}_s": round(self.buckets[c], 6) for c in CATEGORIES}
            out["wall_s"] = round(self.wall_s(), 6)
            out["accounted_s"] = round(self.accounted_s(), 6)
            out["productive_fraction"] = round(self.goodput_fraction(), 6)
            buckets = dict(self.buckets)
        for c in CATEGORIES:
            _registry.gauge(f"goodput/{c}_s").set(buckets[c])
        _registry.gauge("goodput/productive_fraction").set(
            out["productive_fraction"])
        return out


_TRACKER = GoodputTracker()


def get_tracker() -> GoodputTracker:
    return _TRACKER


# -- MFU / throughput -------------------------------------------------------

def tokens_per_example(model) -> float:
    """Tokens one example contributes to throughput: the model's sequence
    length when it has one (``cfg.seq_len``, or ``cfg.max_len`` — the
    GPT spelling), else 1 (classifiers)."""
    cfg = getattr(model, "cfg", None)
    return float(getattr(cfg, "seq_len", None)
                 or getattr(cfg, "max_len", None) or 1)


def peak_flops_for_model(model, device):
    """``(peak_flops_per_chip, dtype_name)`` for the model's compute dtype
    — THE MFU denominator, shared by the trainer's sync points and the
    benchmark driver.  Peak is None when the chip is unknown (CPU)."""
    import numpy as np
    from dtf_tpu.bench.matmul import peak_flops_per_chip
    dtype = np.dtype(getattr(getattr(model, "cfg", None), "dtype", None)
                     or np.float32).name
    return peak_flops_per_chip(device, dtype), dtype


def train_flops_per_example(model, params) -> float:
    """Model FLOPs for ONE training example — the numerator of MFU.

    Prefers the model's own accounting (``train_flops_per_example``, e.g.
    BERT's K-position MLM head); falls back to the standard ``6 · P · T``
    (fwd 2PT + bwd 4PT) using the model's tokens-per-example when it has
    a sequence dimension, else ``6 · P`` (one "token" per example —
    mlp/resnet classifiers, where the dense matmuls dominate exactly as
    in the LM case).  Remat recompute is correctly NOT counted.
    """
    if hasattr(model, "train_flops_per_example"):
        return float(model.train_flops_per_example(params))
    from dtf_tpu.nn.core import count_params
    return 6.0 * float(count_params(params)) * tokens_per_example(model)


def record_throughput(*, examples_per_s: float, tokens_per_example: float,
                      step_ms: float, model_flops_per_example: float,
                      n_chips: int, peak_flops_per_chip: Optional[float],
                      ) -> dict:
    """THE MFU/throughput formula — trainer sync points and the benchmark
    driver both call this so there is exactly one copy.  Sets the
    ``throughput/*`` and ``mfu/*`` gauges and returns them as a dict."""
    tokens_per_s = examples_per_s * tokens_per_example
    tflops_chip = (model_flops_per_example * examples_per_s
                   / max(n_chips, 1) / 1e12)
    out = {"examples_per_s": examples_per_s, "tokens_per_s": tokens_per_s,
           "step_ms": step_ms, "model_tflops_per_chip": tflops_chip,
           "mfu_pct": None}
    _registry.gauge("throughput/examples_per_s").set(examples_per_s)
    _registry.gauge("throughput/tokens_per_s").set(tokens_per_s)
    _registry.gauge("throughput/step_ms").set(step_ms)
    if model_flops_per_example > 0:
        # No FLOPs model -> no MFU claim (a zero gauge would read as
        # "measured zero", which is worse than absent).
        _registry.gauge("mfu/model_tflops_per_chip").set(tflops_chip)
        if peak_flops_per_chip:
            out["mfu_pct"] = (tflops_chip * 1e12
                              / peak_flops_per_chip * 100.0)
            _registry.gauge("mfu/pct_peak").set(out["mfu_pct"])
    return out
